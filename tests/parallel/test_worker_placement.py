"""Async worker → device placement.

The TPU-native analog of the reference's executor-owned compute
(``/root/reference/elephas/worker.py:52-131``): each async worker is
pinned to one local chip, so N workers on an M-chip host drive all M
chips concurrently instead of contending for device 0. Verified here on
the virtual 8-device CPU mesh: worker *i* must create its training
arrays on ``jax.local_devices()[i % n]``.
"""
from itertools import count

import jax
import numpy as np
import pytest

from elephas_tpu.models import SGD
from elephas_tpu.tpu_model import TPUModel
from elephas_tpu.utils.dataset_utils import to_dataset
from elephas_tpu.worker import AsyncWorker


def _port(_count=count(6100)):
    return next(_count)


def _param_devices(model):
    devs = set()
    for layer_params in model.params.values():
        for value in layer_params.values():
            devs |= getattr(value, "devices", lambda: set())()
    return devs


def test_async_worker_trains_on_assigned_device(classification_model):
    """A worker constructed with device=d commits its params to d."""
    classification_model.compile(SGD(learning_rate=0.1),
                                 "categorical_crossentropy", ["acc"], seed=0)
    target = jax.local_devices()[3]
    rng = np.random.default_rng(0)
    x = rng.normal(size=(96, 784)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=96)]

    port = _port()
    tpu_model = TPUModel(classification_model, frequency="epoch",
                         mode="asynchronous", parameter_server_mode="http",
                         port=port)
    tpu_model.start_server()
    try:
        worker = AsyncWorker(
            classification_model.to_json(),
            classification_model.get_weights(), tpu_model.client,
            {"epochs": 1, "batch_size": 32, "verbose": 0}, "epoch",
            tpu_model.master_optimizer, tpu_model.master_loss,
            tpu_model.master_metrics, port=port, device=target)
        worker.train(x, y)
        assert _param_devices(worker.model) == {target}
    finally:
        worker.client.close()
        tpu_model.stop_server()


@pytest.mark.parametrize("num_workers", [4, 8])
def test_fit_assigns_workers_round_robin(num_workers, mnist_data,
                                         classification_model, monkeypatch):
    """TPUModel._fit hands worker i device local_devices[i % n], and each
    worker's training state really lands there."""
    import elephas_tpu.tpu_model as tm

    x_train, y_train, _, _ = mnist_data
    x_train, y_train = x_train[:512], y_train[:512]
    classification_model.compile(SGD(learning_rate=0.1),
                                 "categorical_crossentropy", ["acc"], seed=0)

    assigned = []
    landed = []
    real_worker = tm.AsyncWorker

    class RecordingWorker(real_worker):
        def __init__(self, *args, **kwargs):
            super().__init__(*args, **kwargs)
            assigned.append(self.device)

        def train(self, x, y):
            out = super().train(x, y)
            if self.model is not None:
                landed.append((self.device, _param_devices(self.model)))
            return out

    monkeypatch.setattr(tm, "AsyncWorker", RecordingWorker)

    tpu_model = TPUModel(classification_model, frequency="epoch",
                         num_workers=num_workers, mode="asynchronous",
                         parameter_server_mode="socket", port=_port())
    tpu_model.fit(to_dataset(x_train, y_train), epochs=1, batch_size=32,
                  verbose=0)

    local = jax.local_devices()
    expected = [local[i % len(local)] for i in range(num_workers)]
    assert sorted(assigned, key=str) == sorted(expected, key=str)
    assert landed, "no worker trained"
    for device, devices_seen in landed:
        assert devices_seen == {device}
