"""Multi-host helpers (single-process paths; the multi-process wiring is
exercised by the driver's dryrun and real pods)."""
import numpy as np

from elephas_tpu.parallel.multihost import (global_batch_from_host_data,
                                            global_data_mesh,
                                            host_local_slice, is_coordinator)


def test_is_coordinator_single_process():
    assert is_coordinator()


def test_host_local_slice_covers_everything():
    lo, hi = host_local_slice(100)
    assert (lo, hi) == (0, 100)


def test_global_data_mesh_spans_devices():
    import jax

    mesh = global_data_mesh()
    assert int(np.prod(mesh.devices.shape)) == len(jax.devices())


def test_global_batch_from_host_data():
    mesh = global_data_mesh()
    local = np.arange(16, dtype=np.float32).reshape(16, 1)
    arr = global_batch_from_host_data(mesh, local)
    np.testing.assert_array_equal(np.asarray(arr), local)


def test_barrier_watchdog_timeout_and_poison(monkeypatch):
    """A barrier whose peers never arrive times out with a clear error,
    and every later barrier in the process refuses to run (the
    abandoned rendezvous could pair with it and corrupt the protocol)."""
    import threading

    import pytest
    from jax.experimental import multihost_utils

    import elephas_tpu.parallel.multihost as mh

    release = threading.Event()  # lets the parked watchdog thread exit
    monkeypatch.setattr(mh.jax, "process_count", lambda: 2)
    monkeypatch.setattr(multihost_utils, "sync_global_devices",
                        lambda name: release.wait())
    monkeypatch.setattr(mh, "_POISONED_BARRIER", None)
    try:
        with pytest.raises(RuntimeError, match="timed out"):
            mh.barrier("test_rendezvous", timeout_s=0.2)
        # poisoned: even a barrier that WOULD succeed now refuses
        monkeypatch.setattr(multihost_utils, "sync_global_devices",
                            lambda name: None)
        with pytest.raises(RuntimeError, match="undefined"):
            mh.barrier("next_barrier", timeout_s=5.0)
    finally:
        release.set()  # don't leak a blocked thread into the suite
        mh._POISONED_BARRIER = None  # never leak poison into other tests


def test_barrier_propagates_sync_errors(monkeypatch):
    """An error raised inside the rendezvous (peer died, Gloo reset)
    surfaces to the caller — and does NOT poison later barriers (the
    sync itself completed; no thread was abandoned)."""
    import pytest
    from jax.experimental import multihost_utils

    import elephas_tpu.parallel.multihost as mh

    monkeypatch.setattr(mh.jax, "process_count", lambda: 2)
    monkeypatch.setattr(mh, "_POISONED_BARRIER", None)

    def boom(name):
        raise ConnectionError("peer closed")

    monkeypatch.setattr(multihost_utils, "sync_global_devices", boom)
    with pytest.raises(ConnectionError, match="peer closed"):
        mh.barrier("erroring", timeout_s=5.0)
    monkeypatch.setattr(multihost_utils, "sync_global_devices",
                        lambda name: None)
    mh.barrier("after_error", timeout_s=5.0)  # not poisoned
