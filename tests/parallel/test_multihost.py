"""Multi-host helpers (single-process paths; the multi-process wiring is
exercised by the driver's dryrun and real pods)."""
import numpy as np

from elephas_tpu.parallel.multihost import (global_batch_from_host_data,
                                            global_data_mesh,
                                            host_local_slice, is_coordinator)


def test_is_coordinator_single_process():
    assert is_coordinator()


def test_host_local_slice_covers_everything():
    lo, hi = host_local_slice(100)
    assert (lo, hi) == (0, 100)


def test_global_data_mesh_spans_devices():
    import jax

    mesh = global_data_mesh()
    assert int(np.prod(mesh.devices.shape)) == len(jax.devices())


def test_global_batch_from_host_data():
    mesh = global_data_mesh()
    local = np.arange(16, dtype=np.float32).reshape(16, 1)
    arr = global_batch_from_host_data(mesh, local)
    np.testing.assert_array_equal(np.asarray(arr), local)
