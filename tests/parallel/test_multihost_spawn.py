"""Real multi-process (DCN-style) execution tests.

These launch 2 JAX-distributed subprocesses on CPU (local coordinator,
Gloo collectives) running the same ``TPUModel.fit`` program — work
actually crosses process boundaries, the analog of the reference shipping
closures to remote Spark executors (``elephas/spark_model.py:214``).

Oracle: a single-process run with the same total device count produces
the same weights (sync modes are deterministic); both processes must also
agree with each other exactly (the multi-host contract).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

_DRIVER = os.path.join(os.path.dirname(__file__), "mh_driver.py")
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_PORT = [29810]

#: environment-bound (verified failing identically on the untouched
#: seed on this box before PR 10's changes): this jaxlib's CPU
#: runtime rejects the 2-process Gloo program outright —
#: 'XlaRuntimeError: INVALID_ARGUMENT: Multiprocess computations
#: aren't implemented on the CPU backend.' raised from the first
#: cross-process collective inside TPUModel.fit, so every spawn-based
#: test here dies in the child process before any assertion of OURS
#: runs. Not a knife edge and not a semantics bug in this repo: the
#: same programs pass on jaxlib builds whose CPU client implements
#:  multi-process collectives (the boxes these tests were written on),
#: hence non-strict — a runtime that supports them turns these back
#: into real assertions.
_cpu_multiprocess_xfail = pytest.mark.xfail(
    strict=False,
    reason="environment-bound: this jaxlib's CPU backend raises "
           "'Multiprocess computations aren't implemented' on the "
           "first cross-process collective (see in-file note)")


def _ports():
    _PORT[0] += 2
    return _PORT[0], _PORT[0] + 1


def _run_procs(mode, sync_mode, nprocs, outdir, jax_port, ps_port,
               timeout=420):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, _DRIVER, mode, sync_mode, str(i), str(nprocs),
         str(jax_port), str(ps_port), str(outdir)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for i in range(nprocs)]
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        outputs.append(out)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
    return outputs


def _load_weights(outdir, pid):
    with np.load(os.path.join(str(outdir), f"weights_{pid}.npz")) as z:
        return [z[k] for k in z.files]


@_cpu_multiprocess_xfail
@pytest.mark.parametrize("sync_mode", ["step", "average"])
def test_two_process_sync_matches_single_process(tmp_path, sync_mode):
    jax_port, ps_port = _ports()
    multi_dir = tmp_path / "multi"
    single_dir = tmp_path / "single"
    multi_dir.mkdir()
    single_dir.mkdir()

    _run_procs("synchronous", sync_mode, 2, multi_dir, jax_port, ps_port)
    # oracle: one process, same global device count (4)
    _run_procs("synchronous", sync_mode, 1, single_dir, jax_port + 100,
               ps_port + 100)

    w0 = _load_weights(multi_dir, 0)
    w1 = _load_weights(multi_dir, 1)
    oracle = _load_weights(single_dir, 0)
    for a, b in zip(w0, w1):  # hosts agree exactly
        np.testing.assert_array_equal(a, b)
    for got, want in zip(w0, oracle):  # and match the 1-process program
        np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
    # distributed predict returned the same thing on both hosts
    p0 = np.load(os.path.join(str(multi_dir), "preds_0.npz"))["preds"]
    p1 = np.load(os.path.join(str(multi_dir), "preds_1.npz"))["preds"]
    np.testing.assert_allclose(p0, p1, atol=1e-6)


@_cpu_multiprocess_xfail
def test_two_process_async_parameter_server(tmp_path):
    """Async mode across processes: the PS runs on the coordinator only,
    the second process's workers reach it over the network, and both
    processes leave fit() with identical pulled weights."""
    jax_port, ps_port = _ports()
    _run_procs("asynchronous", "average", 2, tmp_path, jax_port, ps_port)

    w0 = _load_weights(tmp_path, 0)
    w1 = _load_weights(tmp_path, 1)
    for a, b in zip(w0, w1):
        np.testing.assert_array_equal(a, b)
        assert np.all(np.isfinite(a))
    # training must actually have moved the weights off their init
    assert any(np.abs(a).sum() > 0 for a in w0)


@_cpu_multiprocess_xfail
def test_two_process_hybrid_mesh(tmp_path):
    """hybrid_mesh lays the data axis across processes (DCN) with local
    devices contiguous (ICI), and a cross-process reduction executes."""
    jax_port, ps_port = _ports()
    _run_procs("hybrid_mesh", "step", 2, tmp_path, jax_port, ps_port)
    for pid in (0, 1):
        with np.load(os.path.join(str(tmp_path),
                                  f"weights_{pid}.npz")) as z:
            assert z["ok"][0] == 1.0


def test_hybrid_mesh_single_process_fallback():
    from elephas_tpu.parallel.mesh import hybrid_mesh

    mesh = hybrid_mesh((("data", 4), ("model", 2)))
    assert mesh.shape == {"data": 4, "model": 2}
    with pytest.raises(ValueError):
        hybrid_mesh((("model", 2),), dcn_axis="data")
