"""Conv models through sync-average at realistic partition sizes.

Past the unroll budget (nb > 16) the trainer switches to sequential
per-worker training with a per-batch jitted step; these tests pin (a)
that the switch preserves the vmapped program's semantics exactly (same
RNG derivation, same delta averaging) and (b) that the ~25-50x
conv-in-scan layout pessimization does not silently return — the
per-batch sync-average epoch must stay within small-factor range of the
sync-step trainer's per-batch epoch on the same data.
"""
import time

import numpy as np
import pytest

from elephas_tpu.models import (SGD, Activation, Conv2D, Dense, Flatten,
                                Sequential)
from elephas_tpu.parallel.sync_trainer import (SyncAverageTrainer,
                                               SyncStepTrainer)


def _conv_model():
    model = Sequential([
        Conv2D(8, 3, input_shape=(12, 12, 3), padding="same"),
        Activation("relu"),
        Flatten(),
        Dense(10),
        Activation("softmax"),
    ])
    model.compile(SGD(learning_rate=0.05), "categorical_crossentropy",
                  seed=0)
    return model


def _shards(num_workers=2, n=80, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for w in range(num_workers):
        x = rng.normal(0, 1, (n, 12, 12, 3)).astype(np.float32)
        y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
        out.append((x, y))
    return out


def _trainer(model):
    return SyncAverageTrainer(model, model.optimizer,
                              "categorical_crossentropy")


def test_per_batch_path_matches_vmapped_program(monkeypatch):
    """nb > 16 triggers the per-batch conv path; with the conv detection
    disabled the same config runs the vmapped scan program — results
    must agree (identical RNG key derivation and delta averaging)."""
    shards = _shards()
    model_a = _conv_model()
    trainer_a = _trainer(model_a)
    w0 = model_a.get_weights()
    # batch_size 4 over 80 samples -> nb = 20 > 16: per-batch path
    weights_pb, hist_pb = trainer_a.run(w0, shards, epochs=2, batch_size=4,
                                        validation_split=0.0, seed=3)
    assert trainer_a._step_fns, "per-batch path was not taken"

    class _NeverMatches:
        pass

    model_b = _conv_model()
    trainer_b = _trainer(model_b)
    monkeypatch.setattr("elephas_tpu.models.layers.Conv2D", _NeverMatches)
    weights_scan, hist_scan = trainer_b.run(w0, shards, epochs=2,
                                            batch_size=4,
                                            validation_split=0.0, seed=3)
    assert not trainer_b._step_fns, "vmapped path was not taken"
    for a, b in zip(weights_pb, weights_scan):
        np.testing.assert_allclose(a, b, atol=2e-5)
    for ha, hb in zip(hist_pb, hist_scan):
        np.testing.assert_allclose(ha["loss"], hb["loss"], atol=1e-4)


def test_skip_small_partitions_in_per_batch_path():
    """The reference's 'skip partitions <= batch_size' rule holds on the
    per-batch path: tiny partitions contribute no delta and no history."""
    shards = _shards(num_workers=1, n=80) + [_shards(num_workers=1, n=3,
                                                     seed=9)[0]]
    # pad shapes differ per worker; stack_shards pads to the max — the
    # small shard stays inactive via the sizes > batch_size rule
    model = _conv_model()
    trainer = _trainer(model)
    w0 = model.get_weights()
    weights, hists = trainer.run(w0, shards, epochs=1, batch_size=4,
                                 validation_split=0.0)
    assert hists[0] is not None and hists[1] is None


def test_conv_sync_average_not_pessimized_vs_sync_step():
    """Regression pin for the conv-in-scan layout pessimization: one
    sync-average epoch (per-batch path, 64 batches/partition — resnet8)
    must stay within a small factor of one sync-step epoch (per-batch
    dispatch) over the same data — the pessimized scan is ~25-50x off.

    Both sides run a single-device mesh: the pessimization is a layout
    property of conv gradients under scan, not of the mesh, and this
    CI box's 8 virtual CPU devices share one core (per-batch collective
    loops would trip XLA's stuck-collective watchdog)."""
    import jax
    from jax.sharding import Mesh

    from elephas_tpu.models.resnet import build_resnet8

    rng = np.random.default_rng(0)
    batch_size, nb = 4, 64
    n = batch_size * nb  # 64 batches in the one partition
    x = rng.normal(0, 1, (n, 16, 16, 3)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    shards = [(x, y)]

    def resnet():
        model = build_resnet8(input_shape=(16, 16, 3))
        model.compile(SGD(learning_rate=0.05), "categorical_crossentropy",
                      seed=0)
        return model

    model_avg = resnet()
    avg = SyncAverageTrainer(model_avg, model_avg.optimizer,
                             "categorical_crossentropy")
    w0 = model_avg.get_weights()
    avg.run(w0, shards, epochs=1, batch_size=batch_size,
            validation_split=0.0)  # warmup: compile
    assert avg._step_fns, "expected the per-batch conv path"
    t0 = time.perf_counter()
    avg.run(w0, shards, epochs=1, batch_size=batch_size,
            validation_split=0.0)
    avg_time = time.perf_counter() - t0

    model_step = resnet()
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("data",))
    step = SyncStepTrainer(model_step, model_step.optimizer,
                           "categorical_crossentropy", mesh=mesh1)
    step.fit(w0, x, y, epochs=1, batch_size=batch_size,
             validation_split=0.0)  # warmup: compile
    t0 = time.perf_counter()
    step.fit(w0, x, y, epochs=1, batch_size=batch_size,
             validation_split=0.0)
    step_time = time.perf_counter() - t0

    # same step count (64 per-batch dispatches each); generous factor
    # for dispatch overhead + CI noise — the failure mode being pinned
    # is ~25x, not ~4x
    assert avg_time < 4.0 * step_time, (
        f"sync-average epoch {avg_time:.2f}s vs sync-step "
        f"{step_time:.2f}s — conv pessimization returned?")
