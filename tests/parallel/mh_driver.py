"""Subprocess entry point for the multi-host integration tests.

Each invocation is one JAX-distributed process (CPU backend, 2 local
virtual devices) running the SAME TPUModel.fit program — the
single-controller multi-host recipe. Results are written to
``<outdir>/weights_<pid>.npz`` for the parent test to compare.

Usage: python mh_driver.py <mode> <sync_mode> <pid> <nprocs> <jax_port> \
       <ps_port> <outdir>
"""
import os
import sys


def main():
    mode, sync_mode, pid, nprocs, jax_port, ps_port, outdir = sys.argv[1:8]
    pid, nprocs, jax_port, ps_port = (int(pid), int(nprocs), int(jax_port),
                                      int(ps_port))

    import jax

    # the env's sitecustomize pins JAX_PLATFORMS to the TPU plugin; tests
    # must override through jax.config BEFORE any backend initialization
    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 2 if nprocs > 1 else 4)
    except AttributeError:
        # older jax (< 0.5) has no such option: force the device count
        # through XLA_FLAGS instead (still before backend initialization)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count="
              f"{2 if nprocs > 1 else 4}").strip()
    if nprocs > 1:
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{jax_port}",
            num_processes=nprocs, process_id=pid)

    import numpy as np

    if mode == "hybrid_mesh":
        # hybrid DCN x ICI mesh: the data axis spans the two processes
        # (gradient-style psum over DCN), the model axis stays local
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from elephas_tpu.parallel.mesh import hybrid_mesh, shard_leading

        mesh = hybrid_mesh((("data", 2 * nprocs), ("model", 1)))
        assert mesh.shape == {"data": 2 * nprocs, "model": 1}, mesh.shape
        # each data-axis row is one device; consecutive pairs must belong
        # to one process (ici inside, dcn across)
        procs = [d.process_index for d in mesh.devices[:, 0]]
        assert procs == sorted(procs), procs
        assert len(set(procs)) == nprocs, procs
        x = np.arange(4 * nprocs, dtype=np.float32).reshape(2 * nprocs, 2)
        xd = shard_leading(mesh, "data", x)
        total = jax.jit(
            lambda a: jnp.sum(a),
            out_shardings=NamedSharding(mesh, P()))(xd)
        np.testing.assert_allclose(np.asarray(total), x.sum())
        np.savez(os.path.join(outdir, f"weights_{pid}.npz"),
                 ok=np.asarray([1.0]))
        print(f"proc {pid}: OK", flush=True)
        return

    from elephas_tpu.models import SGD, Dense, Sequential
    from elephas_tpu.tpu_model import TPUModel

    # deterministic separable 3-class problem, identical on every process
    rng = np.random.default_rng(0)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w_true = rng.normal(size=(8, 3)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w_true, axis=1)]

    model = Sequential([Dense(16, input_dim=8, activation="relu"),
                        Dense(3, activation="softmax")])
    model.compile(SGD(learning_rate=0.1), "categorical_crossentropy",
                  metrics=["acc"], seed=0)

    if mode in ("async_crash", "async_resume"):
        # DCN-level fault injection: "async_crash" hard-kills the last
        # process mid-fit (simulated host death / preemption) while the
        # coordinator checkpoints each epoch; "async_resume" restarts
        # fresh processes that restore the latest checkpoint and finish.
        from elephas_tpu.models.callbacks import Callback
        from elephas_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(os.path.join(outdir, "ckpt"),
                                max_to_keep=20)

        if mode == "async_crash" and pid == nprocs - 1:
            import elephas_tpu.worker as worker_mod

            real_train = worker_mod.AsyncWorker.train

            def dying_train(self, xt, yt):
                orig_emit = self._emit

                def emit(epoch, loss):
                    orig_emit(epoch, loss)
                    if epoch >= 1:
                        os._exit(43)  # hard death: no cleanup, no barriers
                self._emit = emit
                return real_train(self, xt, yt)

            worker_mod.AsyncWorker.train = dying_train

        restored_step = -1
        if mode == "async_resume":
            latest = mgr.latest_step()
            if latest is not None:
                state = mgr.restore()
                model.set_weights(
                    [state["weights"][str(i)]
                     for i in range(len(state["weights"]))])
                restored_step = latest

        callbacks = []
        if pid == 0:
            class CkptEveryEpoch(Callback):
                def on_epoch_end(cb_self, epoch, logs=None):
                    mgr.save(epoch, {"weights": {
                        str(i): w for i, w in
                        enumerate(cb_self.model.get_weights())}})

            callbacks = [CkptEveryEpoch()]

        tpu_model = TPUModel(model, mode="asynchronous", frequency="epoch",
                             num_workers=2, batch_size=32, port=ps_port,
                             parameter_server_mode="http")
        try:
            tpu_model.fit((x, y), epochs=4, batch_size=32,
                          validation_split=0.0, verbose=0,
                          callbacks=callbacks)
        except Exception as err:  # noqa: BLE001 — the test asserts on this
            print(f"SURVIVOR_ERROR: {type(err).__name__}: {err}",
                  flush=True)
            sys.exit(3)
        weights = tpu_model.master_network.get_weights()
        np.savez(os.path.join(outdir, f"weights_{pid}.npz"),
                 *[np.asarray(w) for w in weights])
        print(f"proc {pid}: OK restored_step={restored_step}", flush=True)
        return

    kwargs = {"sync_mode": sync_mode} if mode == "synchronous" else {}
    tpu_model = TPUModel(model, mode=mode, num_workers=4, batch_size=32,
                         port=ps_port, parameter_server_mode="http", **kwargs)
    tpu_model.fit((x, y), epochs=3, batch_size=32, validation_split=0.0,
                  verbose=0)

    weights = tpu_model.master_network.get_weights()
    np.savez(os.path.join(outdir, f"weights_{pid}.npz"),
             *[np.asarray(w) for w in weights])
    # distributed predict must also work across hosts
    preds = tpu_model.predict(x[:32])
    np.savez(os.path.join(outdir, f"preds_{pid}.npz"), preds=np.asarray(preds))
    print(f"proc {pid}: OK", flush=True)


if __name__ == "__main__":
    main()
