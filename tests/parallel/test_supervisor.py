"""Elastic worker supervision: policy unit tests on synthetic shards,
plus the end-to-end contracts — a worker crash injected via FaultPlan is
reassigned and fit completes; the epoch aggregator no longer stalls
callbacks when a participant dies; a parameter-server death mid-fit is
survived via snapshot → restart → reconnect."""
import threading
import time
from itertools import count

import numpy as np
import pytest

from elephas_tpu.parallel.supervisor import (QuorumLostError,
                                             WorkerSupervisor)
from elephas_tpu.utils.faults import FaultPlan, clear_plan, install_plan

_PORT = count(27500)


@pytest.fixture(autouse=True)
def _clean_plan():
    clear_plan()
    yield
    clear_plan()


# ---------------------------------------------------------------- unit level
def test_reassign_reruns_failed_shard():
    failed_once = threading.Event()
    runs = []

    def run_shard(slot, idx, shard, attempt):
        runs.append((idx, attempt))
        if idx == 1 and not failed_once.is_set():
            failed_once.set()
            raise RuntimeError("worker died")

    sup = WorkerSupervisor(run_shard, on_worker_failure="reassign")
    report = sup.run(["a", "b", "c"])
    assert sorted(report.completed_shards) == [0, 1, 2]
    assert report.restarts == 1
    assert report.reassigned_shards == [1]
    assert report.lost_shards == []
    assert (1, 1) in runs, "the retry must carry attempt=1"


def test_fail_policy_drains_then_raises_first_error():
    ran = []

    def run_shard(slot, idx, shard, attempt):
        ran.append(idx)
        if idx == 0:
            raise ValueError("boom")

    # pre-supervisor semantics: every dispatched shard still runs (the
    # thread pool drained all submitted futures), THEN the first error
    # aborts — one slot makes the ordering deterministic
    sup = WorkerSupervisor(run_shard, on_worker_failure="fail", num_slots=1)
    with pytest.raises(ValueError, match="boom"):
        sup.run(["a", "b", "c"])
    assert ran == [0, 1, 2]
    assert sorted(sup.report.completed_shards) == [1, 2]
    assert sup.report.restarts == 0


def test_continue_drops_shard_within_quorum():
    def run_shard(slot, idx, shard, attempt):
        if idx == 2:
            raise RuntimeError("always dies")

    sup = WorkerSupervisor(run_shard, on_worker_failure="continue",
                           min_workers=0.5)
    report = sup.run(list("abcd"))
    assert sorted(report.completed_shards) == [0, 1, 3]
    assert report.lost_shards == [2]
    assert report.restarts == 0


def test_continue_raises_when_quorum_lost():
    def run_shard(slot, idx, shard, attempt):
        raise RuntimeError("cluster on fire")

    sup = WorkerSupervisor(run_shard, on_worker_failure="continue",
                           min_workers=0.5)
    with pytest.raises(QuorumLostError, match="0/2"):
        sup.run(["a", "b"])


def test_reassign_budget_exhaustion_reraises_original_error():
    attempts = []

    def run_shard(slot, idx, shard, attempt):
        attempts.append(attempt)
        raise ConnectionError("ps is gone")

    sup = WorkerSupervisor(run_shard, on_worker_failure="reassign",
                           max_worker_restarts=2)
    with pytest.raises(ConnectionError, match="ps is gone"):
        sup.run(["a"])
    assert attempts == [0, 1, 2]  # initial + 2 restarts
    assert sup.report.restarts == 2


def test_ps_restart_gives_a_free_retry():
    ps_alive = threading.Event()
    seen_attempts = []

    def run_shard(slot, idx, shard, attempt):
        seen_attempts.append(attempt)
        if not ps_alive.is_set():
            raise ConnectionError("connection refused")

    def ps_restart():
        ps_alive.set()

    # max_worker_restarts=0: any policy-level retry would raise, so a
    # completed run proves the PS path re-queued without spending budget
    sup = WorkerSupervisor(run_shard, on_worker_failure="reassign",
                           max_worker_restarts=0,
                           ps_probe=ps_alive.is_set, ps_restart=ps_restart,
                           ps_probe_interval=30.0)
    report = sup.run(["a"])
    assert report.completed_shards == [0]
    assert report.ps_restarts == 1
    assert seen_attempts == [0, 0], "the free retry keeps attempt=0"


def test_all_workers_felled_by_one_outage_get_free_retries():
    """Workers that failed on the SAME PS outage all deserve the free
    retry: the late arrivals probe an already-restarted (healthy)
    server and must match on the recent restart instead of burning
    their policy budget — or, under 'fail', aborting the fit."""
    alive = threading.Event()
    both_failed = threading.Barrier(2)
    removed = []

    def run_shard(slot, idx, shard, attempt):
        if not alive.is_set():
            both_failed.wait(timeout=10)  # fail together, like one outage
            raise ConnectionError("ps down")

    sup = WorkerSupervisor(run_shard, on_worker_failure="fail",
                           ps_probe=alive.is_set, ps_restart=alive.set,
                           ps_probe_interval=30.0,
                           on_item_failure=lambda i, a, e:
                           removed.append(i))
    report = sup.run(["a", "b"])  # 'fail' would abort without the grace
    assert sorted(report.completed_shards) == [0, 1]
    assert report.ps_restarts == 1, "one outage, one restart"
    assert removed == [], "nobody should have lost their aggregator seat"


def test_on_item_failure_observer_sees_every_failure():
    observed = []

    def run_shard(slot, idx, shard, attempt):
        if attempt == 0:
            raise RuntimeError("first try dies")

    sup = WorkerSupervisor(run_shard, on_worker_failure="reassign",
                           on_item_failure=lambda i, a, e:
                           observed.append((i, a, type(e).__name__)))
    sup.run(["a", "b"])
    assert sorted(observed) == [(0, 0, "RuntimeError"),
                                (1, 0, "RuntimeError")]


def test_flapping_ps_restarts_are_bounded():
    """A server that dies again after every restart must not loop
    forever: the restart budget runs out and the worker policy takes
    over (here: reassign budget exhaustion re-raises)."""
    restarts = []

    sup = WorkerSupervisor(
        lambda *a: (_ for _ in ()).throw(ConnectionError("ps down")),
        on_worker_failure="reassign", max_worker_restarts=1,
        ps_probe=lambda: False, ps_restart=lambda: restarts.append(1),
        ps_probe_interval=30.0, max_ps_restarts=2)
    with pytest.raises(ConnectionError, match="ps down"):
        sup.run(["a"])
    assert len(restarts) == 2
    assert sup.report.ps_restarts == 2
    # after the PS budget: initial + 1 budgeted worker retry, then raise
    assert sup.report.restarts == 3  # 2 free (PS) + 1 budgeted


def test_aggregator_retracts_dead_members_reports():
    """A dead worker's earlier epoch reports must not stand in for a
    live survivor still mid-epoch (early fire), and late reports for a
    fired epoch are dropped."""
    from elephas_tpu.tpu_model import _EpochAggregator

    fired = []
    agg = _EpochAggregator(3, lambda e, logs: fired.append((e, logs)))
    agg.report(0, 1.0, member="a")
    agg.report(0, 2.0, member="b")
    agg.remove_participant(member="a")  # retracts a's epoch-0 report
    assert fired == [], "epoch 0 must wait for the live survivor c"
    agg.report(0, 3.0, member="c")
    assert [e for e, _ in fired] == [0]
    # a's loss still contributes to the mean (its work was real)
    assert fired[0][1]["loss"] == pytest.approx(2.0)
    agg.report(0, 9.0, member="a")  # late duplicate: dropped, no refire
    assert len(fired) == 1


def test_empty_shards_and_bad_policy():
    report = WorkerSupervisor(lambda *a: None).run([])
    assert report.completed_shards == []
    with pytest.raises(ValueError, match="on_worker_failure"):
        WorkerSupervisor(lambda *a: None, on_worker_failure="shrug")
    with pytest.raises(ValueError, match="min_workers"):
        WorkerSupervisor(lambda *a: None, min_workers=0.0)


# ---------------------------------------------------------- fit integration
def _data(n=192, dim=16, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.random((n, dim), dtype=np.float32)
    w = rng.normal(size=(dim, classes))
    y = np.eye(classes, dtype=np.float32)[np.argmax(x @ w, axis=1)]
    return x, y


def _model(dim=16, classes=4, seed=0):
    from elephas_tpu.models import SGD, Activation, Dense, Sequential

    m = Sequential([Dense(16, input_dim=dim), Activation("relu"),
                    Dense(classes), Activation("softmax")])
    m.compile(SGD(learning_rate=0.1), "categorical_crossentropy", seed=seed)
    return m


def test_worker_crash_mid_fit_is_reassigned_and_fit_completes():
    """Acceptance: a FaultPlan-injected worker crash mid-fit is survived
    — fit completes, histories record the reassignment, and the final
    weights reflect every shard's pushes."""
    from elephas_tpu.tpu_model import TPUModel
    from elephas_tpu.utils.dataset_utils import to_dataset

    x, y = _data(n=256)
    model = _model()
    epochs = 2
    tpu_model = TPUModel(model, mode="asynchronous", frequency="epoch",
                         parameter_server_mode="socket", num_workers=2,
                         batch_size=16, port=next(_PORT))
    before = tpu_model.evaluate(x, y)
    before = before[0] if isinstance(before, list) else before

    # the first worker to enter train() dies once; its shard must be
    # re-dispatched and complete on the retry
    plan = FaultPlan([{"site": "worker.train", "action": "error",
                       "times": 1, "message": "injected worker crash"}])
    install_plan(plan)
    tpu_model.fit(to_dataset(x, y), epochs=epochs, batch_size=16,
                  verbose=0, validation_split=0.0)

    assert plan.fired("worker.train"), "the crash must actually have fired"
    report = tpu_model.training_histories[-1]["supervisor"]
    assert report["restarts"] == 1
    assert len(report["reassigned_shards"]) == 1
    assert sorted(report["completed_shards"]) == [0, 1]
    assert report["lost_shards"] == []
    # both shards' pushes landed: each worker pushes once per epoch, and
    # the crashed shard's retry re-ran all its epochs
    assert tpu_model.parameter_server.num_updates >= 2 * epochs
    after = tpu_model.evaluate(x, y)
    after = after[0] if isinstance(after, list) else after
    assert after < before, "training across all shards should reduce loss"


def test_epoch_aggregator_does_not_hang_when_participant_dies():
    """Acceptance: a dead worker must not park EarlyStopping-style
    callbacks forever — the aggregator sheds the participant and every
    epoch still fires."""
    from elephas_tpu.models.callbacks import Callback
    from elephas_tpu.tpu_model import TPUModel
    from elephas_tpu.utils.dataset_utils import to_dataset

    x, y = _data(n=256)
    epochs = 3

    class EpochCounter(Callback):
        def __init__(self):
            self.epochs = []

        def on_epoch_end(self, epoch, logs=None):
            self.epochs.append(epoch)

    # the shard is permanently lost ('continue'): every train attempt of
    # one worker dies, so only remove_participant keeps callbacks alive
    install_plan(FaultPlan([{"site": "worker.train", "action": "error",
                             "times": 1}]))
    cb = EpochCounter()
    tpu_model = TPUModel(_model(), mode="asynchronous", frequency="epoch",
                         parameter_server_mode="socket", num_workers=2,
                         batch_size=16, port=next(_PORT),
                         on_worker_failure="continue", min_workers=0.5,
                         max_worker_restarts=0)

    done = threading.Event()
    result = {}

    def run_fit():
        try:
            tpu_model.fit(to_dataset(x, y), epochs=epochs, batch_size=16,
                          verbose=0, validation_split=0.0, callbacks=[cb])
        except Exception as err:  # noqa: BLE001 — recorded for asserts
            result["error"] = err
        finally:
            done.set()

    t = threading.Thread(target=run_fit, daemon=True)
    t.start()
    assert done.wait(timeout=120), \
        "fit hung — the epoch aggregator stalled on the dead participant"
    t.join(timeout=5)
    assert "error" not in result, result
    assert cb.epochs == list(range(epochs))
    report = tpu_model.training_histories[-1]["supervisor"]
    assert len(report["lost_shards"]) == 1
    assert len(report["completed_shards"]) == 1


def test_aggregator_reports_are_idempotent_per_member():
    """A re-run of the same shard (PS-restart free retry keeps its
    aggregator seat) re-reports epochs it already counted — those must
    not stand in for other members still mid-epoch."""
    from elephas_tpu.tpu_model import _EpochAggregator

    fired = []
    agg = _EpochAggregator(2, lambda e, logs: fired.append(e))
    agg.report(0, 1.0, member="a")
    agg.report(0, 1.0, member="a")  # the re-run re-reporting epoch 0
    assert fired == [], "member a counted twice for epoch 0"
    agg.report(0, 2.0, member="b")
    assert fired == [0]


def test_ps_recovery_needs_a_transport_error():
    """A worker that died of its own bug must not combine with a failed
    probe into a destructive restart of the parameter server."""
    restarts = []
    calls = {"n": 0}

    def run_shard(slot, idx, shard, attempt):
        calls["n"] += 1
        if calls["n"] == 1:
            raise ValueError("worker's own bug")

    sup = WorkerSupervisor(run_shard, on_worker_failure="reassign",
                           ps_probe=lambda: False,  # probe would agree!
                           ps_restart=lambda: restarts.append(1),
                           ps_probe_interval=30.0)
    sup.run(["a"])
    assert restarts == [], "non-transport failure restarted the PS"
    assert sup.report.restarts == 1  # plain policy reassignment instead


def test_monitor_tolerates_a_single_probe_blip():
    """One timed-out health probe on a healthy server must NOT trigger
    the destructive snapshot restart — the monitor demands consecutive
    failures."""
    probes = iter([False])  # one blip, healthy ever after
    restarted = []
    release = threading.Event()

    sup = WorkerSupervisor(
        lambda *a: release.wait(2.0),
        ps_probe=lambda: next(probes, True),
        ps_restart=lambda: restarted.append(1),
        ps_probe_interval=0.05)
    t = threading.Thread(target=sup.run, args=(["a"],))
    t.start()
    time.sleep(0.5)  # several monitor cycles: blip, then healthy
    release.set()
    t.join(timeout=5)
    assert not t.is_alive()
    assert restarted == [], "a single probe blip restarted a live server"
    assert sup.report.ps_restarts == 0


def test_sole_worker_crash_rejoins_callbacks_on_retry():
    """When the ONLY participant dies, its re-run must take the
    reporting role back — otherwise callbacks go silently dead for the
    rest of the fit."""
    from elephas_tpu.models.callbacks import Callback
    from elephas_tpu.tpu_model import TPUModel
    from elephas_tpu.utils.dataset_utils import to_dataset

    x, y = _data(n=128)
    epochs = 3

    class EpochCounter(Callback):
        def __init__(self):
            self.epochs = []

        def on_epoch_end(self, epoch, logs=None):
            self.epochs.append(epoch)

    install_plan(FaultPlan([{"site": "worker.train", "action": "error",
                             "times": 1}]))
    cb = EpochCounter()
    tpu_model = TPUModel(_model(), mode="asynchronous", frequency="epoch",
                         parameter_server_mode="socket", num_workers=1,
                         batch_size=16, port=next(_PORT))
    tpu_model.fit(to_dataset(x, y), epochs=epochs, batch_size=16,
                  verbose=0, validation_split=0.0, callbacks=[cb])
    assert cb.epochs == list(range(epochs)), (
        f"the rejoined worker must report every epoch, got {cb.epochs}")
    report = tpu_model.training_histories[-1]["supervisor"]
    assert report["restarts"] == 1


def test_callback_error_fails_fit_instead_of_reassigning():
    """An exception raised by a user callback must abort the fit — under
    'reassign' it would otherwise be classified as a worker crash, the
    shard silently re-run without epoch events, and fit() would return
    success with the callback never told."""
    from elephas_tpu.models.callbacks import Callback
    from elephas_tpu.tpu_model import TPUModel
    from elephas_tpu.utils.dataset_utils import to_dataset

    x, y = _data(n=256)

    class DiskFull(Callback):
        def on_epoch_end(self, epoch, logs=None):
            if epoch == 1:
                raise IOError("disk full")

    tpu_model = TPUModel(_model(), mode="asynchronous", frequency="epoch",
                         parameter_server_mode="socket", num_workers=2,
                         batch_size=16, port=next(_PORT))
    with pytest.raises(IOError, match="disk full"):
        tpu_model.fit(to_dataset(x, y), epochs=4, batch_size=16,
                      verbose=0, validation_split=0.0,
                      callbacks=[DiskFull()])
    # the failure did not masquerade as a worker crash
    report = tpu_model.training_histories[-1]["supervisor"]
    assert report["restarts"] == 0 and report["failures"] == []


def test_supervisor_report_survives_a_failed_fit():
    """Which shards failed and how often is exactly what the operator
    needs when fit() raises — the report must land in
    training_histories on the failure path too."""
    from elephas_tpu.tpu_model import TPUModel
    from elephas_tpu.utils.dataset_utils import to_dataset

    x, y = _data(n=256)
    install_plan(FaultPlan([{"site": "worker.train", "action": "error",
                             "times": None}]))  # every attempt dies
    tpu_model = TPUModel(_model(), mode="asynchronous", frequency="epoch",
                         parameter_server_mode="socket", num_workers=2,
                         batch_size=16, port=next(_PORT),
                         max_worker_restarts=1)
    with pytest.raises(ConnectionError):
        tpu_model.fit(to_dataset(x, y), epochs=2, batch_size=16,
                      verbose=0, validation_split=0.0)
    report = tpu_model.training_histories[-1]["supervisor"]
    assert report["restarts"] >= 1
    assert report["failures"], "the failure trail must be recorded"


@pytest.mark.slow
def test_ps_death_mid_fit_survived_via_snapshot_restart():
    """Acceptance: with ``ps_auto_restart=True`` a parameter-server death
    mid-fit is detected by the health probe, the server is restarted
    from the latest snapshot on the same port, workers reconnect through
    the client retry path, and training completes."""
    from elephas_tpu.tpu_model import TPUModel
    from elephas_tpu.utils.dataset_utils import to_dataset

    x, y = _data(n=256)
    model = _model()
    tpu_model = TPUModel(model, mode="asynchronous", frequency="epoch",
                         parameter_server_mode="socket", num_workers=2,
                         batch_size=16, port=next(_PORT),
                         ps_auto_restart=True, ps_probe_interval=0.2)
    before = tpu_model.evaluate(x, y)
    before = before[0] if isinstance(before, list) else before

    # pace the workers (deterministically, via the fault layer) so the
    # kill lands mid-fit, not after it
    install_plan(FaultPlan([{"site": "worker.epoch", "action": "delay",
                             "delay": 0.2, "times": None}]))

    original_server = tpu_model.parameter_server
    result = {}

    def run_fit():
        try:
            tpu_model.fit(to_dataset(x, y), epochs=8, batch_size=16,
                          verbose=0, validation_split=0.0)
            result["outcome"] = "completed"
        except Exception as err:  # noqa: BLE001 — recorded for asserts
            result["outcome"] = "raised"
            result["error"] = err

    t = threading.Thread(target=run_fit)
    t.start()
    deadline = time.monotonic() + 30
    while original_server.num_updates < 2:
        assert time.monotonic() < deadline, "fit never started updating"
        time.sleep(0.05)
    updates_before_kill = original_server.num_updates
    original_server.stop()  # murder the PS mid-fit

    t.join(timeout=120)
    assert not t.is_alive(), "fit hung after the PS death"
    assert result.get("outcome") == "completed", result
    report = tpu_model.training_histories[-1]["supervisor"]
    assert report["ps_restarts"] >= 1
    assert tpu_model.parameter_server is not original_server
    # the restart restored the snapshot: progress was kept, not reset
    assert tpu_model.parameter_server.num_updates >= updates_before_kill
    after = tpu_model.evaluate(x, y)
    after = after[0] if isinstance(after, list) else after
    assert np.isfinite(after)
    assert after < before, "training should have continued to converge"
