"""Process-level fault injection on the DCN path.

The in-process fault suite (``tests/parameter/test_fault_injection.py``)
covers thread-level failures; here a real JAX-distributed *process* is
hard-killed mid-fit (simulated host death / preemption). Contract:

- the surviving process exits with a clear, bounded-time error naming
  the barrier — never a silent hang waiting on a dead peer;
- a restarted run restores the latest checkpoint and finishes training.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

_DRIVER = os.path.join(os.path.dirname(__file__), "mh_driver.py")
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_PORT = [31810]


def _ports():
    _PORT[0] += 2
    return _PORT[0], _PORT[0] + 1


def _launch(mode, nprocs, outdir, jax_port, ps_port, timeout=240):
    env = dict(os.environ)
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["ELEPHAS_TPU_BARRIER_TIMEOUT_S"] = "20"
    procs = [subprocess.Popen(
        [sys.executable, _DRIVER, mode, "average", str(i), str(nprocs),
         str(jax_port), str(ps_port), str(outdir)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
        for i in range(nprocs)]
    outputs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        outputs.append(out)
    return procs, outputs


#: environment-bound, same root cause as test_multihost_spawn.py's
#: marker (verified failing identically on the untouched seed on this
#: box before PR 10's changes): this jaxlib's CPU runtime raises
#: 'Multiprocess computations aren't implemented on the CPU backend.'
#: at the first cross-process collective, so the "crash run" here
#: fails during TRAINING rather than at the injected kill — no epoch
#: ever completes, no checkpoint is written, and both tests' premises
#: (a surviving peer mid-fit; a checkpoint to resume from) never
#: materialize. Passes on jaxlib builds whose CPU client implements
#: multi-process collectives, hence non-strict.
_cpu_multiprocess_xfail = pytest.mark.xfail(
    strict=False,
    reason="environment-bound: this jaxlib's CPU backend raises "
           "'Multiprocess computations aren't implemented' before the "
           "fault-injection premise can establish (see in-file note)")


@_cpu_multiprocess_xfail
def test_peer_death_surfaces_clear_error_not_hang(tmp_path):
    """Hard-kill process 1 mid-fit: process 0 must exit within the
    barrier deadline with an error naming the barrier."""
    jax_port, ps_port = _ports()
    start = time.monotonic()
    procs, outputs = _launch("async_crash", 2, tmp_path, jax_port, ps_port)
    elapsed = time.monotonic() - start

    assert procs[1].returncode == 43, \
        f"crash process should hard-exit 43:\n{outputs[1]}"
    # the survivor must FAIL (nonzero) with a visible, named error: the
    # fit raises (barrier watchdog, or Gloo/coordination-service failure
    # detection when it wins the race) and the driver reports it before
    # exiting. JAX's own distributed shutdown may then abort the
    # interpreter, so the exact code varies — silent success or a hang
    # are the failure modes under test.
    assert procs[0].returncode != 0, f"survivor succeeded?!:\n{outputs[0]}"
    assert "SURVIVOR_ERROR" in outputs[0], outputs[0]
    assert ("timed out" in outputs[0] or "peer" in outputs[0]
            or "heartbeat" in outputs[0]), outputs[0]
    # bounded: the 20s barrier deadline plus training/startup slack,
    # nowhere near the subprocess timeout a hang would hit
    assert elapsed < 180, f"survivor took {elapsed:.0f}s — effectively a hang"
    # the coordinator checkpointed at least one epoch before the failure
    from elephas_tpu.utils.checkpoint import CheckpointManager

    assert CheckpointManager(tmp_path / "ckpt").latest_step() is not None


@_cpu_multiprocess_xfail
def test_restart_resumes_from_checkpoint(tmp_path):
    """The full recovery story: crash run leaves checkpoints; a fresh
    2-process run restores the latest step, finishes, and both hosts
    agree on finite weights."""
    jax_port, ps_port = _ports()
    _launch("async_crash", 2, tmp_path, jax_port, ps_port)
    from elephas_tpu.utils.checkpoint import CheckpointManager

    latest = CheckpointManager(tmp_path / "ckpt").latest_step()
    assert latest is not None and latest >= 0

    jax_port, ps_port = _ports()
    procs, outputs = _launch("async_resume", 2, tmp_path, jax_port, ps_port)
    for i, (p, out) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"resume process {i} failed:\n{out}"
        assert f"restored_step={latest}" in out, out

    w0 = np.load(os.path.join(str(tmp_path), "weights_0.npz"))
    w1 = np.load(os.path.join(str(tmp_path), "weights_1.npz"))
    for k in w0.files:
        np.testing.assert_array_equal(w0[k], w1[k])
        assert np.all(np.isfinite(w0[k]))
