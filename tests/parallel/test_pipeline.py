"""Pipeline-parallelism tests: GPipe schedule over the virtual CPU mesh
must match sequential stage application exactly, for values and grads."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from elephas_tpu.parallel.pipeline import make_pipeline_fn, stack_stage_params


def _mesh(pipe=4):
    devices = np.array(jax.devices()[:pipe])
    return Mesh(devices, ("pipe",))


def _stage_fn(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"] + x  # residual, shape-preserving


def _stage_params(key, d=8, hidden=16):
    k1, k2 = jax.random.split(key)
    return {"w1": jax.random.normal(k1, (d, hidden)) * 0.3,
            "b1": jnp.zeros((hidden,)),
            "w2": jax.random.normal(k2, (hidden, d)) * 0.3,
            "b2": jnp.zeros((d,))}


def _sequential(per_stage, x):
    for p in per_stage:
        x = _stage_fn(p, x)
    return x


@pytest.mark.parametrize("num_micro", [4, 8])
def test_pipeline_matches_sequential(num_micro):
    mesh = _mesh(4)
    per_stage = [_stage_params(jax.random.PRNGKey(i)) for i in range(4)]
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(9), (16, 8))

    pipe_fn = make_pipeline_fn(_stage_fn, mesh, num_microbatches=num_micro)
    got = np.asarray(jax.jit(pipe_fn)(stacked, x))
    want = np.asarray(_sequential(per_stage, x))
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_pipeline_gradients_match_sequential():
    mesh = _mesh(4)
    per_stage = [_stage_params(jax.random.PRNGKey(i)) for i in range(4)]
    stacked = stack_stage_params(per_stage)
    x = jax.random.normal(jax.random.PRNGKey(9), (8, 8))
    y = jax.random.normal(jax.random.PRNGKey(10), (8, 8))

    pipe_fn = make_pipeline_fn(_stage_fn, mesh)

    def loss_pipe(p):
        return jnp.mean((pipe_fn(p, x) - y) ** 2)

    def loss_seq(per):
        return jnp.mean((_sequential(per, x) - y) ** 2)

    g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
    g_seq = jax.grad(loss_seq)(per_stage)
    g_seq_stacked = stack_stage_params(g_seq)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5,
                                   rtol=1e-4)


def test_pipeline_with_transformer_blocks():
    """Pipeline the flagship's transformer blocks: 8 layers, 4 stages of 2,
    parity with the unpipelined forward."""
    from elephas_tpu.models.transformer import (TransformerConfig,
                                                _layer_norm, init_params)

    config = TransformerConfig(vocab_size=32, num_layers=8, num_heads=2,
                               d_model=16, d_ff=32, max_seq_len=16,
                               dtype=jnp.float32)
    params = init_params(config, jax.random.PRNGKey(0))

    def block(layer, x):
        from elephas_tpu.ops.attention import attention

        h = _layer_norm(x, layer["ln1"]["gamma"], layer["ln1"]["beta"])
        q = jnp.einsum("btd,dhk->bhtk", h, layer["attn"]["wq"])
        k = jnp.einsum("btd,dhk->bhtk", h, layer["attn"]["wk"])
        v = jnp.einsum("btd,dhk->bhtk", h, layer["attn"]["wv"])
        o = attention(q, k, v, causal=True)
        x = x + jnp.einsum("bhtk,hkd->btd", o, layer["attn"]["wo"])
        h = _layer_norm(x, layer["ln2"]["gamma"], layer["ln2"]["beta"])
        h = jax.nn.gelu(h @ layer["mlp"]["w1"] + layer["mlp"]["b1"])
        return x + h @ layer["mlp"]["w2"] + layer["mlp"]["b2"]

    def stage_fn(stage_params, x):
        # two consecutive blocks per stage
        for j in range(2):
            layer = jax.tree_util.tree_map(lambda p: p[j], stage_params)
            x = block(layer, x)
        return x

    per_stage = [stack_stage_params(
        [params[f"layer_{2 * s + j}"] for j in range(2)]) for s in range(4)]
    stacked = stack_stage_params(per_stage)

    x = jax.random.normal(jax.random.PRNGKey(3), (8, 16, config.d_model))
    mesh = _mesh(4)
    pipe_fn = make_pipeline_fn(stage_fn, mesh, num_microbatches=4)
    got = np.asarray(jax.jit(pipe_fn)(stacked, x))

    want = x
    for i in range(8):
        want = block(params[f"layer_{i}"], want)
    np.testing.assert_allclose(got, np.asarray(want), atol=1e-4, rtol=1e-4)


def test_pipelined_lm_training_matches_sequential():
    """End-to-end pipelined transformer training (embed/head outside the
    stage stack, optimizer over stage-stacked params): per-step losses and
    final weights must match unpipelined training on the same data."""
    import dataclasses

    import optax

    from elephas_tpu.models.transformer import (TransformerConfig,
                                                init_params, make_train_step)
    from elephas_tpu.parallel.pipeline import (make_pipelined_train_step,
                                               merge_transformer_stages,
                                               shard_pipelined_params,
                                               split_transformer_stages)

    config = TransformerConfig(vocab_size=32, num_layers=4, num_heads=2,
                               d_model=16, d_ff=32, max_seq_len=16,
                               dtype=jnp.float32, attention_impl="xla")
    params = init_params(config, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                                config.vocab_size)

    # split BEFORE the oracle runs: the jitted steps donate their param
    # buffers, so the flat pytree is consumed by sequential training
    mesh = _mesh(2)
    pipe_params = shard_pipelined_params(
        split_transformer_stages(params, config, num_stages=2), mesh)

    # sequential oracle — deep-copied: the donating seq step would
    # otherwise delete buffers the replicated pipe params alias on CPU
    tx = optax.adam(1e-2)
    seq_params = jax.tree_util.tree_map(lambda p: jnp.array(p, copy=True),
                                        params)
    seq_opt = tx.init(seq_params)
    seq_step = make_train_step(config, tx)
    seq_losses = []
    for _ in range(4):
        seq_params, seq_opt, loss = seq_step(seq_params, seq_opt, tokens)
        seq_losses.append(float(loss))

    # pipelined: 4 layers over 2 stages, 4 microbatches
    pipe_opt = jax.jit(tx.init)(pipe_params)
    pipe_step = make_pipelined_train_step(config, tx, mesh,
                                          num_microbatches=4)
    pipe_losses = []
    for _ in range(4):
        pipe_params, pipe_opt, loss = pipe_step(pipe_params, pipe_opt,
                                                tokens)
        pipe_losses.append(float(loss))

    np.testing.assert_allclose(pipe_losses, seq_losses, atol=1e-5,
                               rtol=1e-5)
    merged = merge_transformer_stages(jax.device_get(pipe_params), config)
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(seq_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5,
                                   rtol=1e-4)
    assert pipe_losses[-1] < pipe_losses[0]  # actually trained


def test_split_merge_roundtrip():
    from elephas_tpu.models.transformer import (TransformerConfig,
                                                init_params)
    from elephas_tpu.parallel.pipeline import (merge_transformer_stages,
                                               split_transformer_stages)

    config = TransformerConfig(vocab_size=32, num_layers=4, num_heads=2,
                               d_model=16, d_ff=32, max_seq_len=16,
                               dtype=jnp.float32)
    params = init_params(config, jax.random.PRNGKey(0))
    merged = merge_transformer_stages(
        split_transformer_stages(params, config, 2), config)
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with pytest.raises(ValueError, match="split"):
        split_transformer_stages(params, config, 3)


def test_batch_not_divisible_raises():
    mesh = _mesh(4)
    stacked = stack_stage_params(
        [_stage_params(jax.random.PRNGKey(i)) for i in range(4)])
    pipe_fn = make_pipeline_fn(_stage_fn, mesh, num_microbatches=4)
    with pytest.raises(ValueError):
        pipe_fn(stacked, jnp.zeros((6, 8)))


def test_stage_count_mismatch_raises():
    mesh = _mesh(4)
    stacked = stack_stage_params(
        [_stage_params(jax.random.PRNGKey(i)) for i in range(8)])
    pipe_fn = make_pipeline_fn(_stage_fn, mesh)
    with pytest.raises(ValueError, match="stages"):
        pipe_fn(stacked, jnp.zeros((8, 8)))


def test_pipelined_remat_matches_baseline():
    """config.remat reruns each block in the backward sweep; values and
    the training trajectory must be unchanged."""
    import dataclasses

    import optax

    from elephas_tpu.models.transformer import TransformerConfig, init_params
    from elephas_tpu.parallel.pipeline import (make_pipelined_train_step,
                                               shard_pipelined_params,
                                               split_transformer_stages)

    base = TransformerConfig(vocab_size=32, num_layers=4, num_heads=2,
                             d_model=16, d_ff=32, max_seq_len=16,
                             dtype=jnp.float32, attention_impl="xla")
    mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0, 32)
    tx = optax.adam(1e-2)

    results = []
    for remat in (False, True):
        config = dataclasses.replace(base, remat=remat)
        params = shard_pipelined_params(
            split_transformer_stages(init_params(config,
                                                 jax.random.PRNGKey(0)),
                                     config, num_stages=2), mesh)
        opt = jax.jit(tx.init)(params)
        step = make_pipelined_train_step(config, tx, mesh,
                                         num_microbatches=2)
        losses = []
        for _ in range(3):
            params, opt, loss = step(params, opt, tokens)
            losses.append(float(loss))
        results.append(losses)
    np.testing.assert_allclose(results[0], results[1], atol=1e-5, rtol=1e-5)
    assert results[0][-1] < results[0][0]


def test_pipelined_dp_x_pp_matches_sequential_training():
    """dp x pp composition: tokens shard over 'data', stages over
    'pipe'; the optimization trajectory must match plain single-device
    training on the same global batch."""
    import dataclasses

    import optax

    from elephas_tpu.models.transformer import (TransformerConfig,
                                                init_params, make_train_step)
    from elephas_tpu.parallel.pipeline import (make_pipelined_train_step,
                                               merge_transformer_stages,
                                               shard_pipelined_params,
                                               split_transformer_stages)
    from jax.sharding import NamedSharding, PartitionSpec as P

    config = TransformerConfig(vocab_size=32, num_layers=4, num_heads=2,
                               d_model=16, d_ff=32, max_seq_len=16,
                               dtype=jnp.float32, attention_impl="xla")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 8), 0, 32)
    tx = optax.sgd(1e-2)

    # oracle: plain unsharded training
    ref_params = init_params(config, jax.random.PRNGKey(0))
    ref_opt = tx.init(ref_params)
    ref_step = make_train_step(config, tx)
    ref_losses = []
    for _ in range(3):
        ref_params, ref_opt, loss = ref_step(ref_params, ref_opt, tokens)
        ref_losses.append(float(loss))

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "pipe"))
    params = shard_pipelined_params(
        split_transformer_stages(init_params(config, jax.random.PRNGKey(0)),
                                 config, num_stages=2), mesh)
    opt = jax.jit(tx.init)(params)
    tok_sharded = jax.device_put(tokens,
                                 NamedSharding(mesh, P("data", None)))
    step = make_pipelined_train_step(config, tx, mesh, num_microbatches=2,
                                     batch_axis="data")
    losses = []
    for _ in range(3):
        params, opt, loss = step(params, opt, tok_sharded)
        losses.append(float(loss))
    np.testing.assert_allclose(losses, ref_losses, atol=1e-5, rtol=1e-5)

    merged = merge_transformer_stages(jax.device_get(params), config)
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(jax.device_get(ref_params))):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)
