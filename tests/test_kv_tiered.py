"""Tiered KV spill + resumable cross-request sessions: eviction
demotes parked blocks device -> host RAM -> (Q8) object storage
instead of discarding, admission chain walks fall through the tiers
and promote back, and a session-tagged request's trailing KV persists
at retirement so the conversation's next request admits as a chain hit
on ANY replica sharing the session store — all asserted
token-identical against the spill-off / solo ``generate`` oracles,
with the lossy-payload content-addressing rule pinned."""
import re
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.kvtier import (HostTier, SessionStore, SpilledBlock,
                                StorageTier, TieredSpill, decode_payload,
                                encode_payload)
from elephas_tpu.models.block_cache import chain_keys
from elephas_tpu.models.transformer import (TransformerConfig, generate,
                                            init_params)
from elephas_tpu.serving_engine import DecodeEngine
from elephas_tpu.utils.storage import LocalMirrorStore, register_store


@pytest.fixture(scope="module")
def model():
    config = TransformerConfig(vocab_size=97, num_layers=2, num_heads=4,
                               d_model=32, d_ff=64, max_seq_len=64,
                               dtype=jnp.float32)
    params = init_params(config, jax.random.PRNGKey(0))
    return params, config


@pytest.fixture()
def mirror(tmp_path):
    store = LocalMirrorStore(tmp_path)
    register_store("mirror", store)
    yield store
    register_store("mirror", None)


def _ref(params, config, prompt, n):
    return list(np.asarray(
        generate(params, jnp.asarray(prompt)[None], n, config))[0])


def _drain(eng):
    while eng.pending:
        eng.step()


def _events(eng, rid):
    return (eng.request_trace(rid) or {"events": []})["events"]


# ------------------------------------------------------ payload codec
def test_payload_codec_exact_and_q8_lossy_marking():
    """The wire format: ``compress="none"`` round-trips bit-exact with
    ``lossy=False``; ``"q8"`` round-trips close-but-marked-lossy at
    well under half the bytes. The lossy bit travels WITH the payload —
    it is what keeps a dequantized copy from ever re-registering as
    the exact content its tokens address."""
    rng = np.random.default_rng(3)
    payload = {f"layer_{i}": (rng.standard_normal((8, 32, 16),
                                                  dtype=np.float32),
                              rng.standard_normal((8, 32, 16),
                                                  dtype=np.float32))
               for i in range(2)}
    exact = encode_payload(payload, 8, compress="none")
    got, tokens, lossy = decode_payload(exact)
    assert tokens == 8 and not lossy
    for name, (k, v) in payload.items():
        np.testing.assert_array_equal(got[name][0], k)
        np.testing.assert_array_equal(got[name][1], v)
    q8 = encode_payload(payload, 8, compress="q8")
    got, tokens, lossy = decode_payload(q8)
    assert tokens == 8 and lossy
    for name, (k, v) in payload.items():
        np.testing.assert_allclose(got[name][0], k, atol=0.05)
        assert not np.array_equal(got[name][0], k)   # genuinely lossy
    assert len(q8) < 0.5 * len(exact)
    # SpilledBlock accounts its own f32 footprint
    blk = SpilledBlock(b"k", payload, 8, lossy=False)
    assert blk.nbytes == sum(k.nbytes + v.nbytes
                             for k, v in payload.values())


def test_host_overflow_cascades_to_storage_keyed_by_original_tokens(
        mirror):
    """Tier mechanics without an engine: host LRU overflow lands in
    the storage tier Q8-compressed, stored under the ORIGINAL chain
    key (content address of the exact tokens) but marked lossy;
    ``lookup`` falls through host -> storage and reports the source
    tier; ``consumed`` drops only the host copy (storage is the
    durability layer)."""
    rng = np.random.default_rng(5)
    spill = TieredSpill(host_capacity_blocks=2,
                        storage_url="mirror://spill-unit")
    keys = [bytes([i]) * 4 for i in range(3)]
    for key in keys:
        payload = {"layer_0": (rng.standard_normal((4, 8, 8),
                                                   dtype=np.float32),
                               rng.standard_normal((4, 8, 8),
                                                   dtype=np.float32))}
        spill.demote(key, payload, 8)
    # keys[0] aged out of the 2-block host tier into storage, ON DISK
    # under its original content address
    assert mirror.exists(f"mirror://spill-unit/{keys[0].hex()}.npz")
    blk, tier = spill.lookup(keys[0])
    assert tier == "storage" and blk.lossy and blk.key == keys[0]
    blk, tier = spill.lookup(keys[2])
    assert tier == "host" and not blk.lossy
    assert spill.lookup(b"absent") is None
    # consumed: host copy gone, storage copy stays
    spill.consumed(keys[2])
    assert spill.lookup(keys[2]) is None
    spill.consumed(keys[0])
    assert spill.lookup(keys[0])[1] == "storage"
    st = spill.stats()
    assert st["host"]["demotions"] == 3
    assert st["host"]["blocks"] == 1
    assert st["storage"]["blocks"] == 1 and st["storage"]["demotions"] == 1
    assert 0 < st["storage"]["bytes"] < st["host"]["demoted_bytes"]


def test_tiered_spill_thread_safety():
    """Demotion runs on the engine loop while admission walks read
    from submitter threads: hammer both sides plus ``consumed`` and
    require coherent counts, no exceptions."""
    spill = TieredSpill(host_capacity_blocks=8)
    payload = {"l": (np.zeros((2, 4, 4), np.float32),
                     np.zeros((2, 4, 4), np.float32))}
    errors = []

    def writer():
        try:
            for i in range(200):
                spill.demote(bytes([i % 16]), payload, 4)
        except Exception as exc:           # pragma: no cover
            errors.append(exc)

    def reader():
        try:
            for i in range(200):
                found = spill.lookup(bytes([i % 16]))
                if found is not None and i % 3 == 0:
                    spill.consumed(found[0].key)
        except Exception as exc:           # pragma: no cover
            errors.append(exc)

    threads = [threading.Thread(target=f)
               for f in (writer, writer, reader, reader)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert len(spill.host) <= 8
    assert spill.stats()["host"]["demotions"] == 400


# ------------------------------------------- engine demote/promote
def test_eviction_demotes_and_promotion_is_token_identical(model):
    """The tentpole property: under pool pressure parked blocks demote
    to host RAM instead of being discarded, a returning prompt's chain
    walk promotes them back, and the outputs are token-identical to
    the spill-OFF engine AND the solo oracle — with zero refcount
    leaks and the movement visible in /stats + the flight recorder."""
    params, config = model
    rng = np.random.default_rng(5)
    cold = [np.asarray(rng.integers(0, 97, 24)) for _ in range(3)]
    fresh = np.asarray(rng.integers(0, 97, 33))
    traffic = cold + [fresh, cold[0]]

    on = DecodeEngine(params, config, max_slots=1, paged=(13, 8))
    on.enable_kv_spill(host_capacity_blocks=64)
    off = DecodeEngine(params, config, max_slots=1, paged=(13, 8))
    outs = []
    for p, n in [(p, 8) for p in cold] + [(fresh, 6), (cold[0], 8)]:
        rid = on.submit(p, n)
        _drain(on)
        got = on.result(rid)
        outs.append((rid, got))
        orid = off.submit(p, n)
        _drain(off)
        assert got == off.result(orid)                  # spill invisible
    assert outs[-1][1] == _ref(params, config, cold[0], 8)
    st = on.stats["kv_tiers"]
    assert st["host"]["demotions"] >= 2                 # evictions caught
    assert st["promotions"]["host"] >= 1                # and came back
    assert st["host"]["gets"] >= 1
    # the promoted re-admission is an ordinary chain hit on its slot
    last = outs[-1][0]
    promote = next(ev for ev in _events(on, last)
                   if ev["event"] == "kv_promote")
    assert promote["tiers"] == {"host": promote["blocks"]}
    hit = next(ev for ev in _events(on, last)
               if ev["event"] == "kv_cache_hit")
    assert hit["promoted"] >= 1 and hit["blocks"] >= hit["promoted"]
    demote = next(ev for ev in _events(on, last)
                  if ev["event"] == "kv_demote")
    assert demote["blocks"] >= 1                        # one accumulated
    # zero leaks: everything reclaimable, every refcount released
    assert on.stats["blocks_free"] == on.stats["blocks_total"]
    assert all(e.refcount == 0 for e in on._kv_cache._entries.values())
    # spill-off engine surfaces no tier block at all
    assert "kv_tiers" not in off.stats


def test_lossy_storage_block_never_reregisters_chain(model, mirror):
    """The content-addressing fix, pinned: a Q8 storage block keys by
    its ORIGINAL tokens but carries ``lossy=True``. The default engine
    stops its tier walk at the lossy block (recompute, exact output);
    with ``lossy_promote=True`` the block installs but TAINTS the slot
    — its freshly computed blocks never re-register under chain keys,
    never park, never persist to a session."""
    params, config = model
    rng = np.random.default_rng(17)
    cold = [np.asarray(rng.integers(0, 97, 24)) for _ in range(3)]
    fresh = np.asarray(rng.integers(0, 97, 33))

    def pressure(eng):
        for p in cold:
            rid = eng.submit(p, 8)
            _drain(eng)
        rid = eng.submit(fresh, 6)
        _drain(eng)

    # host tier of ONE block: the rest of the evicted chain cascades
    # to Q8 storage, so cold[0]'s leading blocks come back lossy
    strict = DecodeEngine(params, config, max_slots=1, paged=(13, 8))
    strict.enable_kv_spill(host_capacity_blocks=1,
                           storage_url="mirror://spill-strict")
    pressure(strict)
    rid = strict.submit(cold[0], 8)
    _drain(strict)
    assert strict.result(rid) == _ref(params, config, cold[0], 8)
    assert strict.stats["kv_tiers"].get("promotions", {}) == {}
    assert not any(ev["event"] == "kv_promote"
                   for ev in _events(strict, rid))

    opt = DecodeEngine(params, config, max_slots=1, paged=(13, 8))
    opt.enable_kv_spill(host_capacity_blocks=1,
                        storage_url="mirror://spill-opt",
                        lossy_promote=True)
    pressure(opt)
    rid = opt.submit(cold[0], 8)
    _drain(opt)
    out = opt.result(rid)
    assert len(out) == 8                    # served, approximate KV
    promote = next(ev for ev in _events(opt, rid)
                   if ev["event"] == "kv_promote")
    assert promote["tiers"].get("storage", 0) >= 1
    # the tainted slot registered NOTHING: the prompt's chain is not
    # walkable on device, and no lossy payload was parked or persisted
    walk = chain_keys(cold[0][:16], 8, 0)
    assert opt._kv_cache.match_chain(walk) == []
    assert opt.stats["blocks_free"] == opt.stats["blocks_total"]


# ------------------------------------------------- resumable sessions
def test_session_resume_on_different_replica_token_identical(model):
    """The cross-request session: replica A retires a session-tagged
    request and persists its trailing chain; the conversation's next
    turn lands on replica B (same shared store) and admits as a chain
    hit — token-identical to a never-resumed engine, with the
    hit/miss counters and timeline events telling the story."""
    params, config = model
    rng = np.random.default_rng(11)
    store = SessionStore()
    a = DecodeEngine(params, config, max_slots=1, paged=(16, 8),
                     session_store=store)
    b = DecodeEngine(params, config, max_slots=1, paged=(16, 8),
                     session_store=store)
    turn1 = np.asarray(rng.integers(0, 97, 21))
    rid1 = a.submit(turn1, 6, session="conv-1")
    _drain(a)
    out1 = a.result(rid1)
    assert out1 == _ref(params, config, turn1, 6)
    assert any(ev["event"] == "session_saved"
               for ev in _events(a, rid1))
    assert store.stats()["blocks"] == 3     # (21 + 6) tokens -> 3 full
    # turn 2 = turn1 ++ reply ++ new user tokens, on the OTHER replica
    turn2 = np.concatenate([turn1, np.asarray(out1, np.int32),
                            rng.integers(0, 97, 5).astype(np.int32)])
    rid2 = b.submit(turn2, 6, session="conv-1")
    _drain(b)
    plain = DecodeEngine(params, config, max_slots=1, paged=(16, 8))
    prid = plain.submit(turn2, 6)
    _drain(plain)
    assert b.result(rid2) == plain.result(prid) == _ref(
        params, config, turn2, 6)
    promote = next(ev for ev in _events(b, rid2)
                   if ev["event"] == "kv_promote")
    assert promote["tiers"] == {"session": 3}
    # hit/miss accounting: A's first turn had no chain to find (miss),
    # B's resume found it (hit) — per-engine deltas, shared registry
    assert a.stats["kv_tiers"]["session"]["misses"] == 1
    assert a.stats["kv_tiers"]["session"]["hits"] == 0
    assert b.stats["kv_tiers"]["session"]["hits"] == 1
    assert b.stats["kv_tiers"]["session"]["misses"] == 0
    # idempotent persistence: B re-persisted ONLY the blocks A's turn
    # had not already content-addressed
    assert store.stats()["blocks"] == 4     # turn2's 32 KV tokens
    assert b.stats["blocks_free"] == b.stats["blocks_total"]


def test_session_store_object_backend_roundtrip(model, mirror):
    """A storage-backed session store (``url=``) persists through the
    object store and resumes from a COLD replica process — the
    crash-safe variant of the host-backed topology."""
    params, config = model
    rng = np.random.default_rng(23)
    turn1 = np.asarray(rng.integers(0, 97, 21))
    a = DecodeEngine(params, config, max_slots=1, paged=(16, 8),
                     session_store={"url": "mirror://sessions"})
    rid = a.submit(turn1, 6, session="conv-9")
    _drain(a)
    out1 = a.result(rid)
    # a brand-new engine + store object, same URL: state is in the
    # object store, not the process
    b = DecodeEngine(params, config, max_slots=1, paged=(16, 8),
                     session_store={"url": "mirror://sessions"})
    turn2 = np.concatenate([turn1, np.asarray(out1, np.int32),
                            rng.integers(0, 97, 5).astype(np.int32)])
    rid2 = b.submit(turn2, 6, session="conv-9")
    _drain(b)
    assert b.result(rid2) == _ref(params, config, turn2, 6)
    assert any(ev["event"] == "kv_promote"
               for ev in _events(b, rid2))


def test_hot_swap_invalidates_every_tier(model):
    """Weight hot-swap x tiers: chains key on ``weights_version``, so
    spilled and session blocks from v0 can never serve v1 traffic —
    the host tier's RAM is returned eagerly at the swap, the same
    prompt promotes nothing, and the session's next turn misses by
    construction and recomputes under the new params."""
    params, config = model
    params2 = init_params(config, jax.random.PRNGKey(7))
    rng = np.random.default_rng(29)
    store = SessionStore()
    eng = DecodeEngine(params, config, max_slots=1, paged=(13, 8),
                       session_store=store)
    eng.enable_kv_spill(host_capacity_blocks=64)
    cold = [np.asarray(rng.integers(0, 97, 24)) for _ in range(3)]
    for p in cold:
        rid = eng.submit(p, 8, session="conv-2")
        _drain(eng)
    fresh = np.asarray(rng.integers(0, 97, 33))
    rid = eng.submit(fresh, 6)
    _drain(eng)
    assert eng.stats["kv_tiers"]["host"]["demotions"] >= 1
    assert eng.stats["kv_tiers"]["host"]["blocks"] >= 1
    eng.stage_params(params2, version=1)
    # the swap's atomic point empties the host tier outright — the RAM
    # comes back NOW, not at LRU age-out
    assert eng.apply_staged_params() == 1
    assert eng.stats["kv_tiers"]["host"]["blocks"] == 0
    rid = eng.submit(cold[0], 8, session="conv-2")
    _drain(eng)
    got = eng.result(rid)
    assert got == _ref(params2, config, cold[0], 8)
    assert got != _ref(params, config, cold[0], 8)
    # nothing promoted: v0 chain keys simply do not exist under v1
    # (post-swap allocation pressure may re-demote stale v0 DEVICE
    # blocks — they are unreachable by construction and age out)
    assert not any(ev["event"] == "kv_promote"
                   for ev in _events(eng, rid))
    assert eng.stats["kv_tiers"]["session"]["misses"] >= 1
    # v1 sessions persist under v1 keys and resume fine post-swap
    turn2 = np.concatenate([cold[0], np.asarray(got, np.int32)])
    rid2 = eng.submit(turn2, 4, session="conv-2")
    _drain(eng)
    assert eng.result(rid2) == _ref(params2, config, turn2, 4)


# ----------------------------------------------------- QoS interplay
def test_preemption_parks_to_tiers_without_pinning_hbm(model):
    """QoS preemption x spill: a preempted low-priority decode parks
    its blocks UNPINNED (reclaimable, not HBM-resident by fiat); when
    the high-priority admission's allocation needs them they demote to
    host instead of being discarded, and the victim still resumes
    token-identical (its chain promotes back)."""
    from elephas_tpu.serving_qos import TenantQoS

    params, config = model
    rng = np.random.default_rng(31)
    qos = TenantQoS(tenants={"batch": {"priority": "low"},
                             "live": {"priority": "high"}})
    eng = DecodeEngine(params, config, max_slots=1, paged=(9, 8),
                       qos=qos)
    eng.enable_kv_spill(host_capacity_blocks=64)
    pa = np.asarray(rng.integers(0, 97, 12))
    ra = eng.submit(pa, 12, tenant="batch")
    for _ in range(6):
        eng.step()
    # a high-priority arrival whose allocation exceeds the raw free
    # list: the victim's parked blocks must be RECLAIMED (demoted),
    # never pinned in the pool
    pb = np.asarray(rng.integers(0, 97, 52))
    rb = eng.submit(pb, 4, tenant="live")
    _drain(eng)
    assert eng.result(ra) == _ref(params, config, pa, 12)
    assert eng.result(rb) == _ref(params, config, pb, 4)
    assert eng.stats["preemptions"] == 1
    assert eng.stats["kv_cache"]["pinned_blocks"] == 0
    assert eng.stats["kv_tiers"]["host"]["demotions"] >= 1
    events = [ev["event"] for ev in _events(eng, ra)]
    assert "preempted" in events and "resumed" in events
    assert eng.stats["blocks_free"] == eng.stats["blocks_total"]


def test_queued_same_head_after_promotion_no_double_install(model):
    """The concurrent-claim race, pinned deterministically: two
    same-head requests with the head spilled to host. The first
    admission promotes AND re-registers the chain; the queued second
    must then claim those freshly registered device blocks (its stale
    promo memo is invalidated by the changed hit count) rather than
    double-installing the host copies over them."""
    params, config = model
    rng = np.random.default_rng(37)
    eng = DecodeEngine(params, config, max_slots=1, paged=(13, 8))
    eng.enable_kv_spill(host_capacity_blocks=64)
    cold = [np.asarray(rng.integers(0, 97, 24)) for _ in range(3)]
    for p in cold:
        rid = eng.submit(p, 8)
        _drain(eng)
    fresh = np.asarray(rng.integers(0, 97, 33))
    rid = eng.submit(fresh, 6)
    _drain(eng)
    # two same-head continuations: #2 queues behind #1 (one slot)
    p1 = np.concatenate([cold[0][:16], rng.integers(0, 97, 5)])
    p2 = np.concatenate([cold[0][:16], rng.integers(0, 97, 7)])
    r1 = eng.submit(p1, 6)
    r2 = eng.submit(p2, 6)
    _drain(eng)
    assert eng.result(r1) == _ref(params, config, p1, 6)
    assert eng.result(r2) == _ref(params, config, p2, 6)
    # the second rode device blocks: at most one admission promoted
    promos = [ev for r in (r1, r2) for ev in _events(eng, r)
              if ev["event"] == "kv_promote"]
    assert len(promos) <= 1
    hit2 = next(ev for ev in _events(eng, r2)
                if ev["event"] == "kv_cache_hit")
    assert hit2["promoted"] == 0 and hit2["blocks"] >= 1
    assert eng.stats["blocks_free"] == eng.stats["blocks_total"]
    assert all(e.refcount == 0 for e in eng._kv_cache._entries.values())


# ------------------------------------------------------ observability
def test_metrics_stats_http_and_fleet_surfaces(model):
    """The observability satellite end to end: the spill/session
    counter families and tier gauges render on the registry and agree
    with /stats' ``kv_tiers``; the HTTP server forwards the request
    ``session`` field; a fleet membership probe lands ``kv_tiers`` on
    the replica snapshot and sums session hits into the decode tier
    signals."""
    import json
    import urllib.request

    from elephas_tpu.fleet.membership import ReplicaMembership
    from elephas_tpu.serving_http import ServingServer

    params, config = model
    rng = np.random.default_rng(41)
    eng = DecodeEngine(params, config, max_slots=1, paged=(13, 8),
                       session_store=SessionStore())
    eng.enable_kv_spill(host_capacity_blocks=64)
    srv = ServingServer(eng)
    srv.start()
    try:
        url = f"http://127.0.0.1:{srv.port}"

        def post(body):
            req = urllib.request.Request(
                url + "/v1/generate", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            return json.loads(
                urllib.request.urlopen(req, timeout=30).read())

        turn1 = [int(t) for t in rng.integers(0, 97, 21)]
        out1 = post({"prompt": turn1, "max_new_tokens": 6,
                     "session": "conv-http"})
        turn2 = turn1 + [int(t) for t in out1["tokens"]] + [3, 1, 4]
        post({"prompt": turn2, "max_new_tokens": 4,
              "session": "conv-http"})
        # force demotions so the host-tier series are non-trivial
        for _ in range(3):
            post({"prompt": [int(t) for t in rng.integers(0, 97, 33)],
                  "max_new_tokens": 4})
        text = eng.registry.render()
        for fam in ("serving_kv_spill_demotions_total",
                    "serving_kv_spill_promotions_total",
                    "serving_kv_spill_bytes_total",
                    "serving_kv_session_hits_total",
                    "serving_kv_session_misses_total",
                    "serving_kv_tier_blocks",
                    "serving_kv_tier_bytes"):
            assert fam in text, fam
        kt = eng.stats["kv_tiers"]
        m = re.search(r'^serving_kv_spill_demotions_total\{tier="host"\}'
                      r' (\S+)$', text, re.MULTILINE)
        assert m and float(m.group(1)) == kt["host"]["demotions"]
        m = re.search(r'^serving_kv_session_hits_total (\S+)$', text,
                      re.MULTILINE)
        assert m and float(m.group(1)) == kt["session"]["hits"] == 1
        m = re.search(r'^serving_kv_tier_blocks\{tier="session"\} (\S+)$',
                      text, re.MULTILINE)
        assert m and float(m.group(1)) == kt["session"]["blocks"]
        # fleet probe: the /stats block lands on the snapshot and the
        # summed session counters land on the decode tier signals
        mem = ReplicaMembership([url], probe_interval=30.0,
                                join_after=1)
        mem.probe_once()
        snap = mem.snapshot()[url]
        assert snap["kv_tiers"]["session"]["hits"] == 1
        tiers = mem.tier_signals()
        kv = tiers["decode"]["kv_tiers"]
        assert kv["replicas"] == 1 and kv["session_hits"] == 1
        assert kv["host_blocks"] == kt["host"]["blocks"]
    finally:
        srv.stop()


def test_http_session_rejected_on_engines_without_support(model):
    """The capability-probe contract: an explicit ``session`` on an
    engine whose submit has no session parameter fails loudly (400),
    never silently dropped."""
    import json
    import urllib.error
    import urllib.request

    from elephas_tpu.serving_http import ServingServer

    params, config = model

    class _NoSession:
        def __init__(self, eng):
            self._eng = eng
            self.registry = eng.registry

        def submit(self, prompt, max_new_tokens, admit=True):
            return self._eng.submit(prompt, max_new_tokens, admit=admit)

        def __getattr__(self, name):
            return getattr(self._eng, name)

    eng = DecodeEngine(params, config, max_slots=1)
    srv = ServingServer(_NoSession(eng))
    srv.start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/generate",
            data=json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 2,
                             "session": "s"}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=30)
        assert err.value.code == 400
        assert b"session" in err.value.read()
    finally:
        srv.stop()
