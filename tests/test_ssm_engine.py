"""SSM continuous batching: per-request engine output must equal the
request's solo ssm_generate, with O(1) per-slot state instead of a KV
cache; the HTTP server composes unchanged (duck-typed engine)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.models.ssm import (SSMConfig, init_ssm_params,
                                    ssm_generate)
from elephas_tpu.ssm_engine import SSMEngine


@pytest.fixture(scope="module")
def model():
    config = SSMConfig(vocab_size=64, num_layers=2, d_model=32,
                       d_inner=48)
    params = init_ssm_params(config, jax.random.PRNGKey(0))
    return params, config


def _ref(params, config, prompt, n):
    return list(np.asarray(ssm_generate(
        params, jnp.asarray(prompt)[None], n, config))[0])


def test_parity_mixed_lengths_staggered(model):
    params, config = model
    rng = np.random.default_rng(60)
    prompts = [rng.integers(0, 64, int(n))
               for n in rng.integers(3, 12, size=7)]
    eng = SSMEngine(params, config, max_slots=3)
    outs = eng.run(prompts, max_new_tokens=9)
    for p, o in zip(prompts, outs):
        assert o == _ref(params, config, p, 9)
    assert eng.stats["requests_finished"] == 7


def test_multi_step_and_eos(model):
    params, config = model
    rng = np.random.default_rng(61)
    prompt = rng.integers(0, 64, 6)
    full = _ref(params, config, prompt, 12)
    # eos at a token's FIRST occurrence (a fixed full[k] silently
    # breaks when that token also appears earlier in the decode —
    # which depends on the machine's numerics)
    cut = next(i for i, t in enumerate(full) if i >= 1
               and t not in full[:i])
    eos = full[cut]
    eng = SSMEngine(params, config, max_slots=2, steps_per_sync=3,
                    eos_id=eos)
    [out] = eng.run([prompt], max_new_tokens=12)
    assert out == full[:cut]
    # slot freed mid-chunk serves the next request exactly
    p2 = rng.integers(0, 64, 4)
    [out2] = eng.run([p2], max_new_tokens=5)
    ref2 = _ref(params, config, p2, 5)
    if eos in ref2:
        ref2 = ref2[:ref2.index(eos)]
    assert out2 == ref2


def test_cancel_and_streamed_tokens(model):
    params, config = model
    rng = np.random.default_rng(62)
    prompts = [rng.integers(0, 64, int(n)) for n in (5, 7, 4)]
    eng = SSMEngine(params, config, max_slots=1)
    rids = [eng.submit(p, 8) for p in prompts]
    assert eng.cancel(rids[1]) is True       # queued: dropped
    streamed = {r: [] for r in rids}
    while eng.pending:
        for rid, toks in eng.step().items():
            streamed[rid].extend(toks)
    assert streamed[rids[0]] == eng.result(rids[0]) \
        == _ref(params, config, prompts[0], 8)
    assert streamed[rids[2]] == eng.result(rids[2]) \
        == _ref(params, config, prompts[2], 8)
    assert eng.result(rids[1]) is None


def test_http_server_composes(model):
    """ServingServer is engine-agnostic: the SSM engine serves over the
    same HTTP surface (generate/submit/result/stats)."""
    import json
    import urllib.request

    from elephas_tpu.serving_http import ServingServer

    params, config = model
    rng = np.random.default_rng(63)
    prompt = [int(t) for t in rng.integers(0, 64, 6)]
    with ServingServer(SSMEngine(params, config, max_slots=2)) as srv:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/generate",
            data=json.dumps({"prompt": prompt,
                             "max_new_tokens": 7}).encode(),
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert out["tokens"] == _ref(params, config, prompt, 7)


def test_http_server_default_deadline_skipped_for_ssm(model):
    """A server-wide default_deadline_ms must not poison every request
    against an engine without deadline support — the default is skipped
    (SSMEngine serves normally) while a client's EXPLICIT deadline
    fails loudly instead of being silently dropped."""
    import json
    import urllib.error
    import urllib.request

    from elephas_tpu.serving_http import ServingServer

    params, config = model
    rng = np.random.default_rng(64)
    prompt = [int(t) for t in rng.integers(0, 64, 5)]
    with ServingServer(SSMEngine(params, config, max_slots=2),
                       default_deadline_ms=60000) as srv:
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/generate",
            data=json.dumps({"prompt": prompt,
                             "max_new_tokens": 5}).encode(),
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert out["tokens"] == _ref(params, config, prompt, 5)
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/v1/generate",
            data=json.dumps({"prompt": prompt, "max_new_tokens": 5,
                             "deadline_ms": 100}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=60)
            raise AssertionError("explicit deadline silently dropped")
        except urllib.error.HTTPError as err:
            assert err.code == 400
            assert "deadline" in json.loads(err.read())["error"]


def test_per_request_sampling_and_chunked_prefill(model):
    """top_k=1 at temperature>0 collapses to greedy; chunked prefill
    bounds compiles while keeping exact parity; warmup precompiles."""
    params, config = model
    rng = np.random.default_rng(64)
    prompts = [rng.integers(0, 64, int(n)) for n in (3, 5, 9, 11)]
    eng = SSMEngine(params, config, max_slots=2, prefill_chunk=4)
    eng.warmup(prompt_lengths=(3,))
    r_greedy = eng.submit(prompts[0], 7)
    r_k1 = eng.submit(prompts[1], 7, temperature=1.0, top_k=1)
    r2, r3 = (eng.submit(p, 7) for p in prompts[2:])
    while eng.pending:
        eng.step()
    for rid, p in zip((r_greedy, r_k1, r2, r3), prompts):
        assert eng.result(rid) == _ref(params, config, p, 7)
    # compile bound: full chunk + tails {3, 1} across lengths 3/5/9/11
    assert eng._prefill_fn._cache_size() + \
        eng._prefill_cont_fn._cache_size() <= 4 + 1
    with pytest.raises(ValueError, match="top_p"):
        eng.submit(prompts[0], 3, top_p=2.0)
