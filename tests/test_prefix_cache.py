"""Automatic block-level KV prefix caching: content-addressed chain
keys, refcounted pooled blocks with LRU reclaim, host-mode caching on
contiguous engines and prefill workers, weight-swap version keying,
register_prefix as the pinning layer, and the admission accounting —
all asserted token-identical against the solo ``generate`` oracle."""
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.models.block_cache import BlockCache, chain_keys
from elephas_tpu.models.transformer import (TransformerConfig, generate,
                                            init_params)
from elephas_tpu.serving_engine import DecodeEngine


@pytest.fixture(scope="module")
def model():
    config = TransformerConfig(vocab_size=97, num_layers=2, num_heads=4,
                               d_model=32, d_ff=64, max_seq_len=64,
                               dtype=jnp.float32)
    params = init_params(config, jax.random.PRNGKey(0))
    return params, config


def _ref(params, config, prompt, n):
    return list(np.asarray(
        generate(params, jnp.asarray(prompt)[None], n, config))[0])


# ---------------------------------------------------------- chain keys
def test_chain_keys_content_addressed():
    """Keys are a pure function of (version, token contents): shared
    heads share leading keys, any divergence — content or version —
    changes every key from the divergence on, and partial tail blocks
    never key."""
    toks = np.arange(20, dtype=np.int32)
    keys = chain_keys(toks, 8, 0)
    assert len(keys) == 2                     # 20 // 8, tail excluded
    assert keys == chain_keys(toks.copy(), 8, 0)
    # shared head, divergent second block: first key shared, second not
    other = toks.copy()
    other[9] += 1
    keys2 = chain_keys(other, 8, 0)
    assert keys2[0] == keys[0] and keys2[1] != keys[1]
    # the chain embeds the WHOLE prefix: same second-block tokens under
    # a different first block give a different second key
    shifted = toks.copy()
    shifted[0] += 1
    assert chain_keys(shifted, 8, 0)[1] != keys[1]
    # weights_version seeds the chain root
    assert chain_keys(toks, 8, 1)[0] != keys[0]


def test_block_cache_refcount_lru_pin_eviction():
    c = BlockCache()
    k = [bytes([i]) for i in range(4)]
    e0 = c.insert(k[0], 100, 8, acquire=True)
    e1 = c.insert(k[1], 101, 16, acquire=True)
    assert c.reclaimable_count() == 0
    assert c.match_chain(k[:2]) == [e0, e1]
    # walk stops at the first gap
    assert c.match_chain([k[0], k[2], k[1]]) == [e0]
    # shared: two slots referencing, released one at a time
    c.acquire(e0)
    c.release(e0)
    assert c.reclaimable_count() == 0         # still referenced
    c.release(e0)
    c.release(e1)
    assert c.reclaimable_count() == 2
    # LRU order: e0 released first -> evicted first
    freed = []
    c2 = BlockCache(on_evict=lambda e: freed.append(e.payload))
    a = c2.insert(k[0], 1, 8, acquire=True)
    b = c2.insert(k[1], 2, 8, acquire=True)
    c2.release(a)
    c2.release(b)
    assert c2.evict_lru() is a and freed == [1]
    assert c2.match_chain([k[0]]) == []       # gone from the map
    # pinned: never parks, never evicts; unpin re-parks
    p = c2.insert(k[2], 3, 8, acquire=True)
    c2.pin(p)
    c2.release(p)
    assert c2.reclaimable_count() == 1        # only b... b was evicted?
    # b remains parked; p pinned and excluded
    assert c2.is_parked(b) and not c2.is_parked(p)
    c2.unpin(p)
    assert c2.is_parked(p)
    # host-mode capacity evicts past the bound, pinned exempt
    c3 = BlockCache(capacity=2)
    pin = c3.insert(k[0], "pinned", 8)
    c3.pin(pin)
    c3.insert(k[1], "x", 8)
    c3.insert(k[2], "y", 8)
    c3.insert(k[3], "z", 8)
    assert len(c3) == 3 and c3.get(k[0]) is not None
    assert c3.evictions == 1


# ------------------------------------------------- paged engine caching
def test_paged_shared_prefix_hits_token_identical(model):
    """The tentpole property: same traffic, cache on vs off, outputs
    token-identical; with the cache on, every same-head admission
    after the first reuses the head's full blocks (pointer install)
    and records a ``kv_cache_hit`` timeline event."""
    params, config = model
    rng = np.random.default_rng(5)
    head = list(rng.integers(0, 97, 19))      # 2 full blocks + tail 3
    prompts = [np.asarray(head + list(rng.integers(0, 97, 4)))
               for _ in range(5)]

    on = DecodeEngine(params, config, max_slots=2, paged=(32, 8))
    off = DecodeEngine(params, config, max_slots=2, paged=(32, 8),
                       prefix_cache=False)
    rids = [on.submit(p, 6) for p in prompts]
    while on.pending:
        on.step()
    got = [on.result(r) for r in rids]
    assert got == off.run(prompts, max_new_tokens=6)
    for g, p in zip(got, prompts):
        assert g == _ref(params, config, p, 6)
    st = on.stats
    assert st["kv_cache"]["hits"] == 4        # every admission after #1
    assert st["kv_cache"]["misses"] == 1
    assert st["prefix_tokens_reused"] >= 4 * 16
    assert st["blocks_free"] == st["blocks_total"]   # all reclaimable
    assert st["kv_cache"]["reclaimable_blocks"] == st["kv_cache"][
        "cached_blocks"]
    # the flight recorder shows the hit with its block/token counts
    hits = [ev for r in rids
            for ev in (on.request_trace(r) or {"events": []})["events"]
            if ev["event"] == "kv_cache_hit"]
    assert len(hits) == 4
    assert all(ev["blocks"] == 2 and ev["tokens_reused"] == 16
               for ev in hits)
    # off-engine: no cache surfaces at all
    assert "kv_cache" not in off.stats


def test_concurrent_same_head_requests_share_blocks(model):
    """Two same-head requests IN FLIGHT TOGETHER point their tables at
    the same physical blocks (refcount 2); retirement parks the entries
    instead of freeing the blocks, leaking nothing."""
    params, config = model
    rng = np.random.default_rng(9)
    head = list(rng.integers(0, 97, 16))
    p1 = np.asarray(head + list(rng.integers(0, 97, 3)))
    p2 = np.asarray(head + list(rng.integers(0, 97, 5)))
    eng = DecodeEngine(params, config, max_slots=2, paged=(32, 8))
    r1 = eng.submit(p1, 8)
    r2 = eng.submit(p2, 8)
    shared = [e for lst in eng._slot_cached for e in lst]
    assert {e.refcount for e in shared} == {2}       # both tables point
    assert len({id(e) for e in shared}) == 2         # 2 head blocks
    # the two slots' leading table entries are the SAME block ids
    assert list(eng._tables[0][:2]) == list(eng._tables[1][:2])
    while eng.pending:
        eng.step()
    assert eng.result(r1) == _ref(params, config, p1, 8)
    assert eng.result(r2) == _ref(params, config, p2, 8)
    assert all(e.refcount == 0 for e in shared)
    assert eng.stats["blocks_free"] == eng.stats["blocks_total"]


def test_full_pool_reclaims_cold_blocks_instead_of_waiting(model):
    """The acceptance eviction property: a pool whose free list is
    EMPTY (every block parked in the cache) admits new requests by
    reclaiming cold cached blocks LRU-first — never wedging the queue,
    never shedding an admissible request."""
    params, config = model
    rng = np.random.default_rng(13)
    eng = DecodeEngine(params, config, max_slots=1, paged=(13, 8))
    # three cold 24-token prompts: 3 full blocks each -> 9 of the 12
    # allocatable blocks parked in the cache once retired
    cold = [np.asarray(rng.integers(0, 97, 24)) for _ in range(3)]
    for p in cold:
        rid = eng.submit(p, 8)
        while eng.pending:
            eng.step()
        assert eng.result(rid) == _ref(params, config, p, 8)
    st = eng.stats
    assert st["kv_cache"]["cached_blocks"] == 9
    assert len(eng._free_block_ids) == 3      # raw free list: 3 blocks
    assert st["blocks_free"] == 12            # ... but ALL reclaimable
    # a brand-new request needing FIVE blocks — more than the raw free
    # list holds — still admits immediately by reclaiming cold blocks
    fresh = np.asarray(rng.integers(0, 97, 33))
    rid = eng.submit(fresh, 6)
    while eng.pending:
        eng.step()
    assert eng.result(rid) == _ref(params, config, fresh, 6)
    assert eng.stats["kv_cache"]["evictions"] >= 2
    # LRU: the OLDEST cold prompt's chain broke first
    assert len(eng._kv_cache.match_chain(
        chain_keys(cold[0][:24], 8, 0))) < 3


def test_weight_swap_version_keyed_invalidation(model):
    """The hot-swap x cache interaction: blocks cached under version 0
    are NEVER served after a swap (the chain keys on weights_version,
    so the same prompt misses by construction and recomputes under the
    new params — output == the new-params oracle), and the old-version
    blocks park and reclaim under pressure instead of leaking
    refcounts."""
    params, config = model
    params2 = init_params(config, jax.random.PRNGKey(7))
    rng = np.random.default_rng(21)
    head = list(rng.integers(0, 97, 16))
    p1 = np.asarray(head + list(rng.integers(0, 97, 4)))
    p2 = np.asarray(head + list(rng.integers(0, 97, 4)))
    eng = DecodeEngine(params, config, max_slots=1, paged=(16, 8))
    # warm the cache under v0 and prove it hits
    assert eng.run([p1, p2], max_new_tokens=6) == [
        _ref(params, config, p1, 6), _ref(params, config, p2, 6)]
    assert eng.stats["kv_cache"]["hits"] == 1
    v0_keys = chain_keys(p1[:16], 8, 0)
    assert len(eng._kv_cache.match_chain(v0_keys)) == 2
    # hot-swap mid-traffic (stage from "any thread", applied at the
    # admission atomic point) — the SAME head must now miss and the
    # output must equal the NEW params' oracle, not v0's
    eng.stage_params(params2, version=1)
    rid = eng.submit(p1, 6)
    while eng.pending:
        eng.step()
    got = eng.result(rid)
    assert got == _ref(params2, config, p1, 6)
    assert got != _ref(params, config, p1, 6)  # the swap is observable
    st = eng.stats
    assert st["weights_version"] == 1
    assert st["kv_cache"]["hits"] == 1        # unchanged: v0 never hit
    assert st["kv_cache"]["misses"] == 2
    # v1 chains now cache; v0 entries linger parked (no refcount leak)
    assert len(eng._kv_cache.match_chain(chain_keys(p1[:16], 8, 1))) == 2
    assert all(e.refcount == 0 for e in eng._kv_cache._entries.values())
    # ... and age out of the LRU under pool pressure rather than
    # surviving forever: big fresh prompts force reclaim of v0 blocks
    for _ in range(3):
        big = np.asarray(rng.integers(0, 97, 40))
        rid = eng.submit(big, 8)
        while eng.pending:
            eng.step()
        assert eng.result(rid) == _ref(params2, config, big, 8)
    assert eng.stats["kv_cache"]["evictions"] > 0
    assert len(eng._kv_cache.match_chain(v0_keys)) == 0


def test_register_prefix_pins_against_pressure(model):
    """register_prefix = the pinning layer: its full blocks carry a
    refcount floor (never evicted) while unpinned traffic churns the
    LRU around them; clear_prefixes lifts the floor and the blocks
    become ordinary reclaimable entries."""
    params, config = model
    rng = np.random.default_rng(31)
    prefix = list(rng.integers(0, 97, 16))    # 2 pinned blocks
    eng = DecodeEngine(params, config, max_slots=1, paged=(12, 8))
    eng.register_prefix(prefix)
    st = eng.stats
    assert st["kv_cache"]["pinned_blocks"] == 2
    assert st["kv_cache"]["cached_blocks"] == 2
    # a matching request hits the pinned chain with zero head prefill
    p = np.asarray(prefix + list(rng.integers(0, 97, 4)))
    rid = eng.submit(p, 6)
    while eng.pending:
        eng.step()
    assert eng.result(rid) == _ref(params, config, p, 6)
    assert eng.stats["kv_cache"]["hits"] == 1
    # churn: distinct prompts large enough to force eviction pressure
    for _ in range(4):
        q = np.asarray(rng.integers(0, 97, 30))
        rid = eng.submit(q, 6)
        while eng.pending:
            eng.step()
        assert eng.result(rid) == _ref(params, config, q, 6)
    st = eng.stats
    assert st["kv_cache"]["evictions"] > 0
    assert st["kv_cache"]["pinned_blocks"] == 2      # floor held
    assert eng.stats["kv_cache"]["hits"] >= 1
    eng.clear_prefixes()
    assert eng.stats["kv_cache"]["pinned_blocks"] == 0
    assert eng.stats["kv_cache"]["reclaimable_blocks"] == eng.stats[
        "kv_cache"]["cached_blocks"]


def test_paged_registered_subblock_tail_still_wins(model):
    """Longest registered match wins over the block chain: a pinned
    20-token row (2 full blocks + a 4-token tail) serves a matching
    admission WHOLE — counted as the pinning layer's reuse, neither a
    cache hit nor a miss — while a prompt sharing only the full blocks
    takes the cache-hit path."""
    params, config = model
    rng = np.random.default_rng(81)
    prefix = list(rng.integers(0, 97, 20))
    eng = DecodeEngine(params, config, max_slots=1, paged=(16, 8))
    eng.register_prefix(prefix)
    p = np.asarray(prefix + list(rng.integers(0, 97, 4)))
    rid = eng.submit(p, 6)
    while eng.pending:
        eng.step()
    assert eng.result(rid) == _ref(params, config, p, 6)
    st = eng.stats
    assert st["prefix_hits"] == 1             # the 20-token row served
    assert st["prefix_tokens_reused"] == 20
    assert st["kv_cache"]["hits"] == 0
    assert st["kv_cache"]["misses"] == 0      # registered reuse != miss
    # same 2 full blocks, different continuation: no row match, the
    # pinned chain serves via the ordinary cache walk
    q = np.asarray(prefix[:16] + list(rng.integers(0, 97, 6)))
    rid = eng.submit(q, 6)
    while eng.pending:
        eng.step()
    assert eng.result(rid) == _ref(params, config, q, 6)
    assert eng.stats["kv_cache"]["hits"] == 1
    assert eng.stats["prefix_hits"] == 1      # unchanged


def test_check_admissible_accounts_pinned_blocks(model):
    """Pinned blocks permanently shrink allocatable capacity — a
    non-matching request that could only fit by evicting them 400s at
    submit instead of wedging the FIFO head forever; a request RIDING
    the pinned prefix still fits (its table points at the pins)."""
    params, config = model
    rng = np.random.default_rng(41)
    prefix = list(rng.integers(0, 97, 32))    # 4 pinned of 9 allocatable
    eng = DecodeEngine(params, config, max_slots=1, paged=(10, 8))
    eng.register_prefix(prefix)
    # 9 - 4 pinned = 5 allocatable; a foreign 41+7 request needs 6
    with pytest.raises(ValueError, match="never be admitted"):
        eng.submit(np.asarray(rng.integers(0, 97, 41)), 7)
    # the SAME size starting with the pinned prefix needs only 2 new
    # blocks (4 ride the pins) — admissible, and completes
    p = np.asarray(prefix + list(rng.integers(0, 97, 9)))
    rid = eng.submit(p, 7)
    while eng.pending:
        eng.step()
    assert eng.result(rid) == _ref(params, config, p, 7)


def test_pinned_credit_never_wedges_on_unaligned_registered_prefix(model):
    """The check_admissible/_admit consistency contract: a riding
    request admitted on the strength of its leading pinned run must
    ALWAYS ride it at admission time — the longest-registered-match
    override (the non-block-aligned row covers 2 tokens more than the
    chain) yields when pins make a full private allocation permanently
    impossible, instead of wedging the FIFO head forever."""
    params, config = model
    rng = np.random.default_rng(91)
    prefix = list(rng.integers(0, 97, 26))   # 6 pinned blocks + 2 tail
    eng = DecodeEngine(params, config, max_slots=1, paged=(11, 4))
    eng.register_prefix(prefix)
    assert eng.stats["kv_cache"]["pinned_blocks"] == 6
    # 28 + 8 = 36 tokens -> 9 blocks: only admissible via the pinned
    # run (10 allocatable - 6 pinned = 4 private)
    p = np.asarray(prefix + list(rng.integers(0, 97, 2)))
    rid = eng.submit(p, 8)
    for _ in range(60):
        if not eng.pending:
            break
        eng.step()
    assert eng.result(rid) == _ref(params, config, p, 8)


# ----------------------------------------- host mode: contiguous/export
def test_host_mode_export_prefill_cache(model):
    """The prefill-tier cache: a contiguous export engine's second
    same-head export skips the head's prefill compute (cached_tokens)
    and ships an equivalent frame: the cached head's positions are
    bit-identical copies, the recomputed remainder agrees to float
    rounding (a different XLA program), and the sampled first token —
    what decode parity rides on — is identical."""
    params, config = model
    rng = np.random.default_rng(51)
    head = list(rng.integers(0, 97, 16))
    p1 = head + list(rng.integers(0, 97, 4))
    p2 = head + list(rng.integers(0, 97, 4))
    eng = DecodeEngine(params, config, max_slots=1, prefix_cache=True,
                       prefix_cache_block_size=8)
    out1 = eng.export_prefill(p1, block_size=8)
    assert out1["cached_tokens"] == 0
    out2 = eng.export_prefill(p2, block_size=8)
    assert out2["cached_tokens"] == 16
    # oracle: an uncached engine's export of the same prompt
    plain = DecodeEngine(params, config, max_slots=1,
                         prefix_cache=False)
    ref2 = plain.export_prefill(p2, block_size=8)
    assert out2["first_token"] == ref2["first_token"]
    for a, b in zip(out2["kv_blocks"], ref2["kv_blocks"]):
        # blocks 0-1 (the cached head) are bit-identical copies; the
        # remainder block recomputes under a different fusion
        np.testing.assert_array_equal(a[:2], b[:2])
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)
    assert eng.stats["kv_cache"]["hits"] == 1
    # and the identical FULL prompt re-export hits its whole walkable
    # chain (the final aligned block recomputes by design: the
    # remainder extend produces the first-token logits)
    out3 = eng.export_prefill(p2, block_size=8)
    assert out3["cached_tokens"] == 16
    assert out3["first_token"] == ref2["first_token"]


def test_prefill_worker_enables_tier_local_cache(model):
    """PrefillWorker turns the cache on at its wire block size by
    default (and leaves it off when asked)."""
    from elephas_tpu.disagg import PrefillWorker

    params, config = model
    eng = DecodeEngine(params, config, max_slots=1)
    w = PrefillWorker(eng, block_size=8)
    assert eng._kv_cache is not None and eng._kv_cache_bs == 8
    eng2 = DecodeEngine(params, config, max_slots=1)
    PrefillWorker(eng2, block_size=8, prefix_cache=False)
    assert eng2._kv_cache is None
    del w


def test_fleet_shim_reads_engine_cache(model):
    """The _AutoPrefixEngine compat shim: same ctor surface, misses now
    read straight off the engine's block cache."""
    from elephas_tpu.fleet.pool import _AutoPrefixEngine

    params, config = model
    rng = np.random.default_rng(61)
    head = list(rng.integers(0, 97, 6))
    eng = _AutoPrefixEngine(DecodeEngine(params, config, max_slots=2),
                            prefix_tokens=6, capacity=32)
    prompts = [np.asarray(head + list(rng.integers(0, 97, 3)))
               for _ in range(4)]
    rids = [eng.submit(p, 3) for p in prompts]
    while eng.pending:
        eng.step()
    for rid, p in zip(rids, prompts):
        assert eng.result(rid) == _ref(params, config, p, 3)
    assert eng.misses == 1                    # one cold head
    assert eng.registered_prefixes >= 1
    assert eng.stats["kv_cache"]["hits"] == 3


# ------------------------------------------------------- observability
def test_metrics_and_stats_surfaces(model):
    """The new serving_kv_cache_* series render on the registry and
    agree with /stats' kv_cache dict."""
    params, config = model
    rng = np.random.default_rng(71)
    head = list(rng.integers(0, 97, 8))
    eng = DecodeEngine(params, config, max_slots=1, paged=(16, 8))
    prompts = [np.asarray(head + list(rng.integers(0, 97, 3)))
               for _ in range(3)]
    eng.run(prompts, max_new_tokens=3)
    text = eng.registry.render()
    for fam in ("serving_kv_cache_hits_total",
                "serving_kv_cache_misses_total",
                "serving_kv_cache_evictions_total",
                "serving_kv_cache_blocks",
                "serving_kv_cache_reclaimable_blocks"):
        assert fam in text, fam
    ks = eng.stats["kv_cache"]
    m = re.search(r"^serving_kv_cache_hits_total (\S+)$", text,
                  re.MULTILINE)
    assert m and float(m.group(1)) == ks["hits"]
    snap = eng.registry.snapshot()
    assert "serving_kv_cache_blocks" in snap
    assert ks["hits"] == 2 and ks["misses"] == 1


def test_speculative_mode_composes_with_prefix_cache(model):
    """Speculative mode caches the TARGET model's KV like any other
    engine (draft KV is recomputed at admission, never cached) — the
    former constructor rejection is gone; the full hit/parity story is
    pinned in tests/test_speculative_serving.py."""
    params, config = model
    eng = DecodeEngine(params, config, draft_params=params,
                       draft_config=config, prefix_cache=True,
                       prefix_cache_block_size=8)
    assert eng._kv_cache is not None and eng.draft_config is not None
