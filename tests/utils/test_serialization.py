import numpy as np

from elephas_tpu.models import SGD, Activation, Dense, Sequential
from elephas_tpu.utils.serialization import dict_to_model, model_to_dict


def test_model_dict_round_trip():
    model = Sequential()
    model.add(Dense(16, input_dim=8))
    model.add(Activation("relu"))
    model.add(Dense(1, activation="sigmoid"))
    model.compile(SGD(learning_rate=0.1), "binary_crossentropy", ["acc"], seed=3)

    payload = model_to_dict(model)
    assert set(payload.keys()) == {"model", "weights"}

    rebuilt = dict_to_model(payload)
    x = np.random.default_rng(0).random((4, 8), dtype=np.float32)
    np.testing.assert_allclose(np.asarray(rebuilt.apply(rebuilt.params, x)),
                               np.asarray(model.apply(model.params, x)),
                               atol=1e-6)
