import json

from elephas_tpu.utils.model_utils import (LossModelTypeMapper, ModelType,
                                           ModelTypeEncoder, as_enum)


def test_builtin_mapping():
    mapper = LossModelTypeMapper()
    assert mapper.get_model_type("mse") == ModelType.REGRESSION
    assert mapper.get_model_type("mean_absolute_error") == ModelType.REGRESSION
    assert mapper.get_model_type("categorical_crossentropy") == ModelType.CLASSIFICATION
    assert mapper.get_model_type("binary_crossentropy") == ModelType.CLASSIFICATION


def test_custom_loss_registration():
    def my_custom_loss(y_true, y_pred):
        return y_true - y_pred

    LossModelTypeMapper().register_loss(my_custom_loss, ModelType.REGRESSION)
    assert LossModelTypeMapper().get_model_type("my_custom_loss") == ModelType.REGRESSION
    assert LossModelTypeMapper().get_model_type(my_custom_loss) == ModelType.REGRESSION


def test_singleton():
    assert LossModelTypeMapper() is LossModelTypeMapper()


def test_enum_json_round_trip():
    payload = json.dumps({"model_type": ModelType.CLASSIFICATION},
                         cls=ModelTypeEncoder)
    decoded = json.loads(payload, object_hook=as_enum)
    assert decoded["model_type"] == ModelType.CLASSIFICATION
