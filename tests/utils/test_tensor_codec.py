import numpy as np
import pytest

from elephas_tpu.utils import tensor_codec


def test_round_trip_mixed_dtypes():
    arrays = [
        np.random.rand(4, 3).astype(np.float32),
        np.arange(10, dtype=np.int64),
        np.array(3.5, dtype=np.float64),
        np.zeros((2, 0, 3), dtype=np.float32),
        np.array([True, False]),
    ]
    payload = tensor_codec.encode_tensors(arrays, tensor_codec.KIND_DELTA)
    decoded, kind = tensor_codec.decode_tensors(payload)
    assert kind == tensor_codec.KIND_DELTA
    assert len(decoded) == len(arrays)
    for orig, back in zip(arrays, decoded):
        assert orig.dtype == back.dtype
        assert np.array_equal(orig, back)


def test_rejects_garbage():
    with pytest.raises(tensor_codec.CodecError):
        tensor_codec.decode_tensors(b"not a payload at all")


def test_rejects_truncated():
    payload = tensor_codec.encode_weights([np.ones((8, 8), dtype=np.float32)])
    with pytest.raises(tensor_codec.CodecError):
        tensor_codec.decode_tensors(payload[:-10])


def test_empty_list():
    decoded, kind = tensor_codec.decode_tensors(tensor_codec.encode_weights([]))
    assert decoded == []
    assert kind == tensor_codec.KIND_WEIGHTS
