import numpy as np
import pytest

from elephas_tpu.utils import tensor_codec


def test_round_trip_mixed_dtypes():
    arrays = [
        np.random.rand(4, 3).astype(np.float32),
        np.arange(10, dtype=np.int64),
        np.array(3.5, dtype=np.float64),
        np.zeros((2, 0, 3), dtype=np.float32),
        np.array([True, False]),
    ]
    payload = tensor_codec.encode_tensors(arrays, tensor_codec.KIND_DELTA)
    decoded, kind = tensor_codec.decode_tensors(payload)
    assert kind == tensor_codec.KIND_DELTA
    assert len(decoded) == len(arrays)
    for orig, back in zip(arrays, decoded):
        assert orig.dtype == back.dtype
        assert np.array_equal(orig, back)


def test_rejects_garbage():
    with pytest.raises(tensor_codec.CodecError):
        tensor_codec.decode_tensors(b"not a payload at all")


def test_rejects_truncated():
    payload = tensor_codec.encode_weights([np.ones((8, 8), dtype=np.float32)])
    with pytest.raises(tensor_codec.CodecError):
        tensor_codec.decode_tensors(payload[:-10])


def test_empty_list():
    decoded, kind = tensor_codec.decode_tensors(tensor_codec.encode_weights([]))
    assert decoded == []
    assert kind == tensor_codec.KIND_WEIGHTS


# ------------------------------------------------- zero-copy path contracts

def test_copy_false_views_alias_the_payload_buffer():
    """``copy=False`` must return VIEWS of the payload — zero tensor
    copies on the decode path (the receive-side contract)."""
    arrays = [np.random.rand(16, 8).astype(np.float32),
              np.arange(32, dtype=np.int64)]
    payload = tensor_codec.encode_tensors(arrays)
    raw = np.frombuffer(memoryview(payload), dtype=np.uint8)

    views, _ = tensor_codec.decode_tensors(payload, copy=False)
    for v, orig in zip(views, arrays):
        assert np.shares_memory(v, raw), "copy=False must not copy"
        assert np.array_equal(v, orig)

    copies, _ = tensor_codec.decode_tensors(payload, copy=True)
    for c in copies:
        assert not np.shares_memory(c, raw), "copy=True must own memory"


def test_mutating_payload_mutates_views_the_aliasing_contract():
    """The documented view-mode contract: the arrays alias the buffer,
    so mutating a bytearray payload mutates them (and views of
    immutable ``bytes`` are read-only) — callers must treat view-mode
    arrays as frozen snapshots."""
    arr = np.arange(6, dtype=np.float32)
    payload = tensor_codec.encode_tensors([arr])  # bytearray
    (view,), _ = tensor_codec.decode_tensors(payload, copy=False)
    assert view.flags.writeable

    # flip the first float of the tensor body in the raw buffer
    body_off = len(payload) - arr.nbytes
    payload[body_off:body_off + 4] = np.float32(99.0).tobytes()
    assert view[0] == np.float32(99.0), "view must see payload mutation"

    (frozen,), _ = tensor_codec.decode_tensors(bytes(payload), copy=False)
    assert not frozen.flags.writeable
    with pytest.raises((ValueError, RuntimeError)):
        frozen[0] = 1.0


def test_fortran_order_round_trips_bit_exact():
    f = np.asfortranarray(np.random.rand(7, 5).astype(np.float32))
    assert not f.flags["C_CONTIGUOUS"]
    for copy in (True, False):
        (back,), _ = tensor_codec.decode_tensors(
            tensor_codec.encode_tensors([f]), copy=copy)
        assert back.flags["C_CONTIGUOUS"]
        assert back.dtype == f.dtype
        assert np.array_equal(back, f)


def test_noncontiguous_slice_round_trips_bit_exact():
    base = np.random.rand(16, 12).astype(np.float32)
    sliced = base[::2, 1::3]             # strided view, non-contiguous
    assert not sliced.flags["C_CONTIGUOUS"]
    (back,), _ = tensor_codec.decode_tensors(
        tensor_codec.encode_tensors([sliced]))
    assert np.array_equal(back, sliced)
    assert back.tobytes() == np.ascontiguousarray(sliced).tobytes()


def test_zero_d_and_empty_arrays_both_copy_modes():
    arrays = [np.array(2.5, dtype=np.float64),        # 0-d
              np.zeros((0,), dtype=np.float32),       # empty 1-d
              np.zeros((3, 0, 2), dtype=np.int64)]    # empty 3-d
    payload = tensor_codec.encode_tensors(arrays)
    for copy in (True, False):
        decoded, _ = tensor_codec.decode_tensors(payload, copy=copy)
        for orig, back in zip(arrays, decoded):
            assert back.shape == orig.shape
            assert back.dtype == orig.dtype
            assert np.array_equal(back, orig)


def test_encode_is_single_allocation_bytes_like():
    """The encoder writes header + tensor bytes into ONE preallocated
    buffer and returns it — a WRITABLE bytes-like buffer (sendall/HTTP
    bodies take it without a further copy; view-mode decode hands out
    writable arrays over it)."""
    arrays = [np.random.rand(64).astype(np.float32),
              np.arange(5, dtype=np.int32)]
    payload = tensor_codec.encode_tensors(arrays)
    assert isinstance(payload, memoryview) and not payload.readonly
    # byte-identical to the naive per-array serialization
    import struct

    parts = [tensor_codec.MAGIC,
             struct.pack("<BBI", tensor_codec.VERSION,
                         tensor_codec.KIND_WEIGHTS, len(arrays))]
    for a in arrays:
        code = tensor_codec._DTYPE_CODES[a.dtype]
        parts.append(struct.pack("<BB", code, a.ndim))
        parts.append(struct.pack("<%dQ" % a.ndim, *a.shape))
        parts.append(a.tobytes())
    assert bytes(payload) == b"".join(parts)


def test_alloc_frame_contract_buffers_are_fully_written():
    """The no-memset frame allocator: writable, byte-addressed, sized
    exactly — and the encoder upholds the every-byte-written contract
    (byte-identical frames across repeated encodes, no uninitialized
    residue leaking through gaps)."""
    buf = tensor_codec.alloc_frame(32)
    assert isinstance(buf, memoryview)
    assert not buf.readonly
    assert len(buf) == 32 and buf.nbytes == 32
    buf[:4] = b"abcd"                       # writable, sliceable
    assert bytes(buf[:4]) == b"abcd"
    assert len(tensor_codec.alloc_frame(0)) == 0

    # an encode's output depends only on its inputs: every byte of the
    # uninitialized buffer was written (0-d, empty, and multi-tensor
    # frames cover the header/dims/body layout paths)
    arrays = [np.arange(7, dtype=np.int64), np.array(1.5, np.float64),
              np.zeros((2, 0), np.float32)]
    a = bytes(tensor_codec.encode_tensors(arrays))
    b = bytes(tensor_codec.encode_tensors(arrays))
    assert a == b


# --------------------------------------------------- KV-transfer frames

def test_kv_frame_round_trip_copy_false_views():
    """The disagg receive path: encode_kv_frame -> decode(copy=False)
    -> the fp tensors VIEW the payload buffer (zero-copy all the way to
    the decode engine's install)."""
    from elephas_tpu.disagg.wire import decode_kv_frame, encode_kv_frame

    rng = np.random.default_rng(0)
    blocks = [rng.normal(0, 1, (2, 4, 8, 8)).astype(np.float32)
              for _ in range(4)]
    meta = {"rid": 7, "first_token": 42, "prompt": [1, 2, 3]}
    payload = encode_kv_frame(meta, blocks, quant=False)
    raw = np.frombuffer(memoryview(payload), dtype=np.uint8)
    got_meta, got = decode_kv_frame(payload, copy=False)
    assert got_meta == meta
    assert len(got) == len(blocks)
    for orig, back in zip(blocks, got):
        assert np.shares_memory(back, raw), "fp KV decode must be a view"
        assert np.array_equal(back, orig)


def test_kv_frame_q8_bit_layout_and_error_bound():
    """quantize -> frame-encode -> decode(copy=False) -> dequantize:
    the int8 data and f32 scales survive the wire BIT-EXACTLY (pinned
    against a direct quantize pass), and the decoded output honors the
    quantizer's documented error bound."""
    from elephas_tpu.disagg.wire import decode_kv_frame, encode_kv_frame
    from elephas_tpu.models.quantization import quantize_kv

    rng = np.random.default_rng(1)
    blocks = [rng.normal(0, 2, (3, 4, 8, 8)).astype(np.float32)
              for _ in range(2)]
    payload = encode_kv_frame({"rid": 0}, blocks, quant=True)
    # bit layout: the raw frame holds the exact interleaved
    # (int8, float32) pairs a direct quantization produces
    arrays, kind = tensor_codec.decode(bytes(payload))
    assert kind == tensor_codec.KIND_KV_Q8
    body = arrays[1:]
    assert len(body) == 2 * len(blocks)
    for i, orig in enumerate(blocks):
        q, s = quantize_kv(orig)
        assert body[2 * i].dtype == np.int8
        assert np.array_equal(body[2 * i], q)
        assert body[2 * i + 1].dtype == np.float32
        assert np.array_equal(body[2 * i + 1], s)
    # and the decode helper dequantizes within the bound
    _, back = decode_kv_frame(payload, copy=False)
    for orig, rec in zip(blocks, back):
        absmax = np.max(np.abs(orig), axis=-1, keepdims=True)
        assert np.all(np.abs(rec - orig) <= absmax / 254.0 + 1e-12)


def test_kv_frame_q8_wire_bytes_ratio():
    """Q8 frames measure well under the 0.55x fp32 wire-bytes bar (the
    acceptance criterion's codec half, engine-free)."""
    from elephas_tpu.disagg.wire import encode_kv_frame

    rng = np.random.default_rng(2)
    blocks = [rng.normal(0, 1, (4, 4, 16, 8)).astype(np.float32)
              for _ in range(6)]
    fp = len(encode_kv_frame({"rid": 1}, blocks, quant=False))
    q8 = len(encode_kv_frame({"rid": 1}, blocks, quant=True))
    assert q8 / fp <= 0.55, q8 / fp


def test_kv_frame_edge_tensors_and_errors():
    from elephas_tpu.disagg.wire import decode_kv_frame, encode_kv_frame

    # 0-d / empty / non-contiguous bodies survive the frame round trip
    base = np.random.default_rng(3).normal(
        0, 1, (2, 8, 4)).astype(np.float32)
    arrays = [np.float32(2.5), np.empty((2, 0, 4), np.float32),
              base[:, ::2]]
    meta, back = decode_kv_frame(
        encode_kv_frame({"rid": 2}, arrays, quant=True), copy=False)
    assert meta == {"rid": 2}
    assert back[0].shape == () and abs(float(back[0]) - 2.5) < 0.02
    assert back[1].shape == (2, 0, 4)
    assert np.all(np.abs(back[2] - base[:, ::2])
                  <= np.max(np.abs(base[:, ::2]), axis=-1,
                            keepdims=True) / 254.0 + 1e-12)
    # a non-KV kind is rejected
    with pytest.raises(tensor_codec.CodecError):
        decode_kv_frame(tensor_codec.encode_weights(
            [np.ones(3, np.float32)]))
    # a KV frame missing its metadata tensor is rejected
    with pytest.raises(tensor_codec.CodecError):
        decode_kv_frame(tensor_codec.encode([], tensor_codec.KIND_KV))
