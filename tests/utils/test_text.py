"""Byte tokenizer tests: reversibility, batching, LM windowing, and an
end-to-end text -> transformer train smoke."""
import numpy as np
import pytest

from elephas_tpu.utils.text import ByteTokenizer


def test_roundtrip_including_unicode():
    tok = ByteTokenizer()
    for text in ("hello world", "héllo wörld", "日本語テキスト", ""):
        assert tok.decode(tok.encode(text)) == text
    ids = tok.encode("hi", add_bos=True, add_eos=True)
    assert ids[0] == tok.bos_id and ids[-1] == tok.eos_id
    assert tok.decode(ids) == "hi"  # specials are skipped in decode


def test_encode_batch_pads_and_truncates():
    tok = ByteTokenizer()
    out = tok.encode_batch(["abcdef", "xy"], seq_len=4)
    assert out.shape == (2, 4)
    assert list(out[0]) == [97, 98, 99, 100]  # truncated
    assert list(out[1]) == [120, 121, tok.pad_id, tok.pad_id]


def test_corpus_windowing_and_stride():
    tok = ByteTokenizer()
    rows = tok.corpus_to_sequences(["abcd", "ef"], seq_len=4)
    # stream: a b c d <eos> e f <eos> (8 tokens) -> 2 windows of 4
    assert rows.shape == (2, 4)
    assert rows[0, -1] != rows[1, -1]
    overlapped = tok.corpus_to_sequences(["abcd", "ef"], seq_len=4, stride=2)
    assert overlapped.shape[0] == 3
    with pytest.raises(ValueError):
        tok.corpus_to_sequences(["a"], seq_len=64)


def test_text_to_lm_training_end_to_end():
    import jax
    import jax.numpy as jnp
    import optax

    from elephas_tpu.models.transformer import (TransformerConfig,
                                                init_params, make_train_step)

    tok = ByteTokenizer()
    corpus = ["the quick brown fox jumps over the lazy dog. "] * 24
    rows = tok.corpus_to_sequences(corpus, seq_len=32)
    config = TransformerConfig(vocab_size=tok.vocab_size, num_layers=2,
                               num_heads=4, d_model=32, d_ff=64,
                               max_seq_len=32, dtype=jnp.float32)
    params = init_params(config, jax.random.PRNGKey(0))
    tx = optax.adam(1e-2)
    opt = tx.init(params)
    step = make_train_step(config, tx)
    tokens = jnp.asarray(rows[:16])
    first = None
    for _ in range(10):
        params, opt, loss = step(params, opt, tokens)
        first = first if first is not None else float(loss)
    # a repetitive corpus is highly learnable
    assert float(loss) < first * 0.8
