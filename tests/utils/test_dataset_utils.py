"""Dataset conversion tests (mirror of the reference's rdd_utils tests,
``/root/reference/tests/utils/test_rdd_utils.py``)."""
import numpy as np

from elephas_tpu.utils import dataset_utils


def test_to_dataset():
    features = np.ones((5, 10))
    labels = np.ones((5,))
    ds = dataset_utils.to_dataset(features, labels)
    assert ds.count() == 5
    first = ds.first()
    assert first[0].shape == (10,)
    assert first[1] == 1.0


def test_to_labeled_points_categorical():
    features = np.ones((2, 10))
    labels = np.asarray([[0, 0, 1.0], [0, 1.0, 0]])
    lp_ds = dataset_utils.to_labeled_points(features, labels, True)
    assert lp_ds.count() == 2
    first = lp_ds.first()
    assert len(first.features) == 10
    assert first.label == 2.0


def test_to_labeled_points_not_categorical():
    features = np.ones((2, 10))
    labels = np.asarray([[2.0], [1.0]])
    lp_ds = dataset_utils.to_labeled_points(features, labels, False)
    assert lp_ds.count() == 2
    assert lp_ds.first().label == 2.0


def test_from_labeled_points():
    features = np.ones((2, 10))
    labels = np.asarray([2.0, 1.0])
    lp_ds = dataset_utils.to_labeled_points(features, labels, False)
    x, y = dataset_utils.from_labeled_points(lp_ds, False, None)
    assert x.shape == features.shape
    assert y.shape == labels.shape


def test_from_labeled_points_categorical():
    features = np.ones((2, 10))
    labels = np.asarray([[0, 0, 1.0], [0, 1.0, 0]])
    lp_ds = dataset_utils.to_labeled_points(features, labels, True)
    x, y = dataset_utils.from_labeled_points(lp_ds, True, 3)
    assert x.shape == features.shape
    assert y.shape == labels.shape


def test_encode_label():
    encoded = dataset_utils.encode_label(3, 10)
    assert len(encoded) == 10
    for i in range(10):
        assert encoded[i] == (1 if i == 3 else 0)


def test_lp_to_dataset_categorical():
    features = np.ones((2, 10))
    labels = np.asarray([[0, 0, 1.0], [0, 1.0, 0]])
    lp_ds = dataset_utils.to_labeled_points(features, labels, True)
    ds = dataset_utils.lp_to_dataset(lp_ds, categorical=True, nb_classes=3)
    first = ds.first()
    assert first[0].shape == (10,)
    assert first[1].shape == (3,)


def test_lp_to_dataset_not_categorical():
    features = np.ones((2, 10))
    labels = np.asarray([2.0, 1.0])
    lp_ds = dataset_utils.to_labeled_points(features, labels, False)
    ds = dataset_utils.lp_to_dataset(lp_ds, categorical=False, nb_classes=3)
    first = ds.first()
    assert first[0].shape == (10,)
    assert first[1] == 2.0


def test_lp_to_dataset_categorical_nb_classes_inferred():
    features = np.ones((2, 10))
    labels = np.asarray([[0, 0, 1.0], [0, 1.0, 0]])
    lp_ds = dataset_utils.to_labeled_points(features, labels, True)
    ds = dataset_utils.lp_to_dataset(lp_ds, categorical=True)
    assert ds.first()[1].shape == (3,)


def test_dataset_partitioning():
    features = np.arange(10).reshape(10, 1).astype(float)
    labels = np.arange(10).astype(float)
    ds = dataset_utils.to_dataset(features, labels, num_partitions=3)
    sizes = ds.partition_sizes()
    assert sizes == [4, 3, 3]
    parts = ds.partitions()
    assert len(parts) == 3
    # contiguous, order preserving
    assert np.array_equal(parts[0][1], np.array([0, 1, 2, 3.0]))
    re = ds.repartition(2)
    assert re.partition_sizes() == [5, 5]


def test_tokens_to_sequences_chunks_and_pads():
    import numpy as np
    import pytest

    from elephas_tpu.utils.dataset_utils import tokens_to_sequences

    ids = np.arange(10)
    out = tokens_to_sequences(ids, 4)
    assert out.shape == (2, 4)
    np.testing.assert_array_equal(out[0], [0, 1, 2, 3])
    padded = tokens_to_sequences(ids, 4, drop_remainder=False)
    assert padded.shape == (3, 4)
    np.testing.assert_array_equal(padded[2], [8, 9, 9, 9])
    with pytest.raises(ValueError, match="shorter"):
        tokens_to_sequences(np.arange(3), 4)
    with pytest.raises(ValueError, match="seq_len"):
        tokens_to_sequences(ids, 1)
