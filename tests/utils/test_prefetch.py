"""prefetch_to_device: order-preserving async host->device staging."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.utils.prefetch import prefetch_to_device


def test_order_and_values_preserved():
    items = [np.full((4,), i, np.float32) for i in range(7)]
    out = list(prefetch_to_device(iter(items), size=2))
    assert len(out) == 7
    for i, a in enumerate(out):
        assert isinstance(a, jax.Array)
        np.testing.assert_array_equal(np.asarray(a), items[i])


def test_pytree_items():
    items = [(np.ones((2,)) * i, {"y": np.zeros((3,)) + i})
             for i in range(3)]
    out = list(prefetch_to_device(iter(items), size=1))
    for i, (x, d) in enumerate(out):
        np.testing.assert_array_equal(np.asarray(x), items[i][0])
        np.testing.assert_array_equal(np.asarray(d["y"]), items[i][1]["y"])


def test_size_zero_and_short_iterables():
    assert list(prefetch_to_device(iter([]), size=2)) == []
    items = [np.arange(3)]
    [only] = list(prefetch_to_device(iter(items), size=4))  # size > len
    np.testing.assert_array_equal(np.asarray(only), items[0])
    [only0] = list(prefetch_to_device(iter(items), size=0))
    np.testing.assert_array_equal(np.asarray(only0), items[0])
    with pytest.raises(ValueError):
        list(prefetch_to_device(iter(items), size=-1))


def test_sharding_applied():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    sharding = NamedSharding(mesh, P("data"))
    items = [np.arange(8, dtype=np.float32) + i for i in range(3)]
    out = list(prefetch_to_device(iter(items), size=2, sharding=sharding))
    for i, a in enumerate(out):
        assert a.sharding == sharding
        np.testing.assert_array_equal(np.asarray(a), items[i])
