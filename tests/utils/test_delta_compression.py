"""int8 delta compression: bounded per-push error, unbiased under error
feedback, and transparent on both parameter-server wires."""
import numpy as np
import pytest

from elephas_tpu.utils.delta_compression import (ErrorFeedback,
                                                 dequantize_delta,
                                                 quantize_delta)


def test_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    delta = [rng.normal(size=(32, 16)).astype(np.float32) * 0.01,
             rng.normal(size=(16,)).astype(np.float32),
             np.zeros((4, 4), np.float32)]
    wire = quantize_delta(delta)
    assert len(wire) == 6
    assert wire[0].dtype == np.int8 and wire[1].dtype == np.float32
    back = dequantize_delta(wire)
    for d, b in zip(delta, back):
        amax = np.abs(d).max()
        assert np.abs(d - b).max() <= amax / 127.0 + 1e-9
    # wire bytes ~4x smaller than float32
    raw = sum(d.nbytes for d in delta)
    compressed = sum(w.nbytes for w in wire)
    assert compressed < raw / 3.5


def test_dequantize_rejects_odd_frames():
    with pytest.raises(ValueError, match="pairs"):
        dequantize_delta([np.zeros((2,), np.int8)])


def test_error_feedback_is_unbiased():
    """Sum of what the server applies tracks the sum of raw deltas to
    within one residual — rounding never accumulates."""
    rng = np.random.default_rng(1)
    ef = ErrorFeedback()
    raw_sum = np.zeros((8, 8), np.float32)
    applied_sum = np.zeros((8, 8), np.float32)
    for _ in range(50):
        d = rng.normal(size=(8, 8)).astype(np.float32) * 0.003
        raw_sum += d
        ef.apply([d])
        applied_sum += ef.last_on_wire[0]
    # bound: the outstanding residual of ONE push
    bound = np.abs(raw_sum - applied_sum).max()
    per_push = 0.003 * 3 / 127.0  # ~amax/127 of one push
    assert bound <= per_push * 2, (bound, per_push)


def test_wire_transparency_both_transports():
    """A compressing client against each real server: the server's
    weights move by the dequantized delta; an uncompressed client
    coexists on the same server."""
    import socket as socket_mod

    from elephas_tpu.models import SGD, Dense, Sequential
    from elephas_tpu.parameter.client import HttpClient, SocketClient
    from elephas_tpu.parameter.factory import get_transport
    from elephas_tpu.utils.serialization import model_to_dict

    model = Sequential([Dense(4, input_dim=3), Dense(2)])
    model.build()
    model.compile(SGD(learning_rate=0.1), "mse", seed=0)
    rng = np.random.default_rng(2)

    for name, port in (("socket", 15731), ("http", 15732)):
        transport = get_transport(name)
        server = transport.create_server(model_to_dict(model), port,
                                         "asynchronous")
        server.start()
        try:
            cli = transport.create_client(port, compression="int8")
            assert cli.compression == "int8"
            w0 = cli.get_parameters()
            delta = [rng.normal(size=w.shape).astype(np.float32) * 0.01
                     for w in w0]
            cli.update_parameters(delta)
            w1 = cli.get_parameters()
            expect = dequantize_delta(quantize_delta(delta))
            for a, b, d in zip(w0, w1, expect):
                np.testing.assert_allclose(a - b, d, atol=1e-6)
            # plain client against the same server still works
            plain = transport.create_client(port)
            plain.update_parameters(delta)
            w2 = plain.get_parameters()
            for b, c, d in zip(w1, w2, delta):
                np.testing.assert_allclose(b - c, d, atol=1e-6)
        finally:
            server.stop()


def test_async_fit_with_compression_converges():
    """Product path: TPUModel(delta_compression='int8') trains through
    the socket PS and holds the evaluate parity oracle."""
    from elephas_tpu.models import SGD, Dense, Sequential
    from elephas_tpu.tpu_model import TPUModel
    from elephas_tpu.utils.dataset_utils import to_dataset

    rng = np.random.default_rng(3)
    x = rng.normal(size=(512, 16)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[(x.sum(1) > 0).astype(int)]
    m = Sequential([Dense(16, input_dim=16, activation="relu"),
                    Dense(2, activation="softmax")])
    m.compile(SGD(learning_rate=0.05), "categorical_crossentropy",
              ["acc"], seed=0)
    tm = TPUModel(m, mode="asynchronous", frequency="batch",
                  parameter_server_mode="socket", num_workers=2,
                  port=15733, delta_compression="int8")
    tm.fit(to_dataset(x, y), epochs=4, batch_size=32,
           validation_split=0.0, verbose=0)
    ev = tm.evaluate(x, y)
    ref = tm.master_network.evaluate(x, y)
    assert abs(ev[0] - ref[0]) < 0.01
    assert ev[-1] > 0.8, ev

    with pytest.raises(ValueError, match="delta_compression"):
        TPUModel(m, mode="asynchronous", delta_compression="zip",
                 port=15734)


def test_client_rejects_unknown_compression():
    from elephas_tpu.parameter.client import HttpClient, SocketClient

    with pytest.raises(ValueError, match="compression"):
        SocketClient(15740, compression="INT8")
    with pytest.raises(ValueError, match="compression"):
        HttpClient(15741, compression="fp16")
