"""Object-store adapter tests: the gs:// scheme (Cloud TPU's `hadoop fs`
analog, VERDICT r2 item 5) through a local-filesystem fake, end to end:
TPUModel.save/load round-trip and CheckpointManager remote mirroring."""
import json

import numpy as np
import pytest

from elephas_tpu.utils.storage import (CliObjectStore, LocalMirrorStore,
                                       get_store, is_remote, register_store,
                                       split_scheme)


@pytest.fixture
def gs_fake(tmp_path):
    store = LocalMirrorStore(tmp_path / "fake_gcs")
    register_store("gs", store)
    yield store
    register_store("gs", None)


def test_split_scheme_and_is_remote():
    assert split_scheme("gs://bucket/a/b.h5") == ("gs", "bucket/a/b.h5")
    assert split_scheme("/local/path.h5") == (None, "/local/path.h5")
    assert is_remote("gs://b/k") and is_remote("s3://b/k")
    assert not is_remote("model.h5") and not is_remote("file:///x.h5")


def test_registry_prefers_registered_store(gs_fake):
    assert get_store("gs://bucket/x") is gs_fake
    assert isinstance(get_store("s3://bucket/x"), CliObjectStore)
    with pytest.raises(ValueError):
        get_store("/plain/path")


def test_store_file_and_dir_round_trip(gs_fake, tmp_path):
    src = tmp_path / "src.txt"
    src.write_text("payload")
    gs_fake.put_file(str(src), "gs://bucket/dir/src.txt")
    assert gs_fake.exists("gs://bucket/dir/src.txt")
    dest = tmp_path / "dest.txt"
    gs_fake.get_file("gs://bucket/dir/src.txt", str(dest))
    assert dest.read_text() == "payload"

    d = tmp_path / "tree"
    (d / "sub").mkdir(parents=True)
    (d / "a.bin").write_bytes(b"\x00\x01")
    (d / "sub" / "b.bin").write_bytes(b"\x02")
    gs_fake.put_dir(str(d), "gs://bucket/ckpt/step_1")
    out = tmp_path / "tree_out"
    gs_fake.get_dir("gs://bucket/ckpt/step_1", str(out))
    assert (out / "a.bin").read_bytes() == b"\x00\x01"
    assert (out / "sub" / "b.bin").read_bytes() == b"\x02"
    gs_fake.delete("gs://bucket/ckpt/step_1", recursive=True)
    assert not gs_fake.exists("gs://bucket/ckpt/step_1")


def test_tpu_model_save_load_through_gcs(gs_fake, classification_model):
    from elephas_tpu.tpu_model import TPUModel, load_tpu_model

    classification_model.compile("sgd", "categorical_crossentropy",
                                 seed=0)
    tpu_model = TPUModel(classification_model, mode="synchronous",
                         num_workers=2)
    url = "gs://models/run1/model.h5"
    tpu_model.save(url)
    assert gs_fake.exists(url)
    # no overwrite without the flag
    with pytest.raises(FileExistsError):
        tpu_model.save(url)
    tpu_model.save(url, overwrite=True)

    loaded = load_tpu_model(url)
    assert loaded.mode == "synchronous"
    x = np.random.default_rng(0).random((8, 784), dtype=np.float32)
    np.testing.assert_allclose(loaded.master_network.predict(x),
                               classification_model.predict(x), atol=1e-5)


def test_checkpoint_manager_remote_round_trip(gs_fake):
    from elephas_tpu.utils.checkpoint import CheckpointManager

    url = "gs://ckpts/run7"
    mgr = CheckpointManager(url, max_to_keep=2)
    state1 = {"params": {"w": np.arange(6, dtype=np.float32)},
              "step": np.asarray(1)}
    mgr.save(1, state1, model_json='{"arch": 1}',
             distributed_config={"mode": "synchronous"})
    mgr.save(2, {"params": {"w": np.arange(6, dtype=np.float32) * 2},
                 "step": np.asarray(2)})
    mgr.save(3, {"params": {"w": np.arange(6, dtype=np.float32) * 3},
                 "step": np.asarray(3)})

    # remote manifest lists the kept steps; gc pruned step 1 remotely
    manifest = json.loads(gs_fake.read_text(f"{url}/manifest.json"))
    assert manifest["latest_step"] == 3
    assert manifest["steps"] == [2, 3]
    assert manifest["distributed_config"] == {"mode": "synchronous"}
    assert not gs_fake.exists(f"{url}/step_1")

    # a FRESH manager (new process, empty staging dir) restores from the
    # remote alone
    mgr2 = CheckpointManager(url)
    assert mgr2.latest_step() == 3
    template = {"params": {"w": np.zeros(6, dtype=np.float32)},
                "step": np.asarray(0)}
    restored = mgr2.restore(template=template)
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]),
        np.arange(6, dtype=np.float32) * 3)
    restored2 = mgr2.restore(step=2, template=template)
    np.testing.assert_array_equal(
        np.asarray(restored2["params"]["w"]),
        np.arange(6, dtype=np.float32) * 2)
