"""FaultPlan / fault_site: the deterministic chaos layer itself."""
import time

import pytest

from elephas_tpu.utils.faults import (ENV_VAR, FaultEvent, FaultPlan,
                                      InjectedFault, active_plan, clear_plan,
                                      fault_site, install_plan)


@pytest.fixture(autouse=True)
def _clean_plan(monkeypatch):
    """Fault state is process-global: every test starts and ends clean."""
    monkeypatch.delenv(ENV_VAR, raising=False)
    clear_plan()
    yield
    clear_plan()


def test_no_plan_is_a_noop():
    assert active_plan() is None
    assert fault_site("anything") is False


def test_event_window_after_and_times():
    plan = FaultPlan([{"site": "s", "action": "drop", "after": 2,
                       "times": 2}])
    install_plan(plan)
    hits = [fault_site("s") for _ in range(6)]
    assert hits == [False, False, True, True, False, False]
    assert plan.hits("s") == 6
    assert plan.fired() == [("s", 2, "drop"), ("s", 3, "drop")]


def test_times_none_fires_forever():
    install_plan(FaultPlan([{"site": "s", "action": "drop", "after": 1,
                             "times": None}]))
    assert [fault_site("s") for _ in range(4)] == [False, True, True, True]


def test_error_raises_injected_fault_as_connection_error():
    install_plan(FaultPlan([{"site": "s", "action": "error",
                             "message": "boom"}]))
    with pytest.raises(InjectedFault, match="boom") as exc:
        fault_site("s")
    # the retry machinery must see it as a transient transport failure
    assert isinstance(exc.value, ConnectionError)
    assert fault_site("s") is False  # times=1: second hit is clean


def test_delay_sleeps_then_continues():
    install_plan(FaultPlan([{"site": "s", "action": "delay",
                             "delay": 0.15}]))
    t0 = time.monotonic()
    assert fault_site("s") is False
    assert time.monotonic() - t0 >= 0.12


def test_sites_count_independently():
    plan = FaultPlan([{"site": "a", "action": "drop", "after": 1}])
    install_plan(plan)
    assert fault_site("b") is False  # does not advance site a's window
    assert fault_site("a") is False
    assert fault_site("a") is True


def test_json_round_trip():
    plan = FaultPlan([FaultEvent("x", "delay", after=3, times=None,
                                 delay=0.5),
                      FaultEvent("y", "error", message="m", p=0.25)],
                     seed=7)
    clone = FaultPlan.from_json(plan.to_json())
    assert clone.seed == 7
    assert [e.to_dict() for e in clone.events] == \
        [e.to_dict() for e in plan.events]


def test_seeded_probabilistic_events_are_reproducible():
    def pattern(seed):
        plan = FaultPlan([{"site": "s", "action": "drop", "times": None,
                           "p": 0.5}], seed=seed)
        install_plan(plan)
        return [fault_site("s") for _ in range(64)]

    a, b = pattern(3), pattern(3)
    assert a == b, "same seed must inject the same fault sequence"
    assert any(a) and not all(a), "p=0.5 should fire some but not all"
    assert pattern(4) != a, "a different seed should differ (p=0.5, 64 hits)"


def test_env_var_inline_json(monkeypatch):
    plan = FaultPlan([{"site": "s", "action": "drop"}])
    monkeypatch.setenv(ENV_VAR, plan.to_json())
    clear_plan()  # force a reload from the env
    assert fault_site("s") is True
    assert fault_site("s") is False


def test_env_var_file_path(monkeypatch, tmp_path):
    f = tmp_path / "plan.json"
    f.write_text(FaultPlan([{"site": "s", "action": "error"}]).to_json())
    monkeypatch.setenv(ENV_VAR, str(f))
    clear_plan()
    with pytest.raises(InjectedFault):
        fault_site("s")


def test_install_none_disables_even_with_env(monkeypatch):
    monkeypatch.setenv(ENV_VAR, FaultPlan([{"site": "s",
                                            "action": "drop"}]).to_json())
    install_plan(None)  # explicit install wins over the environment
    assert fault_site("s") is False


def test_invalid_action_rejected():
    with pytest.raises(ValueError, match="action"):
        FaultEvent("s", "explode")
