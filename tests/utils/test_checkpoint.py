import json
import numpy as np
import pytest

from elephas_tpu.utils.checkpoint import CheckpointManager


def _state(value):
    return {"params": {"dense": {"kernel": np.full((4, 4), value),
                                 "bias": np.zeros(4)}},
            "step_scalar": np.array(value)}


def test_save_restore_round_trip(tmp_path):
    manager = CheckpointManager(str(tmp_path / "ckpt"))
    manager.save(1, _state(1.0), model_json='{"class_name": "Sequential"}',
                 distributed_config={"mode": "synchronous"})
    restored = manager.restore()
    np.testing.assert_allclose(restored["params"]["dense"]["kernel"],
                               np.full((4, 4), 1.0))
    manifest = manager.manifest()
    assert manifest["latest_step"] == 1
    assert manifest["distributed_config"]["mode"] == "synchronous"


def test_multiple_steps_and_gc(tmp_path):
    manager = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    for step in (1, 2, 3):
        manager.save(step, _state(float(step)))
    assert manager.steps() == [2, 3]
    assert manager.latest_step() == 3
    restored = manager.restore(2)
    np.testing.assert_allclose(restored["step_scalar"], 2.0)


def test_resume_latest(tmp_path):
    manager = CheckpointManager(str(tmp_path / "ckpt"))
    manager.save(5, _state(5.0))
    manager.save(9, _state(9.0))
    fresh = CheckpointManager(str(tmp_path / "ckpt"))
    restored = fresh.restore()
    np.testing.assert_allclose(restored["step_scalar"], 9.0)


def test_training_state_resume_semantics(tmp_path):
    """Checkpoint params+opt_state mid-training, resume, verify identical
    continuation."""
    import jax
    import optax

    from elephas_tpu.models import Dense, Sequential

    model = Sequential([Dense(4, input_dim=3), Dense(1)])
    model.compile("sgd", "mse", seed=0)
    x = np.random.default_rng(0).random((32, 3), dtype=np.float32)
    y = np.random.default_rng(1).random((32,), dtype=np.float32)
    model.fit(x, y, epochs=1, batch_size=8, shuffle=False)

    manager = CheckpointManager(str(tmp_path / "train"))
    trainable, state = model._split_params(model.params)
    manager.save(1, {"trainable": jax.device_get(trainable)},
                 model_json=model.to_json())

    restored = manager.restore()
    flat_a = jax.tree_util.tree_leaves(restored["trainable"])
    flat_b = jax.tree_util.tree_leaves(jax.device_get(trainable))
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b)


def test_sharded_checkpoint_roundtrip_and_reshard(tmp_path):
    """Save TP-sharded transformer params, restore directly into device
    shards via the abstract_params template — including onto a DIFFERENT
    mesh topology than the one that saved (elastic resharding)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding

    from elephas_tpu.models.transformer import (TransformerConfig,
                                                abstract_params, init_params,
                                                shard_params)

    config = TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                               d_model=32, d_ff=64, max_seq_len=32,
                               dtype=jnp.float32)
    mesh_a = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "model"))
    params = shard_params(init_params(config, jax.random.PRNGKey(0)),
                          config, mesh_a)
    manager = CheckpointManager(str(tmp_path / "sharded"))
    manager.save(3, {"params": params})

    # restore onto a transposed topology (2-way data, 4-way model) with
    # FSDP sharding on top — the template dictates the target layout
    mesh_b = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "model"))
    template = {"params": abstract_params(config, mesh_b,
                                          fsdp_axis="data")}
    restored = manager.restore(template=template)["params"]

    ref = jax.device_get(params)
    got = jax.device_get(restored)
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ref)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the restored leaves actually live sharded per the new mesh
    wq = restored["layer_0"]["attn"]["wq"]
    assert isinstance(wq.sharding, NamedSharding)
    assert wq.sharding.mesh.shape["model"] == 4
    assert wq.addressable_shards[0].data.shape[1] == 1  # 4 heads / 4-way


def test_abstract_params_matches_init_shapes():
    import jax
    import jax.numpy as jnp

    from elephas_tpu.models.transformer import (TransformerConfig,
                                                abstract_params, init_params)

    config = TransformerConfig(vocab_size=32, num_layers=1, num_heads=2,
                               d_model=16, d_ff=32, max_seq_len=16,
                               dtype=jnp.float32)
    shapes = abstract_params(config)
    real = init_params(config, jax.random.PRNGKey(0))
    jax.tree_util.tree_map(
        lambda s, p: (s.shape, s.dtype) == (p.shape, p.dtype) or
        (_ for _ in ()).throw(AssertionError((s, p.shape))), shapes, real)


def test_functional_config_manifest_roundtrip(tmp_path):
    """ViT / BERT / Transformer configs round-trip through the checkpoint
    manifest, so a functional-family training run resumes from directory
    + manifest alone."""
    import jax
    import jax.numpy as jnp

    from elephas_tpu.models.bert import BertConfig
    from elephas_tpu.models.saving import config_from_dict, config_to_dict
    from elephas_tpu.models.transformer import TransformerConfig
    from elephas_tpu.models.vit import ViTConfig, init_params

    configs = [
        TransformerConfig(vocab_size=64, num_layers=2, num_heads=4,
                          d_model=32, d_ff=64, max_seq_len=32,
                          num_kv_heads=2, positional="rope",
                          loss_vocab_chunk=16),
        ViTConfig(image_size=16, patch_size=4, num_layers=1, num_heads=2,
                  d_model=16, d_ff=32, pool="mean"),
        BertConfig(vocab_size=64, num_layers=1, num_heads=2, d_model=16,
                   d_ff=32, max_seq_len=16, max_predictions=4),
    ]
    for config in configs:
        rt = config_from_dict(json.loads(json.dumps(
            config_to_dict(config))))
        assert rt == config, type(config).__name__

    # end to end: save a ViT with its config in the manifest, restore
    config = configs[1]
    params = init_params(config, jax.random.PRNGKey(0))
    manager = CheckpointManager(str(tmp_path / "vit"))
    manager.save(1, {"params": params},
                 distributed_config={"model_config": config_to_dict(config)})
    fresh = CheckpointManager(str(tmp_path / "vit"))
    manifest = fresh.manifest()
    restored_config = config_from_dict(
        manifest["distributed_config"]["model_config"])
    assert restored_config == config
    restored = fresh.restore()["params"]
    for a, b in zip(jax.tree_util.tree_leaves(restored),
                    jax.tree_util.tree_leaves(jax.device_get(params))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ----------------------------------------------------------- async saves

def test_async_save_restore_parity(tmp_path):
    """block=False must produce a checkpoint identical to a blocking save,
    and the snapshot must be stable against the caller mutating (or
    donating) its buffers right after save() returns."""
    manager = CheckpointManager(str(tmp_path / "ckpt"))
    state = _state(3.0)
    manager.save(1, state, block=False)
    state["params"]["dense"]["kernel"][:] = -999.0  # donation stand-in
    manager.wait_until_finished()
    restored = manager.restore(1)
    np.testing.assert_allclose(restored["params"]["dense"]["kernel"],
                               np.full((4, 4), 3.0))


def test_async_saves_queue_in_order_with_gc(tmp_path):
    manager = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    for step in (1, 2, 3, 4):
        manager.save(step, _state(float(step)), block=False)
    assert manager.steps() == [3, 4]          # waits, then reads manifest
    assert manager.latest_step() == 4
    np.testing.assert_allclose(manager.restore(3)["step_scalar"], 3.0)


def test_async_then_blocking_save_ordering(tmp_path):
    """A blocking save issued while async writes are queued must land
    after them (manifest log order = issue order)."""
    manager = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=10)
    manager.save(1, _state(1.0), block=False)
    manager.save(2, _state(2.0), block=False)
    manager.save(3, _state(3.0))              # blocking
    assert manager.steps() == [1, 2, 3]
    assert manager.latest_step() == 3


def test_async_save_error_propagates(tmp_path):
    import pytest

    manager = CheckpointManager(str(tmp_path / "ckpt"))

    class _Boom:
        def save(self, *a, **k):
            raise RuntimeError("disk full")

        def wait_until_finished(self):
            pass

    manager._checkpointer = _Boom()
    manager.save(1, _state(1.0), block=False)
    with pytest.raises(RuntimeError, match="disk full"):
        manager.wait_until_finished()
    # the failure is consumed: the manager is usable again afterwards
    manager._checkpointer = None  # npz fallback path
    manager.save(2, _state(2.0), block=False)
    np.testing.assert_allclose(manager.restore(2)["step_scalar"], 2.0)


def test_async_save_jax_arrays(tmp_path):
    import jax.numpy as jnp

    manager = CheckpointManager(str(tmp_path / "ckpt"))
    state = {"w": jnp.arange(8, dtype=jnp.float32),
             "nested": {"b": jnp.ones((2, 2), jnp.bfloat16)}}
    manager.save(7, state, block=False)
    restored = manager.restore(7)
    np.testing.assert_allclose(np.asarray(restored["w"]),
                               np.arange(8, dtype=np.float32))
    assert np.asarray(restored["nested"]["b"]).dtype == jnp.bfloat16


# ----------------------------------------------------------- preemption

def test_preemption_handler_checkpoints_and_exits(tmp_path):
    """SIGTERM (the Cloud TPU eviction notice) triggers one blocking
    checkpoint of the CURRENT state plus a manifest marker, then
    SystemExit(143)."""
    import os
    import signal

    import pytest

    from elephas_tpu.utils.checkpoint import install_preemption_checkpoint

    manager = CheckpointManager(str(tmp_path / "pre_ck"))
    current = {"step": 3}
    uninstall = install_preemption_checkpoint(
        manager, lambda: (current["step"], _state(float(current["step"]))))
    try:
        current["step"] = 7      # state advances after install
        with pytest.raises(SystemExit) as exc:
            os.kill(os.getpid(), signal.SIGTERM)
        assert exc.value.code == 143
    finally:
        uninstall()
    fresh = CheckpointManager(str(tmp_path / "pre_ck"))
    assert fresh.latest_step() == 7
    np.testing.assert_allclose(fresh.restore()["step_scalar"], 7.0)
    m = fresh.manifest()
    assert m["preempted"] is True and m["preempted_step"] == 7
    # handler restored: a second SIGTERM must use the default disposition
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL


def test_preemption_uninstall_restores_handler(tmp_path):
    import signal

    from elephas_tpu.utils.checkpoint import install_preemption_checkpoint

    before = signal.getsignal(signal.SIGTERM)
    manager = CheckpointManager(str(tmp_path / "pre_ck2"))
    uninstall = install_preemption_checkpoint(manager,
                                              lambda: (0, _state(0.0)))
    assert signal.getsignal(signal.SIGTERM) != before
    uninstall()
    assert signal.getsignal(signal.SIGTERM) == before
    assert manager.latest_step() is None   # nothing written without a signal


def test_out_of_order_write_cannot_regress_latest(tmp_path):
    """ADVICE r3 (preemption race): if an older queued write lands after
    the handler's final write, the manifest's resume point must not move
    backwards — latest_step is monotonic; the steps list keeps both."""
    from elephas_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(tmp_path / "ckpt", max_to_keep=5)
    state5 = {"w": np.arange(3.0)}
    mgr.save(5, state5)                      # the "final" write (seq 0)
    # late older write carrying an EARLIER save sequence (the straggler
    # the handler's wait_until_finished missed)
    mgr._write(3, {"w": np.zeros(3)}, None, None, seq=-5)
    assert mgr.latest_step() == 5
    assert mgr.steps() == [3, 5]
    np.testing.assert_array_equal(mgr.restore()["w"], state5["w"])


def test_check_error_reraises_background_writer_failure(tmp_path):
    """A failed ASYNC save must not vanish: the next save() re-raises
    it (via check_error), the failure is consumed exactly once, and
    later saves proceed cleanly."""
    import time

    manager = CheckpointManager(str(tmp_path / "ckpt"))
    orig_write = manager._write

    def failing_write(*args, **kwargs):
        raise RuntimeError("disk full (injected)")

    manager._write = failing_write
    manager.save(1, _state(1.0), block=False)
    # wait for the background future to complete (with its failure)
    deadline = time.time() + 30
    while time.time() < deadline:
        with manager._pending_lock:
            if manager._pending and all(f.done()
                                        for f in manager._pending):
                break
        time.sleep(0.01)
    manager._write = orig_write
    with pytest.raises(RuntimeError, match="disk full"):
        manager.save(2, _state(2.0), block=False)   # check_error path
    # consumed once: the next save is clean and the manager still works
    manager.save(3, _state(3.0), block=False)
    manager.wait_until_finished()
    assert manager.latest_step() == 3
    np.testing.assert_allclose(manager.restore()["step_scalar"], 3.0)


def test_wait_until_finished_reraises_background_writer_failure(tmp_path):
    """wait_until_finished() flushes every queued async write and then
    re-raises the first failure — a blocking save() (which flushes
    first) surfaces it the same way instead of swallowing it."""
    manager = CheckpointManager(str(tmp_path / "ckpt"))
    orig_write = manager._write

    def failing_write(*args, **kwargs):
        raise RuntimeError("writer exploded (injected)")

    manager._write = failing_write
    manager.save(1, _state(1.0), block=False)
    manager._write = orig_write
    with pytest.raises(RuntimeError, match="writer exploded"):
        manager.wait_until_finished()
    # the flush completed despite the failure: nothing is stranded and
    # a subsequent blocking save lands normally
    manager.save(2, _state(2.0))
    assert manager.latest_step() == 2


def test_rollback_save_moves_latest_backwards(tmp_path):
    """The straggler guard must NOT break deliberate rollback: restore
    an older step, keep training, save a smaller step — that save is
    the newest by request order, so it owns the resume point."""
    from elephas_tpu.utils.checkpoint import CheckpointManager

    mgr = CheckpointManager(tmp_path / "ckpt", max_to_keep=5)
    mgr.save(10, {"w": np.full(3, 10.0)})
    mgr.restore(step=10)
    state6 = {"w": np.full(3, 6.0)}
    mgr.save(6, state6)                       # post-rollback run
    assert mgr.latest_step() == 6
    np.testing.assert_array_equal(mgr.restore()["w"], state6["w"])
