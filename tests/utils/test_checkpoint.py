import numpy as np

from elephas_tpu.utils.checkpoint import CheckpointManager


def _state(value):
    return {"params": {"dense": {"kernel": np.full((4, 4), value),
                                 "bias": np.zeros(4)}},
            "step_scalar": np.array(value)}


def test_save_restore_round_trip(tmp_path):
    manager = CheckpointManager(str(tmp_path / "ckpt"))
    manager.save(1, _state(1.0), model_json='{"class_name": "Sequential"}',
                 distributed_config={"mode": "synchronous"})
    restored = manager.restore()
    np.testing.assert_allclose(restored["params"]["dense"]["kernel"],
                               np.full((4, 4), 1.0))
    manifest = manager.manifest()
    assert manifest["latest_step"] == 1
    assert manifest["distributed_config"]["mode"] == "synchronous"


def test_multiple_steps_and_gc(tmp_path):
    manager = CheckpointManager(str(tmp_path / "ckpt"), max_to_keep=2)
    for step in (1, 2, 3):
        manager.save(step, _state(float(step)))
    assert manager.steps() == [2, 3]
    assert manager.latest_step() == 3
    restored = manager.restore(2)
    np.testing.assert_allclose(restored["step_scalar"], 2.0)


def test_resume_latest(tmp_path):
    manager = CheckpointManager(str(tmp_path / "ckpt"))
    manager.save(5, _state(5.0))
    manager.save(9, _state(9.0))
    fresh = CheckpointManager(str(tmp_path / "ckpt"))
    restored = fresh.restore()
    np.testing.assert_allclose(restored["step_scalar"], 9.0)


def test_training_state_resume_semantics(tmp_path):
    """Checkpoint params+opt_state mid-training, resume, verify identical
    continuation."""
    import jax
    import optax

    from elephas_tpu.models import Dense, Sequential

    model = Sequential([Dense(4, input_dim=3), Dense(1)])
    model.compile("sgd", "mse", seed=0)
    x = np.random.default_rng(0).random((32, 3), dtype=np.float32)
    y = np.random.default_rng(1).random((32,), dtype=np.float32)
    model.fit(x, y, epochs=1, batch_size=8, shuffle=False)

    manager = CheckpointManager(str(tmp_path / "train"))
    trainable, state = model._split_params(model.params)
    manager.save(1, {"trainable": jax.device_get(trainable)},
                 model_json=model.to_json())

    restored = manager.restore()
    flat_a = jax.tree_util.tree_leaves(restored["trainable"])
    flat_b = jax.tree_util.tree_leaves(jax.device_get(trainable))
    for a, b in zip(flat_a, flat_b):
        np.testing.assert_allclose(a, b)
