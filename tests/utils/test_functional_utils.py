import numpy as np

from elephas_tpu.utils import functional_utils


def test_add_params():
    pairs = [(np.ones((4, 2)), np.ones((4, 2))), (np.ones(3), 2 * np.ones(3))]
    left = [p[0] for p in pairs]
    right = [p[1] for p in pairs]
    out = functional_utils.add_params(left, right)
    assert np.array_equal(out[0], 2 * np.ones((4, 2)))
    assert np.array_equal(out[1], 3 * np.ones(3))


def test_subtract_params():
    left = [3 * np.ones((2, 2))]
    right = [np.ones((2, 2))]
    out = functional_utils.subtract_params(left, right)
    assert np.array_equal(out[0], 2 * np.ones((2, 2)))


def test_get_neutral():
    out = functional_utils.get_neutral([np.ones((3, 3)), np.ones(5)])
    assert np.array_equal(out[0], np.zeros((3, 3)))
    assert np.array_equal(out[1], np.zeros(5))


def test_divide_by():
    out = functional_utils.divide_by([4 * np.ones(4)], 4)
    assert np.array_equal(out[0], np.ones(4))


def test_tree_ops():
    tree_a = {"layer": {"kernel": np.ones((2, 2)), "bias": np.ones(2)}}
    tree_b = {"layer": {"kernel": np.ones((2, 2)), "bias": 3 * np.ones(2)}}
    summed = functional_utils.tree_add(tree_a, tree_b)
    assert np.array_equal(np.asarray(summed["layer"]["bias"]), 4 * np.ones(2))
    diff = functional_utils.tree_subtract(tree_b, tree_a)
    assert np.array_equal(np.asarray(diff["layer"]["bias"]), 2 * np.ones(2))
    halved = functional_utils.tree_divide(tree_b, 2)
    assert np.array_equal(np.asarray(halved["layer"]["bias"]), 1.5 * np.ones(2))
    zeros = functional_utils.tree_zeros_like(tree_a)
    assert np.array_equal(np.asarray(zeros["layer"]["kernel"]), np.zeros((2, 2)))
