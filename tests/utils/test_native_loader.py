"""Native prefetching batch loader tests (C++ loader in
``native/etpu_loader.cpp`` via :mod:`elephas_tpu.utils.native`)."""
import numpy as np
import pytest

from elephas_tpu.utils import native


@pytest.fixture(scope="module")
def built():
    if not native.build():
        pytest.skip("native toolchain unavailable")
    if not native.available():
        pytest.skip("libetpu.so not built")


def _data(n=37, dim=5):
    x = np.arange(n * dim, dtype=np.float32).reshape(n, dim)
    y = np.arange(n, dtype=np.int32)
    return x, y


def test_batches_match_numpy_gather(built):
    x, y = _data()
    order = np.random.default_rng(1).permutation(len(x))
    got = list(native.batch_iterator((x, y), order, 8))
    assert [b[0].shape[0] for b in got] == [8, 8, 8, 8, 5]
    np.testing.assert_array_equal(np.concatenate([b[0] for b in got]),
                                  x[order])
    np.testing.assert_array_equal(np.concatenate([b[1] for b in got]),
                                  y[order])
    assert got[0][0].dtype == np.float32 and got[0][1].dtype == np.int32


def test_exact_multiple_and_single_batch(built):
    x, y = _data(n=16)
    got = list(native.batch_iterator((x, y), np.arange(16), 8))
    assert len(got) == 2
    got = list(native.batch_iterator((x, y), np.arange(16), 32))
    assert len(got) == 1 and got[0][0].shape[0] == 16


def test_empty_order_yields_nothing(built):
    x, y = _data(n=4)
    assert list(native.batch_iterator((x, y), np.array([], dtype=np.int64),
                                      8)) == []


def test_zero_copy_views_reuse_ring(built):
    x, y = _data(n=40)
    loader = native.NativeBatchLoader((x, y), np.arange(40, dtype=np.uint64),
                                      4, depth=2, copy=False)
    rows = []
    for xb, _ in loader:
        rows.append(xb.copy())  # must copy before the next iteration
    np.testing.assert_array_equal(np.concatenate(rows), x)


def test_loader_feeds_model_fit(built):
    """End-to-end: the fit loop consumes the native loader transparently."""
    from elephas_tpu.models import SGD, Dense, Sequential

    rng = np.random.default_rng(0)
    x = rng.random((96, 8), dtype=np.float32)
    w = rng.random((8, 1), dtype=np.float32)
    y = (x @ w).astype(np.float32)
    model = Sequential([Dense(8, input_dim=8, activation="relu"), Dense(1)])
    model.compile(SGD(learning_rate=0.05), "mse", seed=0)
    history = model.fit(x, y, epochs=12, batch_size=16, verbose=0)
    losses = history.history["loss"]
    assert losses[-1] < losses[0] * 0.5
