import time

from elephas_tpu.utils.tracing import StepTimer, profiler_trace


def test_step_timer_collects_durations():
    timer = StepTimer()
    for _ in range(3):
        with timer:
            time.sleep(0.01)
    assert len(timer.durations) == 3
    assert timer.mean >= 0.01
    summary = timer.summary()
    assert summary["steps"] == 3
    assert summary["p50_s"] >= 0.01
    assert timer.samples_per_sec(64) > 0


def test_profiler_trace_noop_without_logdir():
    with profiler_trace(None):
        pass  # must not raise


def test_profiler_trace_writes(tmp_path):
    import jax
    import jax.numpy as jnp

    logdir = str(tmp_path / "trace")
    with profiler_trace(logdir):
        jnp.ones(4).sum().block_until_ready()
    import os

    assert os.path.exists(logdir)
