"""RWLock tests (the reference left these as an empty stub,
``/root/reference/tests/utils/test_rwlock.py:1``)."""
import threading
import time

from elephas_tpu.utils.rwlock import RWLock


def test_multiple_readers():
    lock = RWLock()
    acquired = []

    def reader():
        lock.acquire_read()
        acquired.append(1)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=2)
    assert len(acquired) == 4
    for _ in range(4):
        lock.release()


def test_writer_excludes_readers():
    lock = RWLock()
    lock.acquire_write()
    got_read = threading.Event()

    def reader():
        lock.acquire_read()
        got_read.set()
        lock.release()

    t = threading.Thread(target=reader)
    t.start()
    time.sleep(0.05)
    assert not got_read.is_set()
    lock.release()
    t.join(timeout=2)
    assert got_read.is_set()


def test_writer_priority_over_new_readers():
    lock = RWLock()
    lock.acquire_read()
    writer_done = threading.Event()
    reader_done = threading.Event()

    def writer():
        lock.acquire_write()
        writer_done.set()
        lock.release()

    def late_reader():
        lock.acquire_read()
        reader_done.set()
        lock.release()

    wt = threading.Thread(target=writer)
    wt.start()
    time.sleep(0.05)
    rt = threading.Thread(target=late_reader)
    rt.start()
    time.sleep(0.05)
    # neither can proceed while the first read lock is held
    assert not writer_done.is_set() and not reader_done.is_set()
    lock.release()
    wt.join(timeout=2)
    rt.join(timeout=2)
    assert writer_done.is_set() and reader_done.is_set()


def test_counter_consistency_under_contention():
    lock = RWLock()
    state = {"value": 0}

    def writer():
        for _ in range(50):
            lock.acquire_write()
            v = state["value"]
            state["value"] = v + 1
            lock.release()

    threads = [threading.Thread(target=writer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert state["value"] == 200
