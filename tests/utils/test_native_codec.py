"""Native C++ codec: byte-parity with the canonical Python implementation."""
import numpy as np
import pytest

from elephas_tpu.utils import native
from elephas_tpu.utils import tensor_codec as tc

pytestmark = pytest.mark.skipif(
    not (native.build() and native.available()),
    reason="native library not built and no compiler available")


ARRAYS = [
    np.random.default_rng(0).random((64, 32)).astype(np.float32),
    np.arange(17, dtype=np.int64),
    np.array(2.5),
    np.zeros((3, 0, 2), dtype=np.float32),
    np.array([True, False, True]),
    np.arange(6, dtype=np.int32).reshape(2, 3),
]


def test_encode_byte_identical():
    py = tc.encode_tensors(ARRAYS, tc.KIND_DELTA)
    nat = native.encode_tensors_native(ARRAYS, tc.KIND_DELTA)
    assert py == bytes(nat)


def test_decode_matches_python():
    payload = tc.encode_tensors(ARRAYS, tc.KIND_WEIGHTS)
    py_arrays, py_kind = tc.decode_tensors(payload)
    nat_arrays, nat_kind = native.decode_tensors_native(payload)
    assert py_kind == nat_kind
    for a, b in zip(py_arrays, nat_arrays):
        assert a.dtype == b.dtype
        assert np.array_equal(a, b)


def test_cross_decode():
    """Python decodes native payloads and vice versa."""
    nat_payload = bytes(native.encode_tensors_native(ARRAYS))
    py_arrays, _ = tc.decode_tensors(nat_payload)
    for a, b in zip(ARRAYS, py_arrays):
        assert np.array_equal(np.asarray(a), b)


@pytest.mark.parametrize("mutilate", [
    lambda p: b"garbage",
    lambda p: p[:8],
    lambda p: p[:12],
    lambda p: p[:-5],
    lambda p: b"XXXX" + p[4:],
])
def test_native_rejects_malformed(mutilate):
    payload = tc.encode_tensors([np.zeros((4, 4), dtype=np.float32)])
    with pytest.raises(tc.CodecError):
        native.decode_tensors_native(mutilate(payload))


def test_dispatch_prefers_native_and_round_trips():
    payload = tc.encode(ARRAYS, tc.KIND_WEIGHTS)
    arrays, kind = tc.decode(bytes(payload))
    assert kind == tc.KIND_WEIGHTS
    for a, b in zip(ARRAYS, arrays):
        assert np.array_equal(np.asarray(a), b)


def test_native_framed_sockets():
    import socket
    import threading

    server, client = socket.socketpair()
    received = {}

    def reader():
        payload = native.recv_frame_native(server.fileno())
        received["arrays"], _ = tc.decode_tensors(payload)

    t = threading.Thread(target=reader)
    t.start()
    payload = bytes(native.encode_tensors_native(ARRAYS[:2]))
    native.send_frame_native(client.fileno(), payload)
    t.join(timeout=5)
    server.close()
    client.close()
    assert np.array_equal(received["arrays"][0], ARRAYS[0])
