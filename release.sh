#!/bin/bash
# Release: build the native library, run the full CPU suite, build a wheel,
# and (with --publish) upload it. Capability mirror of the reference's
# release.sh, with the test gate the reference lacked.
set -euo pipefail
cd "$(dirname "$0")"

echo "== native build =="
bash native/build.sh

echo "== test gate (8-device virtual CPU mesh) =="
JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python -m pytest tests/ -q

echo "== wheel =="
rm -rf dist/
python -m pip wheel --no-deps -w dist .

if [[ "${1:-}" == "--publish" ]]; then
    echo "== publish =="
    python -m twine upload dist/*.whl
fi
echo "release artifacts in dist/"
