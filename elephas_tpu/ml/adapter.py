"""DataFrame bridge: pandas DataFrames <-> Datasets.

The reference bridges Spark DataFrames of (features Vector, label) rows into
RDDs (``elephas/ml/adapter.py:11-47``); the TPU framework's tabular currency
is a pandas DataFrame with a features column holding dense vectors.
"""
from typing import Optional, Tuple

import numpy as np
import pandas as pd

from ..data.dataset import Dataset
from ..mllib.linalg import DenseVector, LabeledPoint
from ..utils.dataset_utils import encode_label, from_labeled_points


def to_data_frame(features: np.ndarray, labels: np.ndarray,
                  categorical: bool = False) -> pd.DataFrame:
    """Build a ``features``/``label`` DataFrame from numpy arrays.

    One-hot labels collapse to class indices when ``categorical`` is set.
    """
    rows = []
    for x, y in zip(features, labels):
        label = float(np.argmax(y)) if categorical else float(np.asarray(y).reshape(-1)[0])
        rows.append({"features": DenseVector(np.asarray(x)), "label": label})
    return pd.DataFrame(rows)


def from_data_frame(df: pd.DataFrame, categorical: bool = False,
                    nb_classes: Optional[int] = None
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """DataFrame back to numpy feature/label arrays."""
    points = Dataset([LabeledPoint(row["label"], row["features"])
                      for _, row in df.iterrows()])
    return from_labeled_points(points, categorical, nb_classes)


def _cell_to_array(cell) -> np.ndarray:
    if isinstance(cell, DenseVector):
        return cell.toArray()
    return np.asarray(cell, dtype=np.float64)


def df_to_dataset(df: pd.DataFrame, categorical: bool = False,
                  nb_classes: Optional[int] = None,
                  features_col: str = "features",
                  label_col: str = "label") -> Dataset:
    """DataFrame into a feature/label pair Dataset (parity:
    ``df_to_simple_rdd``, ``elephas/ml/adapter.py:28-47``)."""
    features = np.stack([_cell_to_array(cell) for cell in df[features_col]])
    raw_labels = df[label_col].to_numpy()
    if categorical:
        if not nb_classes:
            nb_classes = int(np.max(raw_labels)) + 1
        labels = np.stack([encode_label(label, nb_classes)
                           for label in raw_labels])
    else:
        labels = raw_labels.astype(np.float64)
    return Dataset((features, labels))
