from .adapter import df_to_dataset, from_data_frame, to_data_frame
from .pipeline import (Estimator, Transformer, load_ml_estimator,
                       load_ml_transformer)
