"""Parameter mixin config system for the ML-pipeline layer.

A standalone analog of ``pyspark.ml.param.Params`` carrying the reference's
16-mixin surface and defaults (``elephas/ml/params.py:4-259``) — model
config, mode (default ``asynchronous``), frequency (``epoch``), nb_classes
(10), categorical (True), epochs (10), batch_size (32), verbosity (0),
validation_split (0.1), num_workers (8), optimizer config, metrics
(``['acc']``), loss, custom objects ({}), inference batch size (None), and
the features/label/output column trio — plus one TPU-native addition:
sync_mode (default ``average``; ``step`` = per-step sync SGD).
"""
from typing import Any, Dict


class Param:
    """A named, documented parameter belonging to a Params subclass."""

    def __init__(self, parent: "Params", name: str, doc: str):
        self.parent = parent
        self.name = name
        self.doc = doc

    # identity is the name: cooperative multiple-inheritance re-runs mixin
    # __init__s, and a re-created Param must keep addressing the same map slot
    def __hash__(self):
        return hash(self.name)

    def __eq__(self, other):
        return isinstance(other, Param) and other.name == self.name

    def __repr__(self):
        return f"Param({self.name})"


class Params:
    """Base class: explicit values in ``_paramMap`` shadow defaults in
    ``_defaultParamMap``."""

    def __init__(self):
        if not hasattr(self, "_paramMap"):
            self._paramMap: Dict[Param, Any] = {}
            self._defaultParamMap: Dict[Param, Any] = {}
        super().__init__()

    def _param_by_name(self, name: str) -> Param:
        for param in list(self._paramMap) + list(self._defaultParamMap):
            if param.name == name:
                return param
        for attr in vars(self).values():
            if isinstance(attr, Param) and attr.name == name:
                return attr
        raise KeyError(f"No param named {name!r}")

    def _set(self, **kwargs):
        for name, value in kwargs.items():
            self._paramMap[self._param_by_name(name)] = value
        return self

    def _setDefault(self, **kwargs):
        for name, value in kwargs.items():
            self._defaultParamMap[self._param_by_name(name)] = value
        return self

    def getOrDefault(self, param: Param):
        if param in self._paramMap:
            return self._paramMap[param]
        if param in self._defaultParamMap:
            return self._defaultParamMap[param]
        raise KeyError(f"Param {param.name!r} is not set and has no default")

    def explainParams(self) -> str:
        lines = []
        for param in sorted({*self._paramMap, *self._defaultParamMap},
                            key=lambda p: p.name):
            lines.append(f"{param.name}: {param.doc} "
                         f"(current: {self.getOrDefault(param)!r})")
        return "\n".join(lines)


class HasModelConfig(Params):
    """Mandatory: serialized model architecture as a JSON string."""

    def __init__(self):
        super().__init__()
        self.model_config = Param(self, "model_config",
                                  "Serialized model architecture JSON")

    def set_model_config(self, model_config):
        self._paramMap[self.model_config] = model_config
        return self

    def get_model_config(self):
        return self.getOrDefault(self.model_config)

    # migration aliases (reference: HasKerasModelConfig)
    set_keras_model_config = set_model_config
    get_keras_model_config = get_model_config


class HasMode(Params):
    def __init__(self):
        super().__init__()
        self.mode = Param(self, "mode", "training mode")
        self._setDefault(mode="asynchronous")

    def set_mode(self, mode):
        self._paramMap[self.mode] = mode
        return self

    def get_mode(self):
        return self.getOrDefault(self.mode)


class HasFrequency(Params):
    def __init__(self):
        super().__init__()
        self.frequency = Param(self, "frequency", "update frequency")
        self._setDefault(frequency="epoch")

    def set_frequency(self, frequency):
        self._paramMap[self.frequency] = frequency
        return self

    def get_frequency(self):
        return self.getOrDefault(self.frequency)


class HasSyncMode(Params):
    """Synchronous-mode flavor: ``average`` (reference model-averaging,
    ``elephas/spark_model.py:217-228``) or ``step`` (true per-step sync SGD,
    the TPU-native benchmark configuration)."""

    def __init__(self):
        super().__init__()
        self.sync_mode = Param(self, "sync_mode",
                               "synchronous flavor: 'average' or 'step'")
        self._setDefault(sync_mode="average")

    def _set(self, **kwargs):
        # constructor kwargs route through Params._set, not the named
        # setter — validate here so a typo fails at construction, not fit()
        if ("sync_mode" in kwargs
                and kwargs["sync_mode"] not in ("average", "step")):
            raise ValueError("sync_mode must be 'average' or 'step', got "
                             f"{kwargs['sync_mode']!r}")
        return super()._set(**kwargs)

    def set_sync_mode(self, sync_mode):
        return self._set(sync_mode=sync_mode)

    def get_sync_mode(self):
        return self.getOrDefault(self.sync_mode)


class HasSeed(Params):
    """Deterministic-run seed for weight init and data shuffling. ``None``
    (default) draws from entropy — set it for reproducible training runs
    (an upgrade over the reference, which has no seeding at all)."""

    def __init__(self):
        super().__init__()
        self.seed = Param(self, "seed", "RNG seed; None -> entropy")
        self._setDefault(seed=None)

    def set_seed(self, seed):
        return self._set(seed=seed)

    def get_seed(self):
        return self.getOrDefault(self.seed)


class HasNumberOfClasses(Params):
    def __init__(self):
        super().__init__()
        self.nb_classes = Param(self, "nb_classes", "number of classes")
        self._setDefault(nb_classes=10)

    def set_nb_classes(self, nb_classes):
        self._paramMap[self.nb_classes] = nb_classes
        return self

    def get_nb_classes(self):
        return self.getOrDefault(self.nb_classes)


class HasCategoricalLabels(Params):
    def __init__(self):
        super().__init__()
        self.categorical = Param(self, "categorical",
                                 "whether labels are categorical")
        self._setDefault(categorical=True)

    def set_categorical_labels(self, categorical):
        self._paramMap[self.categorical] = categorical
        return self

    def get_categorical_labels(self):
        return self.getOrDefault(self.categorical)


class HasEpochs(Params):
    def __init__(self):
        super().__init__()
        self.epochs = Param(self, "epochs", "number of epochs")
        self._setDefault(epochs=10)

    def set_epochs(self, epochs):
        self._paramMap[self.epochs] = epochs
        return self

    def get_epochs(self):
        return self.getOrDefault(self.epochs)


class HasBatchSize(Params):
    def __init__(self):
        super().__init__()
        self.batch_size = Param(self, "batch_size", "batch size")
        self._setDefault(batch_size=32)

    def set_batch_size(self, batch_size):
        self._paramMap[self.batch_size] = batch_size
        return self

    def get_batch_size(self):
        return self.getOrDefault(self.batch_size)


class HasVerbosity(Params):
    def __init__(self):
        super().__init__()
        self.verbose = Param(self, "verbose", "verbosity level")
        self._setDefault(verbose=0)

    def set_verbosity(self, verbose):
        self._paramMap[self.verbose] = verbose
        return self

    def get_verbosity(self):
        return self.getOrDefault(self.verbose)


class HasValidationSplit(Params):
    def __init__(self):
        super().__init__()
        self.validation_split = Param(self, "validation_split",
                                      "validation split fraction")
        self._setDefault(validation_split=0.1)

    def set_validation_split(self, validation_split):
        self._paramMap[self.validation_split] = validation_split
        return self

    def get_validation_split(self):
        return self.getOrDefault(self.validation_split)


class HasNumberOfWorkers(Params):
    def __init__(self):
        super().__init__()
        self.num_workers = Param(self, "num_workers", "number of workers")
        self._setDefault(num_workers=8)

    def set_num_workers(self, num_workers):
        self._paramMap[self.num_workers] = num_workers
        return self

    def get_num_workers(self):
        return self.getOrDefault(self.num_workers)


class HasOptimizerConfig(Params):
    def __init__(self):
        super().__init__()
        self.optimizer_config = Param(self, "optimizer_config",
                                      "serialized optimizer config")
        self._setDefault(optimizer_config=None)

    def set_optimizer_config(self, optimizer_config):
        self._paramMap[self.optimizer_config] = optimizer_config
        return self

    def get_optimizer_config(self):
        return self.getOrDefault(self.optimizer_config)


class HasMetrics(Params):
    def __init__(self):
        super().__init__()
        self.metrics = Param(self, "metrics", "training metrics")
        self._setDefault(metrics=["acc"])

    def set_metrics(self, metrics):
        self._paramMap[self.metrics] = metrics
        return self

    def get_metrics(self):
        return self.getOrDefault(self.metrics)


class HasLoss(Params):
    def __init__(self):
        super().__init__()
        self.loss = Param(self, "loss", "loss function name")

    def set_loss(self, loss):
        self._paramMap[self.loss] = loss
        return self

    def get_loss(self):
        return self.getOrDefault(self.loss)


class HasCustomObjects(Params):
    def __init__(self):
        super().__init__()
        self.custom_objects = Param(self, "custom_objects",
                                    "custom objects registry")
        self._setDefault(custom_objects={})

    def set_custom_objects(self, custom_objects):
        self._paramMap[self.custom_objects] = custom_objects
        return self

    def get_custom_objects(self):
        return self.getOrDefault(self.custom_objects)


class HasInferenceBatchSize(Params):
    def __init__(self):
        super().__init__()
        self.inference_batch_size = Param(
            self, "inference_batch_size",
            "bounded-memory batch size for transform-time inference")
        self._setDefault(inference_batch_size=None)

    def set_inference_batch_size(self, batch_size):
        self._paramMap[self.inference_batch_size] = batch_size
        return self

    def get_inference_batch_size(self):
        return self.getOrDefault(self.inference_batch_size)


class HasFeaturesCol(Params):
    def __init__(self):
        super().__init__()
        self.featuresCol = Param(self, "featuresCol", "features column name")
        self._setDefault(featuresCol="features")

    def setFeaturesCol(self, value):
        self._paramMap[self.featuresCol] = value
        return self

    def getFeaturesCol(self):
        return self.getOrDefault(self.featuresCol)


class HasLabelCol(Params):
    def __init__(self):
        super().__init__()
        self.labelCol = Param(self, "labelCol", "label column name")
        self._setDefault(labelCol="label")

    def setLabelCol(self, value):
        self._paramMap[self.labelCol] = value
        return self

    def getLabelCol(self):
        return self.getOrDefault(self.labelCol)


class HasOutputCol(Params):
    def __init__(self):
        super().__init__()
        self.outputCol = Param(self, "outputCol", "output column name")
        self._setDefault(outputCol="prediction")

    def setOutputCol(self, value):
        self._paramMap[self.outputCol] = value
        return self

    def getOutputCol(self):
        return self.getOrDefault(self.outputCol)


# migration alias for the reference's mixin name
HasKerasModelConfig = HasModelConfig
HasKerasOptimizerConfig = HasOptimizerConfig
