"""ML-pipeline layer: Estimator -> fitted Transformer over DataFrames.

Capability mirror of ``elephas/ml_model.py:25-269``: the Estimator carries
all compile/train settings as Params, ``fit(df)`` trains a distributed
:class:`~elephas_tpu.tpu_model.TPUModel` and returns a fitted Transformer
whose ``transform(df)`` appends a prediction column — a probability list
for classifiers, a scalar for regressors (decided by the loss->ModelType
mapping), with optional bounded-memory batched inference.
"""
import json
import warnings
from typing import Optional

import h5py
import numpy as np
import pandas as pd

from ..models import get_optimizer, model_from_json
from ..tpu_model import TPUModel
from ..utils.model_utils import (LossModelTypeMapper, ModelType,
                                 ModelTypeEncoder, as_enum)
from .adapter import df_to_dataset
from .params import (HasBatchSize, HasCategoricalLabels, HasCustomObjects,
                     HasEpochs, HasFeaturesCol, HasFrequency,
                     HasInferenceBatchSize, HasLabelCol, HasLoss, HasMetrics,
                     HasMode, HasModelConfig, HasNumberOfClasses,
                     HasNumberOfWorkers, HasOptimizerConfig, HasOutputCol,
                     HasSeed, HasSyncMode, HasValidationSplit, HasVerbosity)


class Estimator(HasCategoricalLabels, HasValidationSplit, HasModelConfig,
                HasFeaturesCol, HasLabelCol, HasMode, HasEpochs, HasBatchSize,
                HasFrequency, HasVerbosity, HasNumberOfClasses,
                HasNumberOfWorkers, HasOutputCol, HasLoss, HasMetrics,
                HasOptimizerConfig, HasCustomObjects, HasSyncMode, HasSeed):
    """Configurable distributed-training estimator.

    ``fit(df)`` -> trained :class:`Transformer`.
    """

    def __init__(self, **kwargs):
        # initialize every mixin exactly once
        HasCategoricalLabels.__init__(self)
        HasValidationSplit.__init__(self)
        HasModelConfig.__init__(self)
        HasFeaturesCol.__init__(self)
        HasLabelCol.__init__(self)
        HasMode.__init__(self)
        HasEpochs.__init__(self)
        HasBatchSize.__init__(self)
        HasFrequency.__init__(self)
        HasVerbosity.__init__(self)
        HasNumberOfClasses.__init__(self)
        HasNumberOfWorkers.__init__(self)
        HasOutputCol.__init__(self)
        HasLoss.__init__(self)
        HasMetrics.__init__(self)
        HasOptimizerConfig.__init__(self)
        HasCustomObjects.__init__(self)
        HasSyncMode.__init__(self)
        HasSeed.__init__(self)
        self.set_params(**kwargs)

    def set_params(self, **kwargs):
        """Set any subset of params by name."""
        return self._set(**kwargs)

    def get_config(self) -> dict:
        return {"model_config": self.get_model_config(),
                "mode": self.get_mode(),
                "frequency": self.get_frequency(),
                "num_workers": self.get_num_workers(),
                "categorical": self.get_categorical_labels(),
                "loss": self.get_loss(),
                "metrics": self.get_metrics(),
                "validation_split": self.get_validation_split(),
                "featuresCol": self.getFeaturesCol(),
                "labelCol": self.getLabelCol(),
                "epochs": self.get_epochs(),
                "batch_size": self.get_batch_size(),
                "verbose": self.get_verbosity(),
                "nb_classes": self.get_nb_classes(),
                "outputCol": self.getOutputCol(),
                "sync_mode": self.get_sync_mode(),
                "seed": self.get_seed()}

    def save(self, file_name: str):
        with h5py.File(file_name, mode="w") as f:
            f.attrs["distributed_config"] = json.dumps({
                "class_name": self.__class__.__name__,
                "config": self.get_config(),
            }).encode("utf8")

    def get_model(self):
        return model_from_json(self.get_model_config(),
                               self.get_custom_objects())

    def fit(self, df: pd.DataFrame) -> "Transformer":
        """Train on a features/label DataFrame; return a fitted Transformer."""
        dataset = df_to_dataset(df, categorical=self.get_categorical_labels(),
                                nb_classes=self.get_nb_classes(),
                                features_col=self.getFeaturesCol(),
                                label_col=self.getLabelCol())
        dataset = dataset.repartition(self.get_num_workers())
        model = model_from_json(self.get_model_config(),
                                self.get_custom_objects())
        loss = self.get_loss()
        optimizer_config = self.get_optimizer_config()
        optimizer = (get_optimizer(optimizer_config) if optimizer_config
                     else "sgd")
        seed = self.get_seed()
        model.compile(loss=loss, optimizer=optimizer,
                      metrics=self.get_metrics(),
                      custom_objects=self.get_custom_objects(),
                      seed=seed)

        tpu_model = TPUModel(model=model, mode=self.get_mode(),
                             frequency=self.get_frequency(),
                             num_workers=self.get_num_workers(),
                             custom_objects=self.get_custom_objects(),
                             sync_mode=self.get_sync_mode())
        tpu_model.fit(dataset, epochs=self.get_epochs(),
                      batch_size=self.get_batch_size(),
                      verbose=self.get_verbosity(),
                      validation_split=self.get_validation_split(),
                      **({} if seed is None else {"seed": seed}))

        return Transformer(
            labelCol=self.getLabelCol(),
            outputCol=self.getOutputCol(),
            featuresCol=self.getFeaturesCol(),
            model_config=tpu_model.master_network.to_json(),
            weights=tpu_model.master_network.get_weights(),
            custom_objects=self.get_custom_objects(),
            model_type=LossModelTypeMapper().get_model_type(loss),
            history=tpu_model.training_histories)

    # deprecated setter trio kept for migration parity
    # (``elephas/ml_model.py:114-127``)
    def setFeaturesCol(self, value):
        warnings.warn("setFeaturesCol is deprecated - supply featuresCol in "
                      "the constructor, i.e. Estimator(featuresCol='foo')",
                      DeprecationWarning)
        return self._set(featuresCol=value)

    def setLabelCol(self, value):
        warnings.warn("setLabelCol is deprecated - supply labelCol in the "
                      "constructor, i.e. Estimator(labelCol='foo')",
                      DeprecationWarning)
        return self._set(labelCol=value)

    def setOutputCol(self, value):
        warnings.warn("setOutputCol is deprecated - supply outputCol in the "
                      "constructor, i.e. Estimator(outputCol='foo')",
                      DeprecationWarning)
        return self._set(outputCol=value)


def load_ml_estimator(file_name: str) -> Estimator:
    with h5py.File(file_name, mode="r") as f:
        conf = f.attrs.get("distributed_config")
        if isinstance(conf, bytes):
            conf = conf.decode("utf8")
        elephas_conf = json.loads(conf)
    return Estimator(**elephas_conf.get("config"))


class Transformer(HasModelConfig, HasLabelCol, HasOutputCol, HasFeaturesCol,
                  HasCustomObjects, HasInferenceBatchSize):
    """Fitted model: ``transform(df)`` appends the prediction column."""

    def __init__(self, **kwargs):
        HasModelConfig.__init__(self)
        HasLabelCol.__init__(self)
        HasOutputCol.__init__(self)
        HasFeaturesCol.__init__(self)
        HasCustomObjects.__init__(self)
        HasInferenceBatchSize.__init__(self)
        self.weights = kwargs.pop("weights", None)
        self.model_type = kwargs.pop("model_type", None)
        self._history = kwargs.pop("history", [])
        self.set_params(**kwargs)

    @property
    def history(self):
        return self._history

    def set_params(self, **kwargs):
        return self._set(**kwargs)

    def get_config(self) -> dict:
        return {"model_config": self.get_model_config(),
                "labelCol": self.getLabelCol(),
                "featuresCol": self.getFeaturesCol(),
                "outputCol": self.getOutputCol(),
                "model_type": self.model_type}

    def save(self, file_name: str):
        # weights go into h5 datasets, not the JSON config attr: the
        # reference JSON-encodes full weights as nested Python lists
        # (``elephas/ml_model.py:172-186``), which at TPU-scale weight
        # counts is an OOM/file-size bomb and loses dtype
        with h5py.File(file_name, mode="w") as f:
            f.attrs["distributed_config"] = json.dumps({
                "class_name": self.__class__.__name__,
                "config": self.get_config(),
            }, cls=ModelTypeEncoder).encode("utf8")
            group = f.create_group("model_weights")
            for i, w in enumerate(self.weights or []):
                group.create_dataset(f"weight_{i}", data=np.asarray(w))

    def get_model(self):
        model = model_from_json(self.get_model_config(),
                                self.get_custom_objects())
        if self.weights is not None:
            model.set_weights(self.weights)
        return model

    def transform(self, df: pd.DataFrame) -> pd.DataFrame:
        """Append the prediction column; classification yields probability
        lists, regression yields scalars (``elephas/ml_model.py:191-256``)."""
        from .adapter import _cell_to_array
        from ..parallel.sync_trainer import build_sharded_predict

        output_col = self.getOutputCol()
        features_col = self.getFeaturesCol()
        model = self.get_model()
        predict_fn = build_sharded_predict(model)

        inference_batch_size = self.get_inference_batch_size()
        if inference_batch_size is not None and inference_batch_size > 0:
            # bounded-memory batched inference: stream the column in
            # chunks end-to-end — host memory stays O(batch), never
            # O(dataset) (the reference streams the partition iterator,
            # ``elephas/ml_model.py:199-221``); order preserved by
            # construction
            column = df[features_col]
            preds = []
            for i in range(0, len(column), inference_batch_size):
                chunk = np.stack([_cell_to_array(cell) for cell in
                                  column.iloc[i:i + inference_batch_size]])
                preds.append(np.asarray(predict_fn(
                    chunk, batch_size=inference_batch_size)))
            predictions = np.vstack(preds) if preds else np.zeros((0,))
        else:
            features = np.stack([_cell_to_array(cell)
                                 for cell in df[features_col]])
            predictions = predict_fn(features)

        results_df = df.copy()
        if self.model_type == ModelType.REGRESSION:
            results_df[output_col] = [float(np.asarray(p).reshape(-1)[0])
                                      for p in predictions]
        else:
            results_df[output_col] = [np.asarray(p).astype(float).tolist()
                                      for p in predictions]
        return results_df


def load_ml_transformer(file_name: str) -> Transformer:
    with h5py.File(file_name, mode="r") as f:
        conf = f.attrs.get("distributed_config")
        if isinstance(conf, bytes):
            conf = conf.decode("utf8")
        elephas_conf = json.loads(conf, object_hook=as_enum)
        config = elephas_conf.get("config")
        group = f.get("model_weights")
        if group is not None:
            config["weights"] = [np.asarray(group[f"weight_{i}"])
                                 for i in range(len(group))]
        elif "weights" in config:  # files written by older versions
            config["weights"] = [np.array(w) for w in config["weights"]]
    return Transformer(**config)
