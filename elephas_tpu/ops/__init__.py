from .attention import attention, blockwise_attention
from .paged_attention import paged_decode_attention, pallas_supported
from .pallas_attention import flash_attention
from .ring_attention import (ring_attention, ring_attention_sharded,
                             ring_flash_attention)
