"""Flash attention as Pallas TPU kernels (forward + backward).

The reference framework has no attention at all (SURVEY.md §5 — its largest
model is an MLP), so this module is pure TPU-native upside: the flagship
transformer's hot op written against the MXU/VMEM directly instead of
through XLA's generic fusion.

Design (flash-attention v2 recurrence):

- Forward grid ``(batch*heads, q_blocks, kv_blocks)`` — the kv axis is the
  innermost (sequential) grid dimension, so the online-softmax accumulators
  live in VMEM scratch across kv steps while ``BlockSpec`` index maps
  stream q/k/v tiles HBM -> VMEM. Never materializes the ``(seq, seq)``
  score matrix.
- Backward is two kernels sharing the saved per-row logsumexp: ``dq`` over
  ``(bh, q_blocks, kv_blocks)`` and ``dk/dv`` over ``(bh, kv_blocks,
  q_blocks)``; ``delta = rowsum(dO * O)`` is precomputed with plain jnp.
- All accumulation is f32 regardless of input dtype (bf16 inputs hit the
  MXU; softmax statistics stay f32 for stability).
- Ragged sequence lengths are handled by padding to block multiples and
  masking both key and query validity inside the kernels.

On non-TPU backends the same kernels run via the Pallas interpreter
(``interpret=True``), which is how the CPU test suite exercises them.
"""
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax < 0.5 spells the TPU compiler-params struct TPUCompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) or getattr(
    pltpu, "TPUCompilerParams")

NEG_INF = -1e30

__all__ = ["flash_attention", "flash_attention_sharded"]


def _use_interpret(interpret: Optional[bool]) -> bool:
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# --------------------------------------------------------------------- fwd
def _fwd_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, sq: int, sk: int, block_q: int, block_k: int,
                causal: bool, scale: float, window=None):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    # global-position offsets (SMEM scalars): 0 for plain attention;
    # under ring/sequence parallelism they place this device's q shard
    # and the current hop's k/v shard on the global sequence axis, so
    # causal/band masking and block skipping see global positions
    q_off = qo_ref[0, 0]
    k_off = ko_ref[0, 0]

    @pl.when(kj == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # skip blocks strictly above the causal diagonal — and, with a
    # sliding window, blocks entirely below it
    diag_reached = ((not causal)
                    or (k_off + kj * block_k
                        <= q_off + qi * block_q + block_q - 1))
    if window is not None:
        in_band = (k_off + kj * block_k + block_k - 1
                   > q_off + qi * block_q - window)
        diag_reached = diag_reached & in_band

    @pl.when(diag_reached)
    def _():
        # native-dtype operands into the MXU (bf16 multiply, f32 accumulate
        # via preferred_element_type) — casting to f32 first would force a
        # 4x-slower f32 MXU pass
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale

        q_loc = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_loc = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        # padding bounds are local to the shard; causal/band are global
        valid = k_loc < sk
        if causal:
            valid = valid & (k_off + k_loc <= q_off + q_loc)
        if window is not None:
            valid = valid & (k_off + k_loc > q_off + q_loc - window)
        s = jnp.where(valid, s, NEG_INF)

        m_prev = m_ref[:, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        # fully-masked rows (query padding): keep p exactly zero
        p = jnp.where(valid, p, 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_ref[:, 0] = l_ref[:, 0] * corr + jnp.sum(p, axis=-1)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:, 0] = m_new

    @pl.when(kj == nk - 1)
    def _():
        l = l_ref[:, 0]
        o_ref[0] = (acc_ref[:] / jnp.maximum(l, 1e-30)[:, None]).astype(
            o_ref.dtype)
        # lse is (block_q, 1): trailing dims (block_q, 1) satisfy the TPU
        # (8, 128)-or-full-dim tile rule, which a (1, block_q) block doesn't
        # fully-masked rows keep lse = NEG_INF-ish so a cross-hop merge
        # weights them to zero
        lse_ref[0] = (m_ref[:, 0] + jnp.log(jnp.maximum(l, 1e-30)))[:, None]


def _as_offset(x):
    """Scalar offset -> (1, 1) int32 array for the SMEM block spec."""
    return jnp.asarray(x, jnp.int32).reshape(1, 1)


#: whole-array SMEM placement for the (1, 1) int32 offset scalars
_SMEM_SPEC = pl.BlockSpec(memory_space=pltpu.SMEM)


def _fwd(q, k, v, causal, block_q, block_k, interpret, window=None,
         q_offset=0, k_offset=0):
    b, h, sq, d = q.shape
    kvh = k.shape[1]
    grp = h // kvh
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    sq_p, sk_p = _round_up(sq, block_q), _round_up(sk, block_k)

    qr = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0))).reshape(
        b * h, sq_p, d)
    kr = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0))).reshape(
        b * kvh, sk_p, d)
    vr = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0))).reshape(
        b * kvh, sk_p, d)

    def kv_row(bh):
        # GQA: query row bh = bi*h + hi reads kv row bi*kvh + hi//grp
        return (bh // h) * kvh + (bh % h) // grp

    grid = (b * h, sq_p // block_q, sk_p // block_k)
    kernel = functools.partial(_fwd_kernel, sq=sq, sk=sk, block_q=block_q,
                               block_k=block_k, causal=causal, scale=scale,
                               window=window)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            _SMEM_SPEC,
            _SMEM_SPEC,
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, kj: (kv_row(bh), kj, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bh, qi, kj: (kv_row(bh), kj, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi, kj: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi, kj: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq_p, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_use_interpret(interpret),
    )(_as_offset(q_offset), _as_offset(k_offset), qr, kr, vr)
    return (o[:, :sq].reshape(b, h, sq, d),
            lse[:, :sq, 0].reshape(b, h, sq))


# --------------------------------------------------------------------- bwd
def _dq_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               delta_ref, dq_ref,
               dq_acc, *, sq: int, sk: int, block_q: int, block_k: int,
               causal: bool, scale: float, window=None):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)
    q_off = qo_ref[0, 0]
    k_off = ko_ref[0, 0]

    @pl.when(kj == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    diag_reached = ((not causal)
                    or (k_off + kj * block_k
                        <= q_off + qi * block_q + block_q - 1))
    if window is not None:
        diag_reached = diag_reached & (k_off + kj * block_k + block_k - 1
                                       > q_off + qi * block_q - window)

    @pl.when(diag_reached)
    def _():
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_loc = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_loc = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        valid = k_loc < sk
        if causal:
            valid = valid & (k_off + k_loc <= q_off + q_loc)
        if window is not None:
            valid = valid & (k_off + k_loc > q_off + q_loc - window)
        p = jnp.where(valid, jnp.exp(s - lse_ref[0]), 0.0)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dq_acc[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kj == nk - 1)
    def _():
        dq_ref[0] = dq_acc[:].astype(dq_ref.dtype)


def _dkv_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                delta_ref,
                dk_ref, dv_ref, dk_acc, dv_acc, *, sq: int, sk: int,
                block_q: int, block_k: int, causal: bool, scale: float,
                nq_blocks: int, window=None):
    kj = pl.program_id(1)
    t = pl.program_id(2)
    # the trailing grid axis enumerates (group member, q block): every
    # query head sharing this kv head accumulates into the same dk/dv
    qi = t % nq_blocks
    total = pl.num_programs(2)
    q_off = qo_ref[0, 0]
    k_off = ko_ref[0, 0]

    @pl.when(t == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    diag_reached = ((not causal)
                    or (k_off + kj * block_k
                        <= q_off + qi * block_q + block_q - 1))
    if window is not None:
        diag_reached = diag_reached & (k_off + kj * block_k + block_k - 1
                                       > q_off + qi * block_q - window)

    @pl.when(diag_reached)
    def _():
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        q_loc = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_loc = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        # mask BOTH query padding (q_loc >= sq would use garbage lse) and
        # key validity/causality
        valid = (k_loc < sk) & (q_loc < sq)
        if causal:
            valid = valid & (k_off + k_loc <= q_off + q_loc)
        if window is not None:
            valid = valid & (k_off + k_loc > q_off + q_loc - window)
        p = jnp.where(valid, jnp.exp(s - lse_ref[0]), 0.0)
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do_ref[0], v_ref[0], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0]) * scale
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(t == total - 1)
    def _():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _bwd(causal, block_q, block_k, interpret, window, residuals, g):
    q, k, v, o, lse = residuals
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    return _bwd_calls(q, k, v, g, lse, delta, causal, block_q, block_k,
                      interpret, window)


def _bwd_calls(q, k, v, g, lse, delta, causal, block_q, block_k, interpret,
               window, q_offset=0, k_offset=0):
    """dq/dk/dv kernel dispatch given precomputed lse and delta.

    ``lse``/``delta`` may be GLOBAL row statistics (ring attention:
    softmax over the whole sequence factorizes as exp(s - lse_global), so
    a per-shard backward with global statistics yields exact gradients).
    """
    b, h, sq, d = q.shape
    kvh = k.shape[1]
    grp = h // kvh
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    sq_p, sk_p = _round_up(sq, block_q), _round_up(sk, block_k)

    def prep(x, s_pad):
        rows = x.shape[0] * x.shape[1]
        return jnp.pad(x, ((0, 0), (0, 0), (0, s_pad - x.shape[2]),
                           (0, 0))).reshape(rows, s_pad, x.shape[3])

    qr, dor = prep(q, sq_p), prep(g, sq_p)
    kr, vr = prep(k, sk_p), prep(v, sk_p)
    # rows as (bh, seq, 1): trailing block dims (block_q, 1) fit TPU tiling
    lser = jnp.pad(lse, ((0, 0), (0, 0), (0, sq_p - sq))).reshape(
        b * h, sq_p, 1)
    deltar = jnp.pad(delta, ((0, 0), (0, 0), (0, sq_p - sq))).reshape(
        b * h, sq_p, 1)

    interp = _use_interpret(interpret)
    common = dict(sq=sq, sk=sk, block_q=block_q, block_k=block_k,
                  causal=causal, scale=scale, window=window)

    def kv_row(bh):
        return (bh // h) * kvh + (bh % h) // grp

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, qi, kj: (bh, qi, 0))
    k_spec = pl.BlockSpec((1, block_k, d),
                          lambda bh, qi, kj: (kv_row(bh), kj, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda bh, qi, kj: (bh, qi, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, **common),
        grid=(b * h, sq_p // block_q, sk_p // block_k),
        in_specs=[_SMEM_SPEC, _SMEM_SPEC,
                  q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=[q_spec],
        out_shape=[jax.ShapeDtypeStruct((b * h, sq_p, d), q.dtype)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
    )(_as_offset(q_offset), _as_offset(k_offset),
      qr, kr, vr, dor, lser, deltar)[0]

    # kv-major grid over the NARROW kv rows; the trailing axis walks
    # (group member, q block) so all grp query heads sharing a kv head
    # accumulate into its dk/dv block
    nq = sq_p // block_q

    def q_row(bkv, t):
        return (bkv // kvh) * h + (bkv % kvh) * grp + t // nq

    q_spec_t = pl.BlockSpec((1, block_q, d),
                            lambda bkv, kj, t: (q_row(bkv, t), t % nq, 0))
    k_spec_t = pl.BlockSpec((1, block_k, d),
                            lambda bkv, kj, t: (bkv, kj, 0))
    row_spec_t = pl.BlockSpec((1, block_q, 1),
                              lambda bkv, kj, t: (q_row(bkv, t), t % nq, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, nq_blocks=nq, **common),
        grid=(b * kvh, sk_p // block_k, grp * nq),
        in_specs=[_SMEM_SPEC, _SMEM_SPEC,
                  q_spec_t, k_spec_t, k_spec_t, q_spec_t, row_spec_t,
                  row_spec_t],
        out_specs=[k_spec_t, k_spec_t],
        out_shape=[jax.ShapeDtypeStruct((b * kvh, sk_p, d), k.dtype),
                   jax.ShapeDtypeStruct((b * kvh, sk_p, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interp,
    )(_as_offset(q_offset), _as_offset(k_offset),
      qr, kr, vr, dor, lser, deltar)

    return (dq[:, :sq].reshape(b, h, sq, d),
            dk[:, :sk].reshape(b, kvh, sk, d),
            dv[:, :sk].reshape(b, kvh, sk, d))


# ------------------------------------------------------------- ring hops
def _clamp_blocks(block_q, block_k, sq, sk):
    # round to 32 rows — a multiple of every dtype's min sublane tile
    return (min(block_q, _round_up(sq, 32)), min(block_k, _round_up(sk, 32)))


def flash_hop_forward(q, k, v, q_offset, k_offset, causal: bool = True,
                      window: Optional[int] = None, block_q: int = 256,
                      block_k: int = 512, interpret: Optional[bool] = None):
    """One ring-attention hop through the flash kernel: block attention of
    the local q shard against one circulating k/v shard, masked on GLOBAL
    positions (``q_offset``/``k_offset`` are traced per-device scalars).

    Returns ``(o, lse)`` — per-hop normalized output and logsumexp row
    statistics, merged across hops by the caller. NOT differentiable;
    ring attention's custom VJP calls :func:`flash_hop_backward`.
    """
    block_q, block_k = _clamp_blocks(block_q, block_k, q.shape[2],
                                     k.shape[2])
    return _fwd(q, k, v, causal, block_q, block_k, interpret, window,
                q_offset=q_offset, k_offset=k_offset)


def flash_hop_backward(q, k, v, g, lse, delta, q_offset, k_offset,
                       causal: bool = True, window: Optional[int] = None,
                       block_q: int = 256, block_k: int = 512,
                       interpret: Optional[bool] = None):
    """Per-hop backward with GLOBAL row statistics: softmax over the full
    ring factorizes as ``exp(s - lse_global)``, so dq/dk/dv for this hop's
    shard pair are exact given the global ``lse`` and
    ``delta = rowsum(dO * O_global)``."""
    block_q, block_k = _clamp_blocks(block_q, block_k, q.shape[2],
                                     k.shape[2])
    return _bwd_calls(q, k, v, g, lse, delta, causal, block_q, block_k,
                      interpret, window, q_offset=q_offset,
                      k_offset=k_offset)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, block_q, block_k, interpret, window):
    o, _ = _fwd(q, k, v, causal, block_q, block_k, interpret, window)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, window):
    o, lse = _fwd(q, k, v, causal, block_q, block_k, interpret, window)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, window, residuals, g):
    return _bwd(causal, block_q, block_k, interpret, window, residuals, g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                    causal: bool = False, block_q: int = 256,
                    block_k: int = 512,
                    interpret: Optional[bool] = None,
                    window: Optional[int] = None) -> jnp.ndarray:
    """Flash attention over ``(batch, heads, seq, head_dim)`` tensors.

    Differentiable (custom VJP with Pallas backward kernels). ``interpret``
    defaults to auto: compiled on TPU, interpreter elsewhere. Block sizes
    should stay multiples of the f32 min tile (8, 128) on real hardware;
    sequence lengths need not be multiples of the block size.
    """
    if q.ndim != 4:
        raise ValueError(f"expected (batch, heads, seq, head_dim), got "
                         f"{q.shape}")
    if q.shape[1] % k.shape[1]:
        raise ValueError(
            f"kv heads {k.shape[1]} must divide query heads {q.shape[1]} "
            "(GQA)")
    # clamp blocks for short sequences, rounding to 32 rows — a multiple of
    # every dtype's min sublane tile (8 f32 / 16 bf16 / 32 int8)
    if window is not None and window < 1:
        raise ValueError("window must be >= 1")
    block_q = min(block_q, _round_up(q.shape[2], 32))
    block_k = min(block_k, _round_up(k.shape[2], 32))
    return _flash(q, k, v, causal, block_q, block_k, interpret,
                  int(window) if window is not None else None)


def flash_attention_sharded(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                            mesh, causal: bool = False,
                            batch_axis: Optional[str] = None,
                            head_axis: Optional[str] = None,
                            block_q: int = 256, block_k: int = 512,
                            interpret: Optional[bool] = None,
                            window: Optional[int] = None) -> jnp.ndarray:
    """Flash attention under a device mesh.

    The Mosaic kernel has no SPMD partitioning rule, so a bare
    :func:`flash_attention` inside a GSPMD-jitted program either fails to
    partition or replicates. Attention is independent per (batch, head), so
    dp/tp sharding needs no communication at all: ``shard_map`` pins the
    batch axis to ``batch_axis`` (data parallel) and the head axis to
    ``head_axis`` (Megatron tensor parallel — the same axis the qkv/out
    projections shard over), and each device runs the kernel on its local
    ``(b/dp, h/tp, seq, d)`` block. Sequence parallelism is NOT handled
    here — that is :func:`~elephas_tpu.ops.ring_attention.ring_attention_sharded`.
    """
    from functools import partial as _partial

    from jax.sharding import PartitionSpec as _P

    from ..utils.compat import shard_map as _shard_map

    spec = _P(batch_axis, head_axis, None, None)
    fn = _shard_map(
        _partial(flash_attention, causal=causal, block_q=block_q,
                 block_k=block_k, interpret=interpret, window=window),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check=False)
    return fn(q, k, v)
