"""Ring attention: sequence/context parallelism over a mesh axis.

Long sequences are sharded across devices along a ``seq`` mesh axis; each
device holds a query shard and streams key/value shards around the ring
with ``lax.ppermute`` (compiled to ICI neighbor exchanges on TPU), folding
each incoming block into a flash-attention online-softmax accumulator. HBM
and VMEM footprint per device is O(seq/P), enabling context lengths that
cannot fit on one chip — the "long-context first-class" requirement the
TPU framework adds over the reference (SURVEY.md §5 lists it absent there).

Communication overlaps with compute: at ring step i every device computes
scores against the shard it currently holds while the next shard is in
flight — the classic ring-attention schedule.

Use inside ``shard_map`` with ``q, k, v`` already sharded on the sequence
axis; see :func:`ring_attention_sharded` for the wrapped entry point.
"""
import math
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

NEG_INF = -1e30


def ring_num_hops(axis_size: int, shard_len: int,
                  window: Optional[int]) -> int:
    """Ring hops a causal sliding-window band actually needs.

    Hop ``i`` visits the kv block ``i`` shards behind the query shard;
    the farthest-back block any query in a shard of length ``s`` can see
    with a band ``k > q - window`` is ``floor((window - 2)/s) + 1`` hops
    away — identical for every device, so the bound is static and the
    out-of-band hops (and their ppermutes) are simply never executed.
    """
    if window is None:
        return axis_size
    if window <= 1:
        return 1  # each query sees only itself: the diagonal block
    return min(axis_size, 2 + (window - 2) // shard_len)


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   axis_name: str, causal: bool = False,
                   window: Optional[int] = None) -> jnp.ndarray:
    """Attention over a ring; call inside ``shard_map``.

    :param q: local query shard ``(batch, heads, seq_local, head_dim)``
    :param k, v: local key/value shards ``(batch, kv_heads, seq_local,
        head_dim)`` — GQA-aware: with ``kv_heads < heads`` the ring
        circulates the NARROW k/v buffers (ICI traffic shrinks by the
        group factor) and each query group attends to its shared head
    :param axis_name: mesh axis carrying the sequence shards
    :param causal: apply a causal mask over *global* positions
    :param window: sliding-window band over global positions — each
        query attends to at most the last ``window`` keys (itself
        included). Requires ``causal``; hops entirely outside the band
        are skipped statically (see :func:`ring_num_hops`), so a narrow
        window on a long ring pays O(window) compute and ICI traffic,
        not O(seq).
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True (sliding-window "
                         "attention is a causal band)")
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    kvh = k.shape[1]
    if h % kvh:
        raise ValueError(f"kv heads {kvh} must divide query heads {h}")
    g = h // kvh
    qg = q.reshape(b, kvh, g, sq, d)
    scale = 1.0 / math.sqrt(d)
    q_pos = my_idx * sq + jnp.arange(sq)[:, None]
    n_hops = ring_num_hops(axis_size, sq, window)

    def step(i, carry):
        o, l, m, k_cur, v_cur = carry
        kv_idx = (my_idx - i) % axis_size
        s = jnp.einsum("bngqd,bnkd->bngqk", qg, k_cur) * scale
        if causal:
            k_pos = kv_idx * k_cur.shape[2] + jnp.arange(k_cur.shape[2])[None, :]
            keep = k_pos <= q_pos
            if window is not None:
                keep = keep & (k_pos > q_pos - window)
            s = jnp.where(keep, s, NEG_INF)
        # hop 0 is the diagonal block, so every query row sees at least
        # its own position first: m is finite from the first hop on and
        # fully-masked later blocks contribute exp(NEG_INF - m) = 0
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        o_new = (o * correction[..., None]
                 + jnp.einsum("bngqk,bnkd->bngqd", p, v_cur))
        # rotate k/v shards one hop around the ring (ICI neighbor exchange)
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o_new, l_new, m_new, k_next, v_next

    o0 = jnp.zeros_like(qg)
    l0 = jnp.zeros((b, kvh, g, sq), dtype=q.dtype)
    m0 = jnp.full((b, kvh, g, sq), NEG_INF, dtype=q.dtype)
    o, l, m, _, _ = lax.fori_loop(0, n_hops, step, (o0, l0, m0, k, v))
    o = o / jnp.maximum(l, 1e-20)[..., None]
    return o.reshape(b, h, sq, d)


# -------------------------------------------------------- zigzag layout
def _zigzag_perms(axis_size: int):
    """The two chunk permutations between contiguous and zigzag layouts.

    Global sequence = ``2P`` chunks. Contiguous: device ``d`` holds
    chunks ``(2d, 2d+1)``. Zigzag: device ``d`` holds ``(d, 2P-1-d)`` —
    one early and one late chunk, so every device owns the same amount
    of causal work. Each layout change moves exactly one chunk per
    device per permutation: two ppermutes total.
    """
    P = axis_size
    perm1 = [(d, 2 * d if 2 * d < P else 2 * P - 1 - 2 * d)
             for d in range(P)]
    perm2 = [(d, 2 * d + 1 if 2 * d + 1 < P else 2 * P - 2 - 2 * d)
             for d in range(P)]
    return perm1, perm2


def _zigzag_scatter(x, axis_name: str, seq_dim: int):
    """Contiguous shard -> zigzag shard (low ‖ high chunk), in-shard_map.

    Device parity decides which received buffer is the low (early)
    chunk: the even-indexed global chunk lands via perm1 on even
    devices and via perm2 on odd ones.
    """
    P = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm1, perm2 = _zigzag_perms(P)
    c1, c2 = jnp.split(x, 2, axis=seq_dim)
    r1 = lax.ppermute(c1, axis_name, perm1)
    r2 = lax.ppermute(c2, axis_name, perm2)
    even = (my % 2) == 0
    low = jnp.where(even, r1, r2)
    high = jnp.where(even, r2, r1)
    return jnp.concatenate([low, high], axis=seq_dim)


def _zigzag_gather(x, axis_name: str, seq_dim: int):
    """Zigzag shard -> contiguous shard (inverse of _zigzag_scatter)."""
    P = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    perm1, perm2 = _zigzag_perms(P)
    inv1 = [(dst, src) for src, dst in perm1]
    inv2 = [(dst, src) for src, dst in perm2]
    low, high = jnp.split(x, 2, axis=seq_dim)
    # device d holds global chunks (d, 2P-1-d); the even-indexed one is
    # `low` on even devices, `high` on odd devices
    even = (my % 2) == 0
    even_chunk = jnp.where(even, low, high)
    odd_chunk = jnp.where(even, high, low)
    r1 = lax.ppermute(even_chunk, axis_name, inv1)
    r2 = lax.ppermute(odd_chunk, axis_name, inv2)
    return jnp.concatenate([r1, r2], axis=seq_dim)


# ----------------------------------------------------------- flash ring
@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis_name, causal, window, block_q, block_k,
                interpret):
    o, _ = _ring_flash_fwd(q, k, v, axis_name, causal, window, block_q,
                           block_k, interpret)
    return o


def _ring_flash_fwd(q, k, v, axis_name, causal, window, block_q, block_k,
                    interpret):
    from .pallas_attention import flash_hop_forward

    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    n_hops = ring_num_hops(axis_size, sq, window) if causal else axis_size
    q_off = my_idx * sq
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def hop(i, carry):
        o, lse, k_cur, v_cur = carry
        kv_idx = (my_idx - i) % axis_size
        # each hop runs the flash kernel on the local block pair with
        # global-position masking; per-hop (o, lse) merge by logsumexp
        # weights — the hop-level analog of the kernel's kv-block online
        # softmax
        o_h, lse_h = flash_hop_forward(q, k_cur, v_cur, q_off,
                                       kv_idx * sk, causal, window,
                                       block_q, block_k, interpret)
        lse_new = jnp.logaddexp(lse, lse_h)
        o = (o * jnp.exp(lse - lse_new)[..., None]
             + o_h.astype(jnp.float32) * jnp.exp(lse_h - lse_new)[..., None])
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o, lse_new, k_next, v_next

    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    lse0 = jnp.full((b, h, sq), NEG_INF, jnp.float32)
    o, lse, _, _ = lax.fori_loop(0, n_hops, hop, (o0, lse0, k, v))
    return o.astype(q.dtype), lse


def _ring_flash_fwd_vjp(q, k, v, axis_name, causal, window, block_q,
                        block_k, interpret):
    o, lse = _ring_flash_fwd(q, k, v, axis_name, causal, window, block_q,
                             block_k, interpret)
    return o, (q, k, v, o, lse)


def _ring_flash_bwd(axis_name, causal, window, block_q, block_k, interpret,
                    residuals, g):
    from .pallas_attention import flash_hop_backward

    q, k, v, o, lse = residuals
    axis_size = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    sq, sk = q.shape[2], k.shape[2]
    n_hops = ring_num_hops(axis_size, sq, window) if causal else axis_size
    q_off = my_idx * sq
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def hop(i, carry):
        dq, k_cur, v_cur, dk, dv = carry
        kv_idx = (my_idx - i) % axis_size
        dq_h, dk_h, dv_h = flash_hop_backward(
            q, k_cur, v_cur, g, lse, delta, q_off, kv_idx * sk, causal,
            window, block_q, block_k, interpret)
        dq = dq + dq_h.astype(jnp.float32)
        # dk/dv accumulators travel WITH their k/v shard around the ring
        dk = dk + dk_h.astype(jnp.float32)
        dv = dv + dv_h.astype(jnp.float32)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return dq, k_next, v_next, dk, dv

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dq, _, _, dk, dv = lax.fori_loop(0, n_hops, hop,
                                     (dq0, k, v, dk0, dv0))
    if n_hops % axis_size:
        # the travelling dk/dv accumulators are n_hops positions past
        # their home shard — one permute sends every block home
        home = [(j, (j - n_hops) % axis_size) for j in range(axis_size)]
        dk = lax.ppermute(dk, axis_name, home)
        dv = lax.ppermute(dv, axis_name, home)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_flash.defvjp(_ring_flash_fwd_vjp, _ring_flash_bwd)


# ------------------------------------------------- zigzag-balanced ring
def _chunk_offsets(z, axis_size, chunk_len):
    """Global row offsets of zigzag device ``z``'s (low, high) chunks."""
    return z * chunk_len, (2 * axis_size - 1 - z) * chunk_len


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _zigzag_ring_flash(q, k, v, axis_name, block_q, block_k, interpret):
    o, _, _ = _zigzag_fwd(q, k, v, axis_name, block_q, block_k, interpret)
    return o


def _zigzag_fwd(q, k, v, axis_name, block_q, block_k, interpret):
    """Balanced causal ring: every device owns one early + one late
    chunk, so per-hop work (after the kernel's dynamic block skip) is
    uniform across the ring — ~2x better wall clock than the contiguous
    layout, whose last device computes every hop while the first sits
    in fully-masked blocks."""
    from .pallas_attention import flash_hop_forward

    P = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    b, h, s, d = q.shape
    if s % 2:
        raise ValueError("zigzag ring needs an even local shard length")
    hl = s // 2
    qz = _zigzag_scatter(q, axis_name, seq_dim=2)
    kz = _zigzag_scatter(k, axis_name, seq_dim=2)
    vz = _zigzag_scatter(v, axis_name, seq_dim=2)
    ql, qh = qz[:, :, :hl], qz[:, :, hl:]
    q_off_l, q_off_h = _chunk_offsets(my, P, hl)
    perm = [(j, (j + 1) % P) for j in range(P)]

    def hop(i, carry):
        o_l, lse_l, o_h, lse_h, k_cur, v_cur = carry
        z = (my - i) % P
        k_off_l, k_off_h = _chunk_offsets(z, P, hl)
        kl, kh = k_cur[:, :, :hl], k_cur[:, :, hl:]
        vl, vh = v_cur[:, :, :hl], v_cur[:, :, hl:]

        def fold(o, lse, qc, q_off, kc, vc, k_off):
            o_p, lse_p = flash_hop_forward(qc, kc, vc, q_off, k_off, True,
                                           None, block_q, block_k,
                                           interpret)
            lse_new = jnp.logaddexp(lse, lse_p)
            o = (o * jnp.exp(lse - lse_new)[..., None]
                 + o_p.astype(jnp.float32)
                 * jnp.exp(lse_p - lse_new)[..., None])
            return o, lse_new

        # NO (q_low, k_high) fold: low q chunks are indices 0..P-1, high
        # k chunks are P..2P-1 — always entirely in the future, fully
        # masked for every device at every hop
        o_l, lse_l = fold(o_l, lse_l, ql, q_off_l, kl, vl, k_off_l)
        o_h, lse_h = fold(o_h, lse_h, qh, q_off_h, kl, vl, k_off_l)
        o_h, lse_h = fold(o_h, lse_h, qh, q_off_h, kh, vh, k_off_h)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o_l, lse_l, o_h, lse_h, k_next, v_next

    z0 = lambda: (jnp.zeros((b, h, hl, d), jnp.float32),
                  jnp.full((b, h, hl), NEG_INF, jnp.float32))
    o_l, lse_l = z0()
    o_h, lse_h = z0()
    o_l, lse_l, o_h, lse_h, _, _ = lax.fori_loop(
        0, P, hop, (o_l, lse_l, o_h, lse_h, kz, vz))
    oz = jnp.concatenate([o_l, o_h], axis=2)
    lsez = jnp.concatenate([lse_l, lse_h], axis=2)
    o = _zigzag_gather(oz.astype(q.dtype), axis_name, seq_dim=2)
    return o, (qz, kz, vz, oz, lsez), None


def _zigzag_fwd_vjp(q, k, v, axis_name, block_q, block_k, interpret):
    o, residuals, _ = _zigzag_fwd(q, k, v, axis_name, block_q, block_k,
                                  interpret)
    return o, residuals


def _zigzag_bwd(axis_name, block_q, block_k, interpret, residuals, g):
    from .pallas_attention import flash_hop_backward

    qz, kz, vz, oz, lsez = residuals
    P = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    s = qz.shape[2]
    hl = s // 2
    # cotangent + global row statistics, in zigzag layout (the transpose
    # of the output gather is the input scatter: both are permutations)
    gz = _zigzag_scatter(g, axis_name, seq_dim=2)
    delta = jnp.sum(gz.astype(jnp.float32) * oz, axis=-1)
    ql, qh = qz[:, :, :hl], qz[:, :, hl:]
    gl, gh = gz[:, :, :hl], gz[:, :, hl:]
    lse_l, lse_h = lsez[:, :, :hl], lsez[:, :, hl:]
    d_l, d_h = delta[:, :, :hl], delta[:, :, hl:]
    q_off_l, q_off_h = _chunk_offsets(my, P, hl)
    perm = [(j, (j + 1) % P) for j in range(P)]

    def hop(i, carry):
        dq, k_cur, v_cur, dk, dv = carry
        z = (my - i) % P
        k_off_l, k_off_h = _chunk_offsets(z, P, hl)
        # mirrors the forward's three folds — the (q_low, k_high) pair is
        # always fully masked and contributes zero gradient
        for q_half, (qc, gc, lse_c, del_c, q_off), k_slices in (
                ((slice(0, hl)), (ql, gl, lse_l, d_l, q_off_l),
                 ((slice(0, hl), k_off_l),)),
                ((slice(hl, s)), (qh, gh, lse_h, d_h, q_off_h),
                 ((slice(0, hl), k_off_l), (slice(hl, s), k_off_h)))):
            for sl, k_off in k_slices:
                dq_p, dk_p, dv_p = flash_hop_backward(
                    qc, k_cur[:, :, sl], v_cur[:, :, sl], gc, lse_c,
                    del_c, q_off, k_off, True, None, block_q, block_k,
                    interpret)
                dq = dq.at[:, :, q_half].add(dq_p.astype(jnp.float32))
                dk = dk.at[:, :, sl].add(dk_p.astype(jnp.float32))
                dv = dv.at[:, :, sl].add(dv_p.astype(jnp.float32))
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return dq, k_next, v_next, dk, dv

    dq0 = jnp.zeros(qz.shape, jnp.float32)
    dk0 = jnp.zeros(kz.shape, jnp.float32)
    dv0 = jnp.zeros(vz.shape, jnp.float32)
    dq, _, _, dk, dv = lax.fori_loop(0, P, hop, (dq0, kz, vz, dk0, dv0))
    # P rotations returned the travelling dk/dv accumulators home; undo
    # the zigzag layout for all three grads (gather = scatter transpose)
    dq = _zigzag_gather(dq, axis_name, seq_dim=2)
    dk = _zigzag_gather(dk, axis_name, seq_dim=2)
    dv = _zigzag_gather(dv, axis_name, seq_dim=2)
    return (dq.astype(qz.dtype), dk.astype(kz.dtype), dv.astype(vz.dtype))


_zigzag_ring_flash.defvjp(_zigzag_fwd_vjp, _zigzag_bwd)


def ring_flash_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                         axis_name: str, causal: bool = False,
                         window: Optional[int] = None, block_q: int = 256,
                         block_k: int = 512,
                         interpret: Optional[bool] = None,
                         zigzag: Optional[bool] = None) -> jnp.ndarray:
    """Ring attention whose per-hop local block runs the Pallas flash
    kernel (VMEM-tiled, never materializing the local ``(sq, sk)`` score
    matrix) instead of the einsum path — the long-context composition of
    sequence parallelism and flash attention. Same semantics and calling
    convention as :func:`ring_attention`; differentiable via the
    global-lse factorization (each hop's backward uses the full ring's
    row statistics, which is exact).

    ``zigzag`` (default: auto — on for full-causal rings) runs the
    balanced schedule: each device owns one early and one late sequence
    chunk, so causal work is uniform across the ring instead of the
    last device computing every hop (~2x wall clock at large ring
    sizes). Windowed rings keep the contiguous layout — the static
    out-of-band hop skip is the better schedule for a narrow band.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if q.shape[1] % k.shape[1]:
        raise ValueError(f"kv heads {k.shape[1]} must divide query heads "
                         f"{q.shape[1]}")
    if zigzag is None:
        zigzag = (causal and window is None and q.shape[2] % 2 == 0
                  and q.shape[2] == k.shape[2])
    if zigzag:
        if not causal or window is not None:
            raise ValueError("zigzag schedule is full-causal only")
        if q.shape[2] != k.shape[2] or q.shape[2] % 2:
            raise ValueError("zigzag needs equal, even q/k shard lengths")
        return _zigzag_ring_flash(q, k, v, axis_name, block_q, block_k,
                                  interpret)
    return _ring_flash(q, k, v, axis_name, causal,
                       int(window) if window is not None else None,
                       block_q, block_k, interpret)


def ring_attention_sharded(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                           mesh: Mesh, seq_axis: str = "seq",
                           causal: bool = False,
                           batch_axis: Optional[str] = None,
                           window: Optional[int] = None,
                           impl: str = "einsum",
                           interpret: Optional[bool] = None) -> jnp.ndarray:
    """shard_map wrapper: global ``(batch, heads, seq, head_dim)`` arrays in,
    sequence sharded over ``seq_axis`` (and optionally batch over
    ``batch_axis``), global attention out.

    ``impl='flash'`` runs each hop's local block through the Pallas flash
    kernel (:func:`ring_flash_attention`) — the TPU path; ``'einsum'`` is
    the XLA reference formulation."""
    batch_spec = batch_axis if batch_axis else None
    spec = PartitionSpec(batch_spec, None, seq_axis, None)

    if impl == "flash":
        local = partial(ring_flash_attention, axis_name=seq_axis,
                        causal=causal, window=window, interpret=interpret)
    elif impl == "einsum":
        local = partial(ring_attention, axis_name=seq_axis, causal=causal,
                        window=window)
    else:
        raise ValueError(f"impl must be 'einsum' or 'flash', got {impl!r}")
    from ..utils.compat import shard_map as _shard_map

    fn = _shard_map(local, mesh=mesh, in_specs=(spec, spec, spec),
                    out_specs=spec, check=False)
    return fn(q, k, v)
