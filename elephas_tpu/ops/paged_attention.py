"""Paged decode attention as a Pallas TPU kernel.

The serving engine's paged decode step
(:func:`~elephas_tpu.models.paged_decode.decode_step_paged`) reads the
KV cache by materializing a gathered view: ``pool[tables]`` copies
every live block into attention order — one extra O(cache) HBM pass
per layer per step — and then runs a plain masked softmax over it.
This module fuses the gather INTO the attention loop: the kernel's
``BlockSpec`` index map reads the block table (scalar-prefetched into
SMEM) and DMAs each block of k/v straight from its pool slot into
VMEM, accumulating flash-style online softmax across the row's blocks.
The (B, MB*bs, D) gathered view is never materialized.

Grid ``(batch, max_blocks)`` with the block axis innermost
(sequential): one program attends one row's query heads against one
pool block. GQA runs as an unrolled loop over kv heads inside the
kernel — each kv head's ``groups`` query rows share its k/v tile.
Blocks entirely past the row's position (or entirely outside its
sliding window) are skipped before any compute. ALiBi biases are baked
in as compile-time constants (slopes are a pure function of the head
count). All accumulation is f32 regardless of pool dtype.

This kernel covers the S=1 decode step — the tokens/s hot path, where
the gather pass is pure overhead. The S>1 verify pass of speculative
decoding keeps the gather path (its cost amortizes over gamma+1
positions and its mask is 2-D).

Numerics: online softmax is algebraically identical to the gather
path's full-row softmax but associates the reduction differently, so
logits agree to float rounding (parity-tested across the attention
variant matrix), not bit-for-bit.

On non-TPU backends the kernel runs via the Pallas interpreter
(``interpret=True``) — correct but slow, which is why the ENGINE falls
back to the gather path off-TPU and only the parity tests drive the
interpreter directly.
"""
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_attention import NEG_INF, _CompilerParams, _use_interpret

__all__ = ["paged_decode_attention", "pallas_supported"]


def pallas_supported() -> bool:
    """True when the compiled (non-interpreted) kernel can run here —
    the engine's ``kernel="pallas"`` fallback check."""
    return jax.default_backend() == "tpu"


def _paged_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, bs: int, kvh: int,
                  groups: int, scale: float, window: Optional[int],
                  slopes: Optional[tuple]):
    b = pl.program_id(0)
    j = pl.program_id(1)
    nb = pl.num_programs(1)
    pos = pos_ref[b, 0]

    @pl.when(j == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # skip blocks wholly past the causal frontier — and, with a sliding
    # window, wholly before it. Table entries past the row's allocation
    # are the scratch sink (id 0): their positions sit past ``pos`` so
    # this same predicate skips them without reading them.
    live = j * bs <= pos
    if window is not None:
        live = live & (j * bs + bs - 1 > pos - window)

    @pl.when(live)
    def _():
        kpos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (1, bs), 1)
        valid = kpos <= pos
        if window is not None:
            valid = valid & (kpos > pos - window)
        if slopes is not None:
            dist = (pos - kpos).astype(jnp.float32)        # (1, bs)
        for n in range(kvh):
            lo = n * groups
            qh = q_ref[0, lo:lo + groups, :]               # (G, D)
            s = jax.lax.dot_general(
                qh, k_ref[0, n], (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # (G, bs)
            if slopes is not None:
                # slopes are python floats (compile-time constants):
                # scalar multiplies, no captured-array constant
                s = s - jnp.concatenate(
                    [dist * slopes[lo + g] for g in range(groups)],
                    axis=0)
            s = jnp.where(valid, s, NEG_INF)
            m_prev = m_ref[lo:lo + groups, 0]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1))
            p = jnp.where(valid, jnp.exp(s - m_new[:, None]), 0.0)
            corr = jnp.exp(m_prev - m_new)
            l_ref[lo:lo + groups, 0] = (l_ref[lo:lo + groups, 0] * corr
                                        + jnp.sum(p, axis=-1))
            acc_ref[lo:lo + groups, :] = (
                acc_ref[lo:lo + groups, :] * corr[:, None]
                + jax.lax.dot_general(
                    p.astype(v_ref.dtype), v_ref[0, n],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32))
            m_ref[lo:lo + groups, 0] = m_new

    @pl.when(j == nb - 1)
    def _():
        l = l_ref[:, 0]
        o_ref[0] = (acc_ref[:]
                    / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def paged_decode_attention(q: jnp.ndarray, k_pool: jnp.ndarray,
                           v_pool: jnp.ndarray, tables: jnp.ndarray,
                           pos: jnp.ndarray,
                           window: Optional[int] = None,
                           alibi_slopes=None,
                           interpret: Optional[bool] = None
                           ) -> jnp.ndarray:
    """Single-position paged attention straight off the block pool.

    :param q: ``(B, num_heads, head_dim)`` queries (positional encoding
        already applied — the kernel sees post-RoPE values, exactly what
        the gather path's einsum sees).
    :param k_pool: ``(num_blocks, kv_heads, block_size, head_dim)``
        pool tensor AFTER this step's k scatter (the current position's
        key is already in its owning block).
    :param v_pool: same shape, values.
    :param tables: ``(B, max_blocks)`` int block ids per row.
    :param pos: ``(B,)`` int current position per row; keys at
        ``kpos <= pos`` (within ``window`` if set) are attended.
    :param window: optional sliding-window width (attend
        ``kpos > pos - window``).
    :param alibi_slopes: optional per-query-head slope array ``(H,)``;
        adds the ``-slope * (pos - kpos)`` ALiBi bias. Must be
        CONCRETE (slopes are a function of the head count, not of
        data) — they are baked into the kernel as constants.
    :param interpret: force/forbid the Pallas interpreter; default
        auto (compiled on TPU, interpreter elsewhere).
    :returns: ``(B, num_heads, head_dim)`` attention output in
        ``q.dtype``.
    """
    b, h, d = q.shape
    _, kvh, bs, _ = k_pool.shape
    if h % kvh:
        raise ValueError(f"kv heads {kvh} must divide query heads {h}")
    mb = tables.shape[1]
    slopes = None
    if alibi_slopes is not None:
        sl = np.asarray(alibi_slopes, np.float32).reshape(-1)
        if sl.shape[0] != h:
            raise ValueError(f"{sl.shape[0]} ALiBi slopes for {h} heads")
        slopes = tuple(float(s) for s in sl)
    kernel = functools.partial(
        _paged_kernel, bs=bs, kvh=kvh, groups=h // kvh,
        scale=1.0 / math.sqrt(d),
        window=int(window) if window is not None else None,
        slopes=slopes)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, mb),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda bi, j, tbl, ps: (bi, 0, 0)),
            # the fused gather: the index map reads the row's table and
            # streams that pool block HBM -> VMEM, no gathered copy
            pl.BlockSpec((1, kvh, bs, d),
                         lambda bi, j, tbl, ps: (tbl[bi, j], 0, 0, 0)),
            pl.BlockSpec((1, kvh, bs, d),
                         lambda bi, j, tbl, ps: (tbl[bi, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d),
                               lambda bi, j, tbl, ps: (bi, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
            pltpu.VMEM((h, 1), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_use_interpret(interpret),
    )(jnp.asarray(tables, jnp.int32),
      jnp.asarray(pos, jnp.int32).reshape(b, 1), q, k_pool, v_pool)
