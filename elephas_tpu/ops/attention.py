"""Attention ops: reference softmax attention + blockwise variants.

The reference framework has no attention (its largest model is an MLP;
long-context is absent per SURVEY.md §5), but the TPU framework treats
long-context as first-class: :mod:`.ring_attention` scales sequence length
across the mesh, and this module holds the single-device building blocks.

All shapes are ``(batch, heads, seq, head_dim)``.
"""
import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
              causal: bool = False,
              mask: Optional[jnp.ndarray] = None,
              bias: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Plain softmax attention (reference implementation / XLA-fused
    path). ``bias`` (broadcastable to ``(B, H, Tq, Tk)``, e.g. T5
    relative-position bias) adds to the scaled scores before masking."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        scores = scores + bias
    if causal:
        q_pos = jnp.arange(q.shape[2])[:, None]
        k_pos = jnp.arange(k.shape[2])[None, :]
        scores = jnp.where(k_pos <= q_pos, scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


@partial(jax.jit, static_argnames=("block_size", "causal"))
def blockwise_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        block_size: int = 512,
                        causal: bool = False) -> jnp.ndarray:
    """Memory-bounded attention via online softmax over key/value blocks.

    The flash-attention recurrence: never materializes the full
    ``(seq, seq)`` score matrix, so HBM footprint is O(seq * block).
    """
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    nb = -(-sk // block_size)
    pad = nb * block_size - sk
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    k_blocks = k.reshape(b, h, nb, block_size, d)
    v_blocks = v.reshape(b, h, nb, block_size, d)
    q_pos = jnp.arange(sq)[:, None]

    def body(carry, inputs):
        o, l, m = carry
        k_blk, v_blk, blk_idx = inputs
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        k_pos = blk_idx * block_size + jnp.arange(block_size)[None, :]
        valid = k_pos < sk
        if causal:
            valid = valid & (k_pos <= q_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        o_new = o * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        return (o_new, l_new, m_new), None

    o0 = jnp.zeros_like(q)
    l0 = jnp.zeros((b, h, sq), dtype=q.dtype)
    m0 = jnp.full((b, h, sq), NEG_INF, dtype=q.dtype)
    ks = jnp.moveaxis(k_blocks, 2, 0)
    vs = jnp.moveaxis(v_blocks, 2, 0)
    (o, l, _), _ = jax.lax.scan(body, (o0, l0, m0),
                                (ks, vs, jnp.arange(nb)))
    return o / jnp.maximum(l, 1e-20)[..., None]
