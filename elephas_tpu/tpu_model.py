"""TPUModel: the distributed training/inference/evaluation API.

The capability mirror of the reference's ``SparkModel``/``SparkMLlibModel``
(``elephas/spark_model.py:28-352``), re-architected single-controller:

- The "cluster" is a :class:`jax.sharding.Mesh`; "broadcast" is replicated
  sharding; "collect + driver merge" is an all-reduce inside one jitted
  program (synchronous mode), so the reference's O(params x workers) numpy
  merge loop on the driver does not exist here.
- ``mode='synchronous'`` keeps the reference's *semantics* (each worker
  trains a full local copy, deltas are averaged once,
  ``elephas/spark_model.py:217-228``) by default (``sync_mode='average'``);
  ``sync_mode='step'`` switches to true per-step synchronous SGD — the
  benchmark configuration.
- ``mode='asynchronous' | 'hogwild'`` run parameter-server training with
  the reference's pull/train/push loop at ``epoch`` or ``batch``
  frequency over HTTP or raw-TCP transports.
- Distributed predict preserves input order by construction (contiguous
  shards) instead of the reference's zipWithIndex/sortBy dance; distributed
  evaluate is the sample-count-weighted reduction.
"""
import json
import subprocess
from copy import deepcopy
from pathlib import Path
from typing import Any, Dict, List, Optional, Union
from uuid import uuid4

import h5py
import numpy as np

from .data.dataset import Dataset
from .mllib.adapter import from_matrix, from_vector, to_matrix, to_vector
from .mllib.linalg import Matrix, Vector
from .models import deserialize_optimizer, get_optimizer, serialize_optimizer
from .models.core import BaseModel
from .models.saving import load_model
from .parameter.factory import get_transport
from .utils.dataset_utils import lp_to_dataset, to_dataset
from .utils.serialization import model_to_dict
from .worker import AsyncWorker


def _temp_model_path(file_name: str) -> str:
    """Unique local staging filename carrying ``file_name``'s suffix —
    used for hadoop/object-store transfers in both directions."""
    return str(uuid4()) + "-temp-model-file." + file_name.split(".")[-1]


def _run_hadoop(cli, detail: str = ""):
    """Run a ``hadoop fs`` command, raising on ANY failure — a missing
    CLI or non-zero exit must never read as success (the reference
    swallows both, ``spark_model.py:127-134``)."""
    suffix = f" {detail}" if detail else ""
    try:
        proc = subprocess.run(cli, capture_output=True, text=True)
    except FileNotFoundError:
        raise RuntimeError(
            f"hadoop CLI not found — cannot run {' '.join(cli)}{suffix}")
    if proc.returncode != 0:
        raise RuntimeError(
            f"{' '.join(cli[:3])} failed (rc={proc.returncode}): "
            f"{proc.stderr.strip() or proc.stdout.strip()}{suffix}")


class _EpochAggregator:
    """Turns per-worker epoch completions into driver-level epoch_end.

    Each async worker reports ``(epoch, mean_loss)`` after its local
    epoch; when all participants have reported epoch k the aggregator
    fires ``on_epoch(k, logs)`` (on the last reporter's thread — workers
    train concurrently, so a worker can only reach epoch k+1 after
    emitting its own k event, which keeps firings ordered). ``on_epoch``
    returning True latches the stop flag every worker polls at its epoch
    boundaries — EarlyStopping that actually stops asynchronous training
    mid-run.

    A dead worker must not park every callback forever: the supervisor
    calls :meth:`remove_participant` when it declares a worker failed,
    which shrinks the quorum and immediately fires any epoch the
    survivors have already completed.
    """

    def __init__(self, participants: int, on_epoch):
        import threading

        self.participants = max(1, participants)
        self.on_epoch = on_epoch
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {}
        self._losses: Dict[int, List[float]] = {}
        self._fired: set = set()
        self._member_epochs: Dict[Any, set] = {}
        self._stop = threading.Event()

    def _fire_locked(self, epoch: int):
        # fire under the lock: callbacks mutate the master network,
        # and serializing here keeps reports cheap (callbacks are
        # epoch-granular)
        self._fired.add(epoch)
        losses = self._losses.pop(epoch, [])
        logs = {"loss": float(np.mean(losses))} if losses else {}
        if self.on_epoch(epoch, logs):
            self._stop.set()

    def report(self, epoch: int, loss: Optional[float], member=None):
        with self._lock:
            if epoch in self._fired:
                return  # late report for an epoch fired after a removal
            if member is not None:
                seen = self._member_epochs.setdefault(member, set())
                if epoch in seen:
                    # idempotent per member: a re-run of the same shard
                    # (after a PS restart) re-reports epochs it already
                    # counted — they must not stand in for other members
                    return
                seen.add(epoch)
            self._counts[epoch] = self._counts.get(epoch, 0) + 1
            if loss is not None:
                self._losses.setdefault(epoch, []).append(float(loss))
            if (self.participants <= 0
                    or self._counts[epoch] < self.participants):
                return
            self._fire_locked(epoch)

    def remove_participant(self, member=None):
        """A participant died: shrink the quorum and fire every pending
        epoch the survivors have already fully reported — the stall fix
        for EarlyStopping/ModelCheckpoint waiting on a dead worker.

        The dead member's own reports are retracted from unfired epochs
        first (its ``member`` key as passed to :meth:`report`): a count
        it contributed must not stand in for a live survivor still
        mid-epoch, or the epoch would fire early."""
        with self._lock:
            self.participants -= 1
            for epoch in self._member_epochs.pop(member, ()):
                if epoch not in self._fired and self._counts.get(epoch, 0):
                    self._counts[epoch] -= 1
            if self.participants <= 0:
                return  # nobody left; the supervisor policy decides
            for epoch in sorted(self._counts):
                if (epoch not in self._fired
                        and self._counts[epoch] >= self.participants):
                    self._fire_locked(epoch)

    def rejoin_if_empty(self) -> bool:
        """Re-register one participant iff every participant has been
        removed. A re-run normally reports no epoch events (its dead
        predecessor's role is gone), but when NOBODY is left reporting
        — single-worker fit, or a blip that felled every worker — the
        retry must take the role back or callbacks go silently dead for
        the rest of the fit."""
        with self._lock:
            if self.participants > 0:
                return False
            self.participants = 1
            return True

    def should_stop(self) -> bool:
        return self._stop.is_set()


class TPUModel:
    """Distributed model: train/predict/evaluate over a TPU device mesh.

    :param model: compiled :class:`~elephas_tpu.models.Sequential` or
        :class:`~elephas_tpu.models.Model`
    :param mode: ``asynchronous`` (default), ``synchronous`` or ``hogwild``
    :param frequency: ``epoch`` or ``batch`` — async update granularity
    :param parameter_server_mode: ``http`` or ``socket``
    :param num_workers: worker/partition count (defaults to dataset
        partitioning, which defaults to the device count)
    :param custom_objects: registry for custom layers/activations/losses
    :param batch_size: training/inference batch size default
    :param port: parameter-server port
    :param sync_mode: ``average`` (reference model-averaging semantics) or
        ``step`` (per-step sync SGD; throughput configuration)
    :param on_worker_failure: async/hogwild failure policy —
        ``reassign`` (default: a failed worker's shard is re-run on a
        surviving slot, bounded by ``max_worker_restarts`` per shard),
        ``fail`` (fail-fast) or ``continue`` (drop the shard while at
        least a ``min_workers`` fraction of shards completes)
    :param ps_auto_restart: supervise the parameter server too: snapshot
        it while healthy and restart it from the latest snapshot on the
        same port if it dies mid-fit (probed every
        ``ps_probe_interval`` seconds); workers reconnect via retry.
        A sharded plane is supervised per shard: only the dead shard is
        rebuilt (from its own snapshot) while the survivors keep serving
    :param ps_shards: partition the weight list across this many
        parameter servers on consecutive ports ``port..port+N-1``
        (greedy byte-size bin-packing), with a fan-out client that
        pulls/pushes all shards on parallel connections — lifts the
        single-server RPC ceiling on async training (default 1)
    :param ps_pipeline: double-buffer delta pushes in the
        reference-parity worker loops: the push for batch/epoch *k*
        overlaps computation of *k+1* (one in-flight push max, staleness
        bounded at 1, errors surfaced at the next sync point). Subsumed
        by ``async_overlap`` only at batch frequency, where the
        overlapped communicator runs and already pipelines its RPCs
    :param ps_standby: arm one warm STANDBY server per shard (ports
        ``port+N..port+2N-1``), fed by its primary's applied-delta
        stream; ``ps_auto_restart`` supervision then PROMOTES the
        standby on primary death — zero applied-update loss, epoch-
        fenced against zombie primaries — and only falls back to
        snapshot-restart when no healthy standby exists. Requires
        ``ps_shards >= 2``
    """

    def __init__(self, model: BaseModel, mode: str = "asynchronous",
                 frequency: str = "epoch", parameter_server_mode: str = "http",
                 num_workers: Optional[int] = None,
                 custom_objects: Optional[Dict] = None, batch_size: int = 32,
                 port: int = 4000, *args, **kwargs):
        self._training_histories: List = []
        self._master_network = model
        if not model.compiled:
            raise Exception(
                "Compile your model before initializing an elephas_tpu model "
                "with it")
        if not model.built:
            raise Exception(
                "Build your model (known input shape) before initializing an "
                "elephas_tpu model with it")
        self.mode = mode
        self.frequency = frequency
        self.num_workers = num_workers
        self.weights = model.get_weights()
        self.master_optimizer = serialize_optimizer(model.optimizer)
        self.master_loss = model.loss
        self.master_metrics = list(model.metrics or [])
        self.custom_objects = custom_objects or {}
        self.parameter_server_mode = parameter_server_mode
        self.batch_size = batch_size
        self.port = port
        self.sync_mode = kwargs.pop("sync_mode", "average")
        if self.sync_mode not in ("average", "step"):
            raise ValueError(
                "sync_mode must be 'average' or 'step', got "
                f"{self.sync_mode!r}")
        # async throughput knobs (batch frequency): background RPC overlap
        # + on-device delta accumulation window (1 = reference semantics)
        self.async_overlap = bool(kwargs.pop("async_overlap", False))
        self.async_accum = max(1, int(kwargs.pop("async_accum", 1)))
        # int8 delta compression on the PS wire (~4x fewer push bytes;
        # workers carry EF residuals so training stays unbiased)
        self.delta_compression = kwargs.pop("delta_compression", None)
        if self.delta_compression not in (None, "int8"):
            raise ValueError("delta_compression must be None or 'int8', "
                             f"got {self.delta_compression!r}")
        # elastic supervision (async/hogwild): what to do when a worker
        # thread dies mid-fit — 'reassign' re-runs its shard (bounded by
        # max_worker_restarts per shard), 'fail' is fail-fast, 'continue'
        # drops the shard while at least a min_workers fraction succeeds
        from .parallel.supervisor import POLICIES

        self.on_worker_failure = kwargs.pop("on_worker_failure", "reassign")
        if self.on_worker_failure not in POLICIES:
            raise ValueError(
                f"on_worker_failure must be one of {POLICIES}, "
                f"got {self.on_worker_failure!r}")
        self.max_worker_restarts = max(
            0, int(kwargs.pop("max_worker_restarts", 2)))
        self.min_workers = float(kwargs.pop("min_workers", 0.5))
        if not (0.0 < self.min_workers <= 1.0):
            # fail at construction, not mid-fit after the PS is up
            raise ValueError(
                f"min_workers must be in (0, 1], got {self.min_workers}")
        # PS crash survivability: when True, the supervisor health-probes
        # the parameter server, snapshots it while healthy, and restarts
        # it from the latest snapshot on the same port if it dies —
        # workers reconnect through the client retry path
        self.ps_auto_restart = bool(kwargs.pop("ps_auto_restart", False))
        self.ps_probe_interval = float(kwargs.pop("ps_probe_interval", 2.0))
        if self.ps_probe_interval <= 0:
            # fail at construction: 0 would busy-spin the PS monitor
            raise ValueError(
                f"ps_probe_interval must be > 0, got "
                f"{self.ps_probe_interval}")
        self.max_ps_restarts = max(0, int(kwargs.pop("max_ps_restarts", 5)))
        # sharded parameter plane: partition the weight list across N
        # servers on ports port..port+N-1 (greedy byte-size bin-packing)
        # so pulls/pushes fan out in parallel instead of funneling
        # through one server's RPC throughput
        self.ps_shards = int(kwargs.pop("ps_shards", 1))
        if self.ps_shards < 1:
            raise ValueError(f"ps_shards must be >= 1, got {self.ps_shards}")
        # pipelined async push: the delta push for batch/epoch k runs on
        # a background thread and overlaps computation of k+1 (one
        # in-flight push max, staleness bounded at 1)
        self.ps_pipeline = bool(kwargs.pop("ps_pipeline", False))
        # hot-standby failover (sharded plane): one warm standby per
        # shard fed by the primary's applied-delta stream; supervision
        # PROMOTES it on primary death (zero applied-update loss)
        # instead of restarting from a snapshot
        self.ps_standby = bool(kwargs.pop("ps_standby", False))
        if self.ps_standby and self.ps_shards < 2:
            raise ValueError(
                "ps_standby requires a sharded plane (ps_shards >= 2); "
                "single-server recovery is snapshot-restart")
        self.kwargs = kwargs

        self.serialized_model = model_to_dict(model)
        self.parameter_server = None
        self.client = None
        if self.mode != "synchronous":
            from .parameter.factory import create_sharded_server

            self.parameter_server = create_sharded_server(
                self.parameter_server_mode, self.serialized_model,
                self.port, self.mode, self.ps_shards,
                standby=self.ps_standby,
                custom_objects=self.custom_objects)
            self.client = self._make_client()

        self._replica = None  # lazily-built worker replica for predict/eval
        # trainers cached across fit() calls so their jitted epoch
        # programs survive; keyed by the compile-level config
        self._trainer_cache = {}
        self._replica_src = None  # master params the replica last adopted
        self._predict_fn = None
        self._evaluate_fn = None

    # ------------------------------------------------------------------ admin
    def get_config(self) -> Dict:
        base_config = {
            "parameter_server_mode": self.parameter_server_mode,
            "mode": self.mode,
            "frequency": self.frequency,
            "num_workers": self.num_workers,
            "batch_size": self.batch_size,
        }
        config = base_config.copy()
        if self.sync_mode != "average":
            config["sync_mode"] = self.sync_mode
        if self.async_overlap:
            config["async_overlap"] = True
        if self.async_accum != 1:
            config["async_accum"] = self.async_accum
        if self.on_worker_failure != "reassign":
            config["on_worker_failure"] = self.on_worker_failure
        if self.max_worker_restarts != 2:
            config["max_worker_restarts"] = self.max_worker_restarts
        if self.min_workers != 0.5:
            config["min_workers"] = self.min_workers
        if self.ps_auto_restart:
            config["ps_auto_restart"] = True
        if self.ps_probe_interval != 2.0:
            config["ps_probe_interval"] = self.ps_probe_interval
        if self.max_ps_restarts != 5:
            config["max_ps_restarts"] = self.max_ps_restarts
        if self.ps_shards != 1:
            config["ps_shards"] = self.ps_shards
        if self.ps_pipeline:
            config["ps_pipeline"] = True
        if self.ps_standby:
            config["ps_standby"] = True
        config.update(self.kwargs)
        return config

    @property
    def training_histories(self):
        return self._training_histories

    @property
    def master_compute_dtype(self) -> Optional[str]:
        """The master's compile-level mixed-precision dtype, read live so
        a recompile is seen by workers and replicas alike."""
        dt = getattr(self._master_network, "_compute_dtype", None)
        return str(dt) if dt is not None else None

    @property
    def master_network(self) -> BaseModel:
        return self._master_network

    @master_network.setter
    def master_network(self, network: BaseModel):
        self._master_network = network

    def start_server(self):
        self.parameter_server.start()

    def _make_client(self):
        """A parameter client matching the configured plane — plain
        transport client, or a sharded fan-out client derived from the
        same deterministic shard plan the server group uses."""
        from .parameter.factory import create_sharded_client

        return create_sharded_client(
            self.parameter_server_mode, self.port, self.serialized_model,
            self.ps_shards, compression=self.delta_compression)

    def _ps_supervision(self):
        """(probe, restart) hooks for the worker supervisor's parameter-
        server watchdog. The probe snapshots the live server while it is
        healthy; restart rebuilds a server of the same transport on the
        same port from the latest snapshot and starts it — workers
        reconnect through the client retry path, with the idempotency
        window carried over so in-flight resends stay deduplicated.

        A sharded plane supervises per shard: each shard is probed and
        snapshotted independently, and a restart rebuilds ONLY the dead
        shard(s) from their own snapshots while the survivors keep
        serving."""
        from .parameter.sharding import ShardedServerGroup

        if isinstance(self.parameter_server, ShardedServerGroup):
            return self._sharded_ps_supervision()
        import time as _time

        state = {"snapshot": self.parameter_server.snapshot(),
                 "t": _time.monotonic()}
        state["at"] = state["snapshot"]["num_updates"]
        # snapshotting copies every weight under the server's read lock;
        # during active training every probe would otherwise pay it, so
        # the copy cadence is floored well below the probe cadence (the
        # price: a restart rolls back at most this much progress)
        min_spacing = max(5 * self.ps_probe_interval, 2.0)

        def probe() -> bool:
            if not self.client.health_check():
                return False
            try:
                server = self.parameter_server
                now = _time.monotonic()
                if (server.num_updates != state["at"]
                        and now - state["t"] >= min_spacing):
                    snap = server.snapshot()
                    state["snapshot"] = snap
                    state["at"] = snap["num_updates"]
                    state["t"] = now
            except Exception:
                pass  # keep serving the previous snapshot
            return True

        def restart():
            try:
                self.parameter_server.stop()  # release the port/threads
            except Exception:
                pass
            transport = get_transport(self.parameter_server_mode)
            server = transport.create_server(
                self.serialized_model, self.port, self.mode,
                custom_objects=self.custom_objects)
            server.restore(state["snapshot"])
            server.start()
            self.parameter_server = server

        return probe, restart

    def _sharded_ps_supervision(self):
        """Per-shard (probe, restart) hooks for a sharded plane. The
        probe health-checks every shard through its own sub-client and
        snapshots each healthy shard on the same cadence the
        single-server path uses; restart rebuilds only the shards whose
        probe failed, each from ITS latest snapshot on its own port —
        the surviving shards never stop serving."""
        import time as _time

        group = self.parameter_server
        subs = self.client.clients     # one probe lane per shard
        now = _time.monotonic()
        state = [{"snapshot": group.snapshot_shard(i), "t": now}
                 for i in range(group.num_shards)]
        for st in state:
            st["at"] = st["snapshot"]["num_updates"]
        min_spacing = max(5 * self.ps_probe_interval, 2.0)

        def probe() -> bool:
            ok = True
            for i, sub in enumerate(subs):
                if not sub.health_check():
                    ok = False
                    continue
                try:
                    server = group.servers[i]
                    t = _time.monotonic()
                    if (server.num_updates != state[i]["at"]
                            and t - state[i]["t"] >= min_spacing):
                        snap = server.snapshot()
                        state[i] = {"snapshot": snap,
                                    "at": snap["num_updates"], "t": t}
                except Exception:
                    pass  # keep serving the previous snapshot
            return ok

        def restart():
            for i, sub in enumerate(subs):
                if sub.health_check():
                    continue       # this shard is fine — leave it alone
                # hot-standby first: promotion loses ZERO applied
                # updates (every acked delta is already on the standby)
                # and fences the dead primary's epoch; snapshot-restart
                # is the no-standby (or unhealthy-standby) fallback,
                # which loses post-snapshot deltas — the documented
                # lossy trade
                if group.promote_shard(i) is not None:
                    continue
                group.restart_shard(i, state[i]["snapshot"])

        return probe, restart

    def stop_server(self):
        if self.client is not None:
            self.client.close()  # drop the persistent PS connection
        self.parameter_server.stop()

    # ------------------------------------------------------------------- save
    def save(self, file_name: str, overwrite: bool = False,
             to_hadoop: bool = False):
        """Save model + distributed config to h5/keras, optionally pushing
        the file to a Hadoop cluster (parity: ``elephas/spark_model.py:92-134``).

        ``file_name`` may also be an object-store URL (``gs://...``,
        ``s3://...`` — the Cloud TPU analog of the hadoop path): the file
        is written locally and uploaded through the scheme's registered
        :mod:`~elephas_tpu.utils.storage` adapter."""
        assert (file_name[-3:] == ".h5" or file_name[-6:] == ".keras"), \
            "File name must end with either '.h5' or '.keras'"
        from .utils.storage import get_store, is_remote

        remote_url = None
        if is_remote(file_name):
            if to_hadoop:
                raise ValueError("to_hadoop and an object-store URL are "
                                 "mutually exclusive")
            remote_url = file_name
            file_name = _temp_model_path(file_name)

        if overwrite and not to_hadoop and remote_url is None \
                and Path(file_name).exists():
            Path(file_name).unlink()

        if to_hadoop:
            cluster_file_path = deepcopy(file_name)
            file_name = _temp_model_path(file_name)

        model = self._master_network
        model.save(file_name, overwrite=True)
        with h5py.File(file_name, mode="a") as f:
            f.attrs["distributed_config"] = json.dumps({
                "class_name": self.__class__.__name__,
                "config": self.get_config(),
            }).encode("utf8")

        if to_hadoop:
            cli = ["hadoop", "fs", "-moveFromLocal"]
            if overwrite:
                cli.append("-f")
            cli.extend([file_name, cluster_file_path])
            # a failed put must raise, not silently "succeed" (the
            # reference swallows this — spark_model.py:127-134 — but
            # silent success on save is data loss)
            _run_hadoop(cli, f"(local copy kept at {file_name})")
        elif remote_url is not None:
            store = get_store(remote_url)
            if not overwrite and store.exists(remote_url):
                Path(file_name).unlink()
                raise FileExistsError(
                    f"{remote_url} exists (pass overwrite=True)")
            try:
                store.put_file(file_name, remote_url)
            finally:
                Path(file_name).unlink(missing_ok=True)

    # ------------------------------------------------------------------- data
    def _as_dataset(self, data, with_labels: bool = True) -> Dataset:
        if isinstance(data, Dataset):
            ds = data
        elif isinstance(data, tuple) and len(data) == 2:
            ds = to_dataset(data[0], data[1])
        elif isinstance(data, np.ndarray):
            ds = Dataset((data,))
        elif isinstance(data, (list,)):
            ds = Dataset.from_pairs(data) if with_labels else Dataset((np.asarray(data),))
        else:
            raise ValueError(f"Cannot interpret training data: {type(data)}")
        if not ds.is_columnar:
            ds = Dataset.from_pairs(ds.rows(), num_partitions=ds._num_partitions)
        return ds

    # -------------------------------------------------------------------- fit
    def fit(self, dataset: Union[Dataset, tuple], **kwargs):
        """Distributed training over a partitioned dataset.

        Multi-host (DCN) execution: when launched as a JAX-distributed
        program (``JAX_COORDINATOR_ADDRESS``/``JAX_NUM_PROCESSES`` env, or
        TPU-pod auto-detection via :func:`initialize_multihost`), every
        process calls ``fit`` with the same dataset. Synchronous modes
        train over the global mesh spanning all hosts' devices; async
        modes start the parameter server on the coordinator and run each
        host's workers against it over DCN.

        :param dataset: pair :class:`Dataset` or ``(features, labels)``
        :param epochs, batch_size, verbose, validation_split: as in Keras
        """
        from .models.ssm_model import SSMModel
        from .models.transformer_model import TransformerModel
        from .parallel.multihost import ensure_multihost

        ensure_multihost()
        if isinstance(self._master_network, (TransformerModel, SSMModel)):
            self._fit_transformer(dataset, **kwargs)
            return
        ds = self._as_dataset(dataset)
        if self.num_workers:
            ds = ds.repartition(self.num_workers)

        if self.mode in ["asynchronous", "synchronous", "hogwild"]:
            self._fit(ds, **kwargs)
        else:
            raise ValueError(
                "Choose from one of the modes: asynchronous, synchronous "
                "or hogwild")

    def _fit(self, ds: Dataset, **kwargs):
        train_config = dict(kwargs)
        train_config.setdefault("batch_size", self.batch_size)
        self._refresh_replica()

        # driver-level callbacks: per-epoch hooks for sync_mode='step'
        # (whose epoch loop runs on the driver) and for async/hogwild
        # (worker epoch events aggregated by _EpochAggregator, with live
        # PS pulls); round-level (one epoch_end per fit) only for model
        # averaging, whose epochs run inside one compiled program
        from .models.callbacks import CallbackList

        callbacks = train_config.pop("callbacks", None)
        cbs = CallbackList(callbacks, self._master_network)
        self._master_network.stop_training = False
        cbs.train_begin()
        histories_before = len(self._training_histories)

        # train_end must fire even when fit raises (interrupt, callback
        # error): async ModelCheckpoint flushes its background writes
        # there, and a skipped flush leaves a torn manifest racing any
        # restore the user attempts from the except handler
        try:
            if self.mode == "synchronous":
                if self.sync_mode == "step":
                    self._fit_sync_step(ds, callbacks=cbs, **train_config)
                else:
                    self._fit_sync_average(ds, **train_config)
            elif self.mode in ("asynchronous", "hogwild"):
                self._fit_async(ds, callbacks=cbs, **train_config)
            else:
                raise ValueError("Unsupported mode {}".format(self.mode))

            if cbs and self.mode == "synchronous" and self.sync_mode == "average":
                # model averaging runs all epochs inside one compiled program,
                # so callbacks get one round-level epoch_end: mean of each
                # metric's final value across THIS fit's worker histories.
                # (sync-step and async modes fire real per-epoch hooks.)
                new_histories = self._training_histories[histories_before:]
                sums: Dict[str, list] = {}
                for hist in new_histories:
                    for k, v in hist.items():
                        if v:
                            sums.setdefault(k, []).append(v[-1])
                cbs.epoch_end(0, {k: float(np.mean(v))
                                  for k, v in sums.items()})
        finally:
            cbs.train_end()

    def _fit_transformer(self, data, epochs: int = 10,
                         batch_size: Optional[int] = None,
                         verbose: int = 0, validation_split: float = 0.1,
                         **kwargs):
        """Train an LM family (:class:`TransformerModel` /
        :class:`SSMModel`) through the same callback/history/checkpoint
        plumbing as the Keras-style models.

        LM training is per-step synchronous SGD over the device mesh
        (the ``sync_mode='step'`` semantics); parameter-server modes
        target the delta-exchange Keras-style models."""
        if self.mode != "synchronous":
            raise ValueError(
                "LM families train synchronously (per-step sync SGD "
                "over the device mesh); asynchronous/hogwild parameter-"
                "server modes apply to the Keras-style models")
        from .models.ssm_model import SSMModel

        import jax

        net = self._master_network
        if (isinstance(net, SSMModel) and net.mesh is None
                and len(jax.devices()) > 1):
            # hand the SSM its dp mesh (TransformerModel builds its own)
            from jax.sharding import Mesh

            net.attach_mesh(Mesh(np.array(jax.devices()),
                                 (net.data_axis,)))
        # TransformerModel.fit owns the callback plumbing (CallbackList,
        # stop_training, train_begin/end) — one implementation, not two
        history = self._master_network.fit(
            self._extract_tokens(data), epochs=epochs,
            batch_size=batch_size or self.batch_size, verbose=verbose,
            validation_split=validation_split,
            callbacks=kwargs.pop("callbacks", None),
            seed=kwargs.get("seed", 0))
        self._training_histories.append(history)

    @staticmethod
    def _extract_tokens(data):
        """Token rows from a Dataset / (tokens, labels) pair / array — LM
        targets are the shifted input, so any label column is ignored.
        Returns an ndarray, or a lazy ColumnSource passed through unread
        (predict streams those batch-at-a-time; fit materializes them)."""
        from .data.sources import ColumnSource

        if isinstance(data, Dataset):
            return (data.columns[0] if data.is_columnar
                    else np.asarray(data.rows()))
        if isinstance(data, tuple) and len(data) == 2:
            data = data[0]
        if isinstance(data, ColumnSource):
            return data
        return np.asarray(data)

    def _worker_metric_fns(self):
        from .models import metrics as metrics_mod

        return [metrics_mod.get(m, loss=self.master_loss,
                                custom_objects=self.custom_objects)
                for m in self.master_metrics]

    def _fit_sync_average(self, ds: Dataset, epochs: int = 10,
                          batch_size: int = 32, verbose: int = 0,
                          validation_split: float = 0.1, **kwargs):
        from .parallel.sync_trainer import SyncAverageTrainer

        replica = self._get_replica()
        trainer = self._cached_trainer(
            "sync_average", lambda: SyncAverageTrainer(
                replica, deserialize_optimizer(self.master_optimizer),
                self.master_loss, self._worker_metric_fns(),
                self.custom_objects))
        shards = ds.partitions()
        new_weights, histories = trainer.run(
            self._master_network.get_weights(), shards, epochs=epochs,
            batch_size=batch_size, validation_split=validation_split,
            seed=kwargs.get("seed", 0))
        for history in histories:
            if history is not None:
                self._training_histories.append(history)
        self._master_network.set_weights(new_weights)

    def _fit_sync_step(self, ds: Dataset, epochs: int = 10,
                       batch_size: int = 32, verbose: int = 0,
                       validation_split: float = 0.1, callbacks=None,
                       **kwargs):
        from .parallel.sync_trainer import SyncStepTrainer

        replica = self._get_replica()
        trainer = self._cached_trainer(
            "sync_step", lambda: SyncStepTrainer(
                replica, deserialize_optimizer(self.master_optimizer),
                self.master_loss, self._worker_metric_fns(),
                self.custom_objects))
        x, y = ds.to_arrays()

        epoch_callback = None
        if callbacks:
            def epoch_callback(epoch_idx, logs):
                # the trainer synced the replica's resumable state from
                # device; adopt it so callbacks observe current weights
                # and checkpoint the optimizer moments too
                self._master_network.set_weights(replica.get_weights())
                self._master_network._opt_state = replica._opt_state
                callbacks.epoch_end(epoch_idx, logs)
                return bool(getattr(self._master_network, "stop_training",
                                    False))

        new_weights, history = trainer.fit(
            self._master_network.get_weights(), x, y, epochs=epochs,
            batch_size=batch_size, validation_split=validation_split,
            seed=kwargs.get("seed", 0), verbose=verbose,
            epoch_callback=epoch_callback)
        self._training_histories.append(history)
        if not (callbacks and epochs):
            self._master_network.set_weights(new_weights)
        # else: the master adopted each epoch's weights in epoch_callback,
        # and any callback mutation of them wins over the trainer result

    def _fit_async(self, ds: Dataset, epochs: int = 10, batch_size: int = 32,
                   verbose: int = 0, validation_split: float = 0.1,
                   callbacks=None, **kwargs):
        import jax

        from .parallel.multihost import (barrier, coordinator_bind_env,
                                         is_coordinator)

        multi = jax.process_count() > 1
        if multi:
            # the PS lives on the coordinator host; broadcast its address
            # so every process's clients resolve to it over DCN, then
            # rebuild this process's client against the resolved address
            # (the HTTP client binds its URL at construction)
            coordinator_bind_env(self.port)
            # _make_client honors ps_shards: the worker processes need
            # the same fan-out client the coordinator uses, resolved
            # against the broadcast coordinator address
            self.client = self._make_client()
        serving = (not multi) or is_coordinator()

        # Multi-host discipline: a barrier skipped by ONE process hangs
        # every other process forever (sync_global_devices has no
        # timeout), so a local failure must not short-circuit the barrier
        # sequence — record it, drain the same barriers as everyone else,
        # then raise. Peers of a failed process fail in bounded time too:
        # clients give up after their retry deadline against a dead PS.
        failure = None
        try:
            if serving:
                self.start_server()
        except Exception as err:
            failure = err
        if multi:
            barrier("elephas_tpu_ps_up")  # workers must not race a down PS
        try:
            if failure is None:
                train_config = {"epochs": epochs, "batch_size": batch_size,
                                "verbose": verbose,
                                "validation_split": validation_split}
                model_json = self._master_network.to_json()
                init = self._master_network.get_weights()
                shards = ds.partitions()
                if multi:
                    # every process sees the same partition list (same
                    # dataset, same repartition); each takes a disjoint
                    # strided slice
                    shards = shards[jax.process_index()::jax.process_count()]

                # real per-epoch callbacks for async modes: workers emit
                # epoch events; when every participating (non-empty)
                # worker finishes epoch k, the driver pulls the live
                # global weights off the PS and fires epoch_end — so
                # EarlyStopping/ModelCheckpoint observe current state and
                # can stop async training mid-run. (Multi-host: each
                # process aggregates its own workers; a stop triggered
                # here halts this process's workers.)
                # shape[0] only — np.asarray here would materialize an
                # out-of-core shard's whole column on the driver
                nonempty = [bool(shard[0].shape[0]) for shard in shards]
                aggregator = None
                cb_failure: Dict[str, BaseException] = {}
                if callbacks:
                    participants = sum(nonempty)

                    def on_epoch(epoch_idx, logs):
                        import warnings as _warnings

                        try:
                            # cheap liveness probes first: on_epoch runs
                            # under the aggregator lock (including from
                            # the supervisor's failure path), so a dead
                            # PS must cost the ~5s probes, not a full
                            # pull-retry deadline, before degrading to
                            # the previous weights. Two chances: one
                            # timed-out probe on a busy-but-live server
                            # must not skip a checkpoint-relevant pull.
                            if (self.client.health_check()
                                    or self.client.health_check()):
                                self._master_network.set_weights(
                                    self.client.get_parameters())
                            else:
                                _warnings.warn(
                                    "parameter server unreachable; "
                                    "callbacks see the previous weights")
                        except Exception as err:
                            _warnings.warn(
                                f"per-epoch weight pull failed ({err}); "
                                "callbacks see the previous weights")
                        try:
                            callbacks.epoch_end(epoch_idx, logs)
                        except BaseException as err:  # noqa: BLE001
                            # a callback error must FAIL the fit, not
                            # leak into the reporting worker's thread
                            # (the supervisor would classify it as a
                            # worker crash and quietly reassign the
                            # shard, swallowing the exception)
                            cb_failure.setdefault("err", err)
                            return True  # stop every worker at its next
                            # epoch boundary; re-raised after the drain
                        return bool(getattr(self._master_network,
                                            "stop_training", False))

                    if participants:
                        aggregator = _EpochAggregator(participants, on_epoch)

                # round-robin worker→chip assignment: N async workers on
                # an M-chip host drive all M chips concurrently instead of
                # contending for chip 0 (the TPU-native analog of each
                # reference worker owning an executor's compute,
                # elephas/worker.py:52-131)
                local_devices = jax.local_devices()
                import threading as _threading

                # shards whose aggregator seat was removed after a
                # policy-level failure; a re-run of one reports no epoch
                # events (unless it rejoins an emptied aggregator). A
                # PS-restart free retry never lands here, so it keeps
                # its seat — per-member idempotent reports make its
                # re-reported epochs harmless.
                removed: set = set()
                removed_lock = _threading.Lock()

                def run_shard(slot, shard_idx, shard, attempt):
                    with removed_lock:
                        attach = (aggregator is not None
                                  and nonempty[shard_idx]
                                  and (shard_idx not in removed
                                       or aggregator.rejoin_if_empty()))
                        if attach:
                            # rejoining re-runs take the seat back (the
                            # sole-worker-crash case: without this,
                            # callbacks go silently dead for the fit)
                            removed.discard(shard_idx)
                    x_w, y_w = shard
                    worker = AsyncWorker(
                        model_json, init, self.client, train_config,
                        self.frequency, self.master_optimizer,
                        self.master_loss, self.master_metrics,
                        self.custom_objects, port=self.port,
                        compute_dtype=self.master_compute_dtype,
                        overlap=self.async_overlap,
                        accum_batches=self.async_accum,
                        pipeline=self.ps_pipeline,
                        epoch_event=(
                            (lambda e, l, _m=shard_idx:
                             aggregator.report(e, l, member=_m))
                            if attach else None),
                        should_stop=(aggregator.should_stop if aggregator
                                     else None),
                        device=local_devices[slot % len(local_devices)])
                    try:
                        worker.train(np.asarray(x_w), np.asarray(y_w))
                    finally:
                        worker.client.close()

                def on_item_failure(shard_idx, attempt, error):
                    # a failed worker leaves the epoch aggregator (once
                    # per shard, however many times its re-runs fail);
                    # removing it fires any epoch the survivors already
                    # completed, so callbacks never stall on the dead
                    if aggregator is None or not nonempty[shard_idx]:
                        return
                    with removed_lock:
                        if shard_idx in removed:
                            return
                        removed.add(shard_idx)
                    aggregator.remove_participant(member=shard_idx)

                ps_probe = ps_restart = None
                if self.ps_auto_restart and serving:
                    ps_probe, ps_restart = self._ps_supervision()

                if shards:
                    from .parallel.supervisor import WorkerSupervisor

                    supervisor = WorkerSupervisor(
                        run_shard,
                        on_worker_failure=self.on_worker_failure,
                        max_worker_restarts=self.max_worker_restarts,
                        min_workers=self.min_workers,
                        ps_probe=ps_probe, ps_restart=ps_restart,
                        ps_probe_interval=self.ps_probe_interval,
                        max_ps_restarts=self.max_ps_restarts,
                        on_item_failure=on_item_failure)
                    try:
                        supervisor.run(shards)
                    except BaseException as run_err:
                        # a captured callback error is the ROOT cause
                        # (it stopped the workers); a drain-time worker
                        # error must not mask it
                        if cb_failure:
                            raise cb_failure["err"] from run_err
                        raise
                    finally:
                        # the report must survive a failed fit too —
                        # which shards failed/restarted is exactly what
                        # the operator needs when run() raises
                        self._training_histories.append(
                            {"supervisor": supervisor.report.as_dict()})
                    if cb_failure:
                        raise cb_failure["err"]
        except BaseException as err:
            # BaseException, not Exception: a callback may raise
            # SystemExit/KeyboardInterrupt (captured in cb_failure and
            # re-raised above), and skipping the barrier drain below
            # would hang every peer process forever — the exact failure
            # mode the barrier discipline here exists to prevent. The
            # failure is re-raised after the drain.
            failure = err
        if multi:
            barrier("elephas_tpu_workers_done")
        try:
            if failure is None:
                # every process pulls the final weights BEFORE the
                # coordinator tears the server down, so all hosts leave
                # fit() in agreement
                new_parameters = self.client.get_parameters()
                self._master_network.set_weights(new_parameters)
        except Exception as err:
            failure = err
        if multi:
            barrier("elephas_tpu_params_pulled")
        if serving:
            try:
                self.stop_server()
            except Exception:
                if failure is None:
                    raise
        if failure is not None:
            raise failure

    # ------------------------------------------------------------ predict/eval
    #: bound on live trainer entries: each holds compiled epoch programs,
    #: so the cache is LRU rather than unbounded or single-entry —
    #: alternating two fit configs (sync_mode, metric set, ...) must not
    #: recompile on every call
    _TRAINER_CACHE_MAX = 8

    def _cached_trainer(self, kind: str, build):
        """Reuse a trainer (and its compiled epoch programs) across fit()
        calls. Keyed by everything that changes the traced computation:
        optimizer config, loss, metric set, and the replica's compute
        dtype. A replica invalidation (architecture change) clears the
        cache wholesale."""
        key = (kind, str(self.master_optimizer), str(self.master_loss),
               tuple(str(m) for m in self.master_metrics),
               self.master_compute_dtype,
               id(self._replica))
        trainer = self._trainer_cache.get(key)
        if trainer is None:
            trainer = build()
            self._trainer_cache[key] = trainer
            while len(self._trainer_cache) > self._TRAINER_CACHE_MAX:
                self._trainer_cache.pop(next(iter(self._trainer_cache)))
        else:
            self._trainer_cache[key] = self._trainer_cache.pop(key)
        return trainer

    def _invalidate_replica(self):
        self._replica = None
        self._trainer_cache = {}
        self._replica_src = None
        self._predict_fn = None
        self._evaluate_fn = None

    def _refresh_replica(self):
        """Invalidate the replica (and with it every cached compiled
        trainer/program) only when the master's *architecture* changed;
        weight and compute-dtype drift are re-synced per call by
        :meth:`_get_replica`, and compile-config changes are part of the
        trainer cache key — so repeated/alternating fit() calls keep
        their compiled programs."""
        arch = self._master_network.to_json()
        if self._replica is not None and arch != getattr(
                self, "_replica_arch", None):
            self._invalidate_replica()
        self._replica_arch = arch

    def _get_replica(self) -> BaseModel:
        """A worker copy of the master network (master stays untouched
        during distributed execution, as with the reference's broadcast)."""
        from .models.core import model_from_json

        if self._replica is None:
            self._replica = model_from_json(self._master_network.to_json(),
                                            self.custom_objects)
            self._replica_src = None
        # mixed precision is compile-level config, not architecture: carry
        # it onto the replica (checked every call: a master recompile with
        # a different dtype must not leave a stale replica dtype behind —
        # and the already-traced predict/eval functions must be dropped,
        # or they would keep serving the old dtype's compilation)
        master_dtype = getattr(self._master_network, "_compute_dtype", None)
        if self._replica._compute_dtype != master_dtype:
            self._replica._compute_dtype = master_dtype
            self._replica._invalidate_jit()
            self._predict_fn = None
            self._evaluate_fn = None
        # sync only when the master's params pytree object changed
        # (set_weights/trainers always swap it): an unconditional
        # set_weights would rebuild the replica's pytree every call and
        # defeat the replicated-param caches in the sharded predict/eval
        if self._replica_src is not self._master_network.params:
            self._replica.set_weights(self._master_network.get_weights())
            self._replica_src = self._master_network.params
        return self._replica

    def predict(self, data: Union[Dataset, np.ndarray],
                batch_size: Optional[int] = None,
                out: Union[None, str, np.ndarray] = None) -> np.ndarray:
        """Distributed inference; returns predictions in input order.

        ``out``: stream predictions into a preallocated array or (as a
        string) a ``.npy`` file created with ``open_memmap`` — with a
        file-backed dataset neither the inputs nor the outputs ever
        fully materialize in process memory (the analog of the
        reference predicting over an RDD it never collects,
        ``elephas/spark_model.py:154-160``)."""
        from .models.ssm_model import SSMModel
        from .models.transformer_model import TransformerModel
        from .parallel.sync_trainer import build_sharded_predict

        if isinstance(self._master_network, (TransformerModel, SSMModel)):
            tokens = self._extract_tokens(data)
            if isinstance(out, str):
                # (rows, seq, vocab) logits stream straight to a .npy
                # memmap: the output of a long-corpus predict is usually
                # far larger than the inputs and must not accumulate
                out = np.lib.format.open_memmap(
                    out, mode="w+",
                    shape=(int(tokens.shape[0]), int(tokens.shape[1]),
                           int(self._master_network.config.vocab_size)),
                    dtype=np.float32)
            return self._master_network.predict(
                tokens, batch_size=batch_size or self.batch_size, out=out)
        if isinstance(data, Dataset):
            if data.is_columnar:
                x = data.columns[0]  # lazy sources pass through unread
            else:
                x = np.asarray(data.rows())
        else:
            x = np.asarray(data)
        replica = self._get_replica()
        if self._predict_fn is None:
            self._predict_fn = build_sharded_predict(replica)
        if isinstance(out, str):
            out = np.lib.format.open_memmap(
                out, mode="w+",
                shape=(int(x.shape[0]),) + tuple(replica.output_shape),
                dtype=np.float32)
        return self._predict_fn(
            x, batch_size=batch_size or max(self.batch_size, 256), out=out)

    def evaluate(self, x_test: np.ndarray, y_test: np.ndarray,
                 **kwargs) -> Union[List[float], float]:
        """Distributed evaluation: sample-count-weighted loss/metric means
        (parity: ``elephas/spark_model.py:274-308``)."""
        from .models.ssm_model import SSMModel
        from .models.transformer_model import TransformerModel
        from .parallel.sync_trainer import build_sharded_evaluate

        if isinstance(self._master_network, (TransformerModel, SSMModel)):
            return self._master_network.evaluate(
                np.asarray(x_test),
                batch_size=kwargs.get("batch_size", self.batch_size))
        replica = self._get_replica()
        if self._evaluate_fn is None:
            self._evaluate_fn = build_sharded_evaluate(
                replica, self.master_loss, self._worker_metric_fns(),
                self.custom_objects)
        from .data.sources import ColumnSource

        def _keep_lazy(arr):
            return arr if isinstance(arr, ColumnSource) else np.asarray(arr)

        return self._evaluate_fn(_keep_lazy(x_test), _keep_lazy(y_test),
                                 batch_size=kwargs.get("batch_size",
                                                       max(self.batch_size, 256)))


class TPUMatrixModel(TPUModel):
    """Distributed model over LabeledPoint datasets and dense linalg types
    (capability mirror of ``SparkMLlibModel``, ``elephas/spark_model.py:311-352``)."""

    def __init__(self, model: BaseModel, mode: str = "asynchronous",
                 frequency: str = "epoch", parameter_server_mode: str = "http",
                 num_workers: int = 4, custom_objects: Optional[Dict] = None,
                 batch_size: int = 32, port: int = 4000, *args, **kwargs):
        super().__init__(model=model, mode=mode, frequency=frequency,
                         parameter_server_mode=parameter_server_mode,
                         num_workers=num_workers, custom_objects=custom_objects,
                         batch_size=batch_size, port=port, *args, **kwargs)

    def fit(self, labeled_points: Dataset, epochs: int = 10,
            batch_size: int = 32, verbose: int = 0,
            validation_split: float = 0.1, categorical: bool = False,
            nb_classes: Optional[int] = None):
        """Train on a Dataset of LabeledPoints."""
        ds = lp_to_dataset(labeled_points, categorical, nb_classes)
        ds = ds.repartition(self.num_workers)
        self._fit(ds, epochs=epochs, batch_size=batch_size, verbose=verbose,
                  validation_split=validation_split)

    def predict(self, mllib_data: Union[Matrix, Vector]):
        """Predict on a dense Matrix or Vector, returning the same type."""
        if isinstance(mllib_data, Matrix):
            return to_matrix(self._master_network.predict(
                from_matrix(mllib_data)))
        elif isinstance(mllib_data, Vector):
            return to_vector(self._master_network.predict(
                from_vector(mllib_data)[None, :])[0])
        else:
            raise ValueError(
                "Provide either a Matrix or Vector, got {}".format(
                    type(mllib_data)))


def load_tpu_model(file_name: str, from_hadoop: bool = False,
                   custom_objects: Optional[Dict] = None
                   ) -> Union[TPUModel, TPUMatrixModel]:
    """Load a distributed model saved by :meth:`TPUModel.save`
    (parity: ``elephas/spark_model.py:355-389``). Object-store URLs
    (``gs://``, ``s3://``) download through the scheme's registered
    :mod:`~elephas_tpu.utils.storage` adapter."""
    from .utils.storage import get_store, is_remote

    assert (file_name[-3:] == ".h5" or file_name[-6:] == ".keras"), \
        "File name must end with either '.h5' or '.keras'"

    remote = is_remote(file_name)
    if from_hadoop and remote:
        raise ValueError("from_hadoop and an object-store URL are "
                         "mutually exclusive")
    temp_download = from_hadoop or remote
    if from_hadoop:
        temp_file = _temp_model_path(file_name)
        _run_hadoop(["hadoop", "fs", "-copyToLocal", file_name, temp_file])
        file_name = temp_file
    elif remote:
        temp_file = _temp_model_path(file_name)
        get_store(file_name).get_file(file_name, temp_file)
        file_name = temp_file

    model = load_model(file_name, custom_objects)
    with h5py.File(file_name, mode="r") as f:
        dist_conf = f.attrs.get("distributed_config")
        if isinstance(dist_conf, bytes):
            dist_conf = dist_conf.decode("utf8")
        elephas_conf = json.loads(dist_conf)
    class_name = elephas_conf.get("class_name")
    config = elephas_conf.get("config")

    if temp_download:
        Path(file_name).unlink()

    if class_name == TPUModel.__name__:
        return TPUModel(model=model, **config)
    elif class_name == TPUMatrixModel.__name__:
        return TPUMatrixModel(model=model, **config)
    raise ValueError(f"Unknown distributed model class {class_name!r}")
