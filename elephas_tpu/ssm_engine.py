"""Continuous-batching engine for the selective-SSM family.

The SSM's decode state is a constant ``(d_inner,)`` vector per layer
per sequence — no KV cache, no block tables, no position bookkeeping.
That collapses most of what :class:`~elephas_tpu.serving_engine.
DecodeEngine` manages for transformers (cache rows, prefix KV, paged
pools) into one ``(max_slots, d_inner)`` state matrix per layer, which
is why this engine is its own small class rather than a configuration
of the transformer engine: the two share the slot/queue SEMANTICS
(submit with per-request sampling settings, step/run/result/cancel,
eos + budget retirement, streamed per-step token returns — same parity
oracle, per-request greedy output ≡ solo
:func:`~elephas_tpu.models.ssm.ssm_generate`) but none of the cache
machinery. Prefix caching is pointless here (a prefix's entire effect
IS the state vector), and paged memory is moot (state is O(1) per slot
by construction: serving memory never grows with context length).
Prefill rides the shared :func:`~elephas_tpu.models.ssm.ssm_prefill`;
``prefill_chunk`` bounds its compile shapes exactly like the
transformer engine's.
"""
import time
from collections import deque
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .models.ssm import SSMConfig, init_ssm_state, ssm_decode_step, ssm_prefill
from .obs.context import current_context, use_context
from .obs.events import FlightRecorder
from .obs.metrics import (MetricsRegistry, counter_baseline,
                          since_baseline)
from .obs.trace import span_if_counted
from .serving_engine import INTER_TOKEN_BUCKETS, _filter_logits_rows

__all__ = ["SSMEngine"]


class SSMEngine:
    """Slot-based online serving over one SSM parameter pytree.

    :param params: :func:`~elephas_tpu.models.ssm.init_ssm_params` tree
    :param config: the model's :class:`~elephas_tpu.models.ssm.SSMConfig`
    :param max_slots: device batch width (concurrent requests)
    :param temperature: default sampling temperature (0 = greedy,
        parity with ``ssm_generate``); overridable per request
    :param eos_id: optional stop token (not part of the output)
    :param steps_per_sync: decode steps fused per dispatch (one
        ``lax.scan``) — same dispatch-latency lever as the transformer
        engine's; per-slot output is unchanged.
    :param prefill_chunk: prefill prompts in fixed-size pieces (the
        recurrence continues across chunks through the carried state),
        bounding admission compiles to at most ``prefill_chunk`` shapes.
    :param registry: metrics registry backing :attr:`stats` (fresh
        per-engine instance by default, exactly like
        :class:`~elephas_tpu.serving_engine.DecodeEngine`'s; the HTTP
        server's ``GET /metrics`` reads it).
    """

    #: flight-recorder decode sampling, mirroring DecodeEngine's
    TRACE_STEP_EVERY = 8

    def __init__(self, params: Dict, config: SSMConfig,
                 max_slots: int = 8, temperature: float = 0.0,
                 eos_id: Optional[int] = None, seed: int = 0,
                 steps_per_sync: int = 1,
                 prefill_chunk: Optional[int] = None,
                 registry: Optional[MetricsRegistry] = None):
        self.params = params
        self.config = config
        self.max_slots = int(max_slots)
        self.temperature = float(temperature)
        self.eos_id = eos_id
        self.steps_per_sync = int(steps_per_sync)
        if self.steps_per_sync < 1:
            raise ValueError("steps_per_sync must be >= 1")
        self.prefill_chunk = (None if prefill_chunk is None
                              else int(prefill_chunk))
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self._key = jax.random.PRNGKey(seed)
        self.state = init_ssm_state(config, self.max_slots)
        self._last = np.zeros(self.max_slots, np.int32)
        self._budget = np.zeros(self.max_slots, np.int32)
        self._temp = np.full(self.max_slots, self.temperature, np.float32)
        self._topk = np.zeros(self.max_slots, np.int32)    # 0 = off
        self._topp = np.ones(self.max_slots, np.float32)   # 1 = off
        self._rid: List[Optional[int]] = [None] * self.max_slots
        self._queue: deque = deque()
        self._outputs: Dict = {}
        self._done: Dict = {}
        self._fresh: Dict = {}
        self._next_rid = 0
        # tracing: submit-time context per rid + the flight recorder
        # (same contract as DecodeEngine's — the HTTP trace routes read
        # either engine through request_trace/recent_traces)
        self._trace_ctx: Dict[int, object] = {}
        self.recorder = FlightRecorder()
        # registry-backed counters (the store behind .stats and /metrics)
        self.registry = reg = (registry if registry is not None
                               else MetricsRegistry())
        self._m_steps = reg.counter(
            "serving_steps_total",
            "device round trips (engine steps)").labels()
        self._m_emitted = reg.counter(
            "serving_tokens_emitted_total", "output tokens emitted"
            ).labels()
        self._m_finished = reg.counter(
            "serving_requests_finished_total",
            "requests retired at eos or budget").labels()
        # same eviction visibility as DecodeEngine's recorder: a
        # truncated timeline must read as truncated, not absent
        self.recorder.bind_eviction_counter(reg.counter(
            "flight_recorder_evictions_total",
            "flight-recorder timelines evicted by the ring bound, "
            "by request state at eviction", labels=("state",)))
        # weak ref, like DecodeEngine's gauges: an injected shared
        # registry must not pin a discarded engine via its callbacks
        import weakref

        ref = weakref.ref(self)
        self._m_queue_depth = reg.gauge(
            "serving_queue_depth", "requests backlogged, not yet admitted")
        self._m_queue_depth.set_function(
            lambda: float(len(e._queue))
            if (e := ref()) is not None else 0.0)
        self._m_step_latency = reg.histogram(
            "serving_step_latency_seconds",
            "wall time of one engine step (admission + device dispatch)"
            ).labels()
        # user-experienced latency decomposition, mirroring
        # DecodeEngine's: TTFT + inter-token gaps, observed off HOST
        # dicts (never the bounded flight-recorder ring)
        self._m_ttft = reg.histogram(
            "serving_ttft_seconds",
            "submit-to-first-token wall time per request",
            exemplars=True).labels()
        self._m_inter_token = reg.histogram(
            "serving_inter_token_seconds",
            "wall time between consecutive output tokens of one "
            "request", buckets=INTER_TOKEN_BUCKETS).labels()
        self._submit_mono: Dict[int, float] = {}
        self._last_tok_t: Dict[int, float] = {}
        self._ttft_val: Dict[int, float] = {}
        # per-engine baselines, like DecodeEngine's: a shared injected
        # registry may carry a predecessor's totals; stats reports
        # this engine's deltas (zero baselines for the default fresh
        # registry, where stats ≡ the scraped series)
        self._stat_base = counter_baseline(
            self._m_steps, self._m_emitted, self._m_finished)

        c = config
        n_sync = self.steps_per_sync

        @jax.jit
        def _prefill(params, prompt):
            return ssm_prefill(params, prompt, c)

        @jax.jit
        def _prefill_cont(params, prompt, state):
            return ssm_prefill(params, prompt, c, state=state)

        @partial(jax.jit, donate_argnums=(0,))
        def _install(state, row_state, slot):
            # the ENGINE state is donated (updated in place); the
            # batch-1 prefill row is read-only
            return jax.tree_util.tree_map(
                lambda big, row: jax.lax.dynamic_update_index_in_dim(
                    big, row[0], slot, 0), state, row_state)

        def _one(params, state, last, temps, topk, topp, key):
            # same scale-then-filter semantics as the transformer
            # engine's shared sampling body (the lax.cond skips the
            # filter work for all-greedy batches)
            logits, state = ssm_decode_step(params, state, last, c)
            key, sub = jax.random.split(key)
            safe = jnp.maximum(temps, 1e-6)[:, None]
            need = jnp.any(((topk > 0) | (topp < 1.0)) & (temps > 0))
            filtered = jax.lax.cond(
                need, lambda x: _filter_logits_rows(x, topk, topp),
                lambda x: x, logits / safe)
            sampled = jax.random.categorical(sub, filtered, axis=-1)
            tok = jnp.where(temps > 0, sampled,
                            jnp.argmax(logits, axis=-1))
            return tok.astype(jnp.int32), state, key

        @partial(jax.jit, donate_argnums=(1,))
        def _step(params, state, last, temps, topk, topp, key):
            tok, state, key = _one(params, state, last, temps, topk,
                                   topp, key)
            return tok[:, None], state, key            # (B, 1)

        @partial(jax.jit, donate_argnums=(1,))
        def _multi_step(params, state, last, temps, topk, topp, key):
            def body(carry, _):
                state, tok, key = carry
                nxt, state, key = _one(params, state, tok, temps, topk,
                                       topp, key)
                return (state, nxt, key), nxt

            (state, _, key), toks = jax.lax.scan(
                body, (state, last, key), None, length=n_sync)
            return jnp.swapaxes(toks, 0, 1), state, key  # (B, K)

        self._prefill_fn = _prefill
        self._prefill_cont_fn = _prefill_cont
        self._install_fn = _install
        self._step_fn = (_multi_step if n_sync > 1 else _step)

    # ------------------------------------------------------------ warmup
    def warmup(self, prompt_lengths: Sequence[int] = ()):
        """Compile the decode step and each length's admission prefill
        before traffic (idle engine only) — the SSM analog of
        :meth:`DecodeEngine.warmup`, zero extra device memory (the step
        warms by donating the engine's own state)."""
        if any(r is not None for r in self._rid) or self._queue:
            raise RuntimeError("warmup() needs an idle engine")
        _, self.state, _ = self._step_fn(
            self.params, self.state, jnp.zeros(self.max_slots, jnp.int32),
            jnp.asarray(self._temp), jnp.asarray(self._topk),
            jnp.asarray(self._topp), jax.random.PRNGKey(0))
        for length in sorted(set(int(n) for n in prompt_lengths)):
            if length < 1:
                raise ValueError(f"prompt length {length} out of range")
            _, row = self._row_prefill(np.zeros(length, np.int32))
            self.state = self._install_fn(self.state, row, 0)

    # ------------------------------------------------------------ queue
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               admit: bool = True) -> int:
        """Queue a request; per-request sampling settings and the
        ``admit=False`` deferred-admission knob mirror the transformer
        engine's (so the HTTP server's request fields work identically
        against either family)."""
        if temperature is not None and not (
                temperature >= 0 and np.isfinite(temperature)):
            raise ValueError("temperature must be >= 0 and finite, "
                             f"got {temperature}")
        if top_k is not None and top_k < 1:
            raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        rid = self._next_rid
        self._next_rid += 1
        self._submit_mono[rid] = time.monotonic()
        ctx = current_context()
        if ctx is not None:
            self._trace_ctx[rid] = ctx
        self.recorder.start(rid,
                            trace_id=None if ctx is None else ctx.trace_id,
                            prompt_tokens=int(prompt.size),
                            max_new_tokens=int(max_new_tokens))
        self._queue.append((rid, prompt, int(max_new_tokens),
                            self.temperature if temperature is None
                            else float(temperature),
                            0 if top_k is None else int(top_k),
                            1.0 if top_p is None else float(top_p)))
        if admit:
            self._admit()
        return rid

    def cancel(self, rid: int) -> bool:
        """Same contract as the transformer engine's ``cancel``."""
        for i, item in enumerate(self._queue):
            if item[0] == rid:
                del self._queue[i]
                self._trace_ctx.pop(rid, None)
                self._submit_mono.pop(rid, None)
                self.recorder.record(rid, "cancelled", stage="queued")
                return True
        for slot, r in enumerate(self._rid):
            if r == rid:
                tokens = len(self._outputs.get(rid, ()))
                self._outputs.pop(rid, None)
                self._fresh.pop(rid, None)
                self._rid[slot] = None
                self._trace_ctx.pop(rid, None)
                self._submit_mono.pop(rid, None)
                self._last_tok_t.pop(rid, None)
                self._ttft_val.pop(rid, None)
                self.recorder.record(rid, "cancelled", stage="decoding",
                                     tokens=tokens)
                return True
        return False

    def _row_prefill(self, prompt: np.ndarray):
        """Batch-1 prefill, chunked when ``prefill_chunk`` bounds the
        compile shapes (the recurrence carries across chunks)."""
        chunk = self.prefill_chunk
        if chunk is None or prompt.size <= chunk:
            return self._prefill_fn(self.params,
                                    jnp.asarray(prompt[None]))
        logits = row = None
        for start in range(0, prompt.size, chunk):
            blk = jnp.asarray(prompt[None, start:start + chunk])
            if row is None:
                logits, row = self._prefill_fn(self.params, blk)
            else:
                logits, row = self._prefill_cont_fn(self.params, blk,
                                                    row)
        return logits, row

    def _admit(self):
        for slot in range(self.max_slots):
            if self._rid[slot] is not None:
                continue
            if not self._queue:
                return
            rid, prompt, max_new, temp, topk, topp = self._queue.popleft()
            wait = self.recorder.age(rid)
            self.recorder.record(
                rid, "admitted", slot=slot,
                queue_wait_s=None if wait is None else round(wait, 6))
            t_pre = time.monotonic()
            # restore the submitter's context around this request's
            # prefill, exactly like DecodeEngine._admit
            with use_context(self._trace_ctx.get(rid)):
                logits, row = self._row_prefill(prompt)
                self.state = self._install_fn(self.state, row, slot)
                if temp > 0:
                    self._key, sub = jax.random.split(self._key)
                    filt = _filter_logits_rows(
                        logits / temp, jnp.asarray([topk], jnp.int32),
                        jnp.asarray([topp], jnp.float32))[0]
                    t0 = int(jax.random.categorical(sub, filt))
                else:
                    t0 = int(jnp.argmax(logits[0]))
            self.recorder.record(
                rid, "prefill", prompt_tokens=int(prompt.size),
                duration_s=round(time.monotonic() - t_pre, 6))
            self._rid[slot] = rid
            self._outputs[rid] = []
            self._last[slot] = t0
            self._budget[slot] = max_new
            self._temp[slot] = temp
            self._topk[slot] = topk
            self._topp[slot] = topp
            if self._record(slot, t0):
                self._fresh[rid] = t0

    def _record(self, slot: int, tok: int) -> bool:
        rid = self._rid[slot]
        if self.eos_id is not None and tok == self.eos_id:
            self._finish(slot)
            return False
        self._outputs[rid].append(tok)
        self._m_emitted.inc()
        # TTFT / inter-token stamps off host dicts (the DecodeEngine
        # contract: histogram samples never depend on the trace ring)
        now_tok = time.monotonic()
        last_tok = self._last_tok_t.get(rid)
        if last_tok is None:
            t_sub = self._submit_mono.get(rid)
            if t_sub is not None:
                ctx = self._trace_ctx.get(rid)
                ttft = now_tok - t_sub
                self._m_ttft.observe(
                    ttft, trace_id=None if ctx is None
                    else ctx.trace_id)
                self._ttft_val[rid] = ttft
        else:
            self._m_inter_token.observe(now_tok - last_tok)
        self._last_tok_t[rid] = now_tok
        n = len(self._outputs[rid])
        if n % self.TRACE_STEP_EVERY == 0:
            self.recorder.record(rid, "step", tokens=n)
        self._budget[slot] -= 1
        if self._budget[slot] <= 0:
            self._finish(slot)
        return True

    def _finish(self, slot: int):
        rid = self._rid[slot]
        self._done[rid] = self._outputs.pop(rid)
        self._rid[slot] = None
        self._m_finished.inc()
        self._trace_ctx.pop(rid, None)
        self._submit_mono.pop(rid, None)
        self._last_tok_t.pop(rid, None)
        ttft = self._ttft_val.pop(rid, None)
        total = self.recorder.age(rid)
        self.recorder.record(
            rid, "finished", tokens=len(self._done[rid]),
            total_s=None if total is None else round(total, 6),
            **({} if ttft is None else {"ttft_s": round(ttft, 6)}))

    # ------------------------------------------------------------- step
    @property
    def pending(self) -> int:
        return (len(self._queue)
                + sum(r is not None for r in self._rid)
                + len(self._fresh))

    def step(self) -> Dict[int, List[int]]:
        """Advance every active slot by ``steps_per_sync`` tokens;
        returns ``{rid: [tokens]}`` emitted since the last call."""
        # device round trips only, like DecodeEngine.step
        with span_if_counted("serving.step", self._m_steps,
                             histogram=self._m_step_latency):
            return self._step_impl()

    def _step_impl(self) -> Dict[int, List[int]]:
        self._admit()
        emitted = {rid: [tok] for rid, tok in self._fresh.items()}
        self._fresh = {}
        active = np.asarray([r is not None for r in self._rid])
        if not active.any():
            return emitted
        self._m_steps.inc()
        toks, self.state, self._key = self._step_fn(
            self.params, self.state, jnp.asarray(self._last),
            jnp.asarray(self._temp), jnp.asarray(self._topk),
            jnp.asarray(self._topp), self._key)
        toks = np.asarray(toks)                        # (B, K)
        for slot in np.nonzero(active)[0]:
            rid = self._rid[slot]
            for tok in toks[slot]:
                if self._rid[slot] is None:
                    break                  # retired mid-chunk
                self._last[slot] = tok
                if self._record(slot, int(tok)):
                    emitted.setdefault(rid, []).append(int(tok))
        self._admit()
        return emitted

    def run(self, requests: Sequence[Sequence[int]],
            max_new_tokens: int) -> List[List[int]]:
        rids = [self.submit(p, max_new_tokens) for p in requests]
        while self.pending:
            self.step()
        return [self.result(r) for r in rids]

    def result(self, rid: int) -> Optional[List[int]]:
        return self._done.pop(rid, None)

    # ---------------------------------------------------------- tracing
    def request_trace(self, rid: int) -> Optional[Dict]:
        """Flight-recorder timeline for ``rid`` (same contract as
        :meth:`DecodeEngine.request_trace`)."""
        return self.recorder.trace(rid)

    def recent_traces(self, limit: int = 32) -> List[Dict]:
        return self.recorder.recent(limit)

    @property
    def stats(self) -> Dict[str, float]:
        steps = int(since_baseline(self._stat_base, self._m_steps))
        emitted = int(since_baseline(self._stat_base, self._m_emitted))
        out = {"steps": steps,
               "tokens_emitted": emitted,
               "requests_finished": int(
                   since_baseline(self._stat_base, self._m_finished)),
               "tokens_per_step": (emitted / steps if steps else 0.0),
               "queue_depth": len(self._queue)}
        ttft_p50 = self._m_ttft.quantile(0.5)
        if ttft_p50 is not None:
            out["ttft_p50_s"] = round(ttft_p50, 6)
            out["ttft_p95_s"] = round(self._m_ttft.quantile(0.95), 6)
        itl_p50 = self._m_inter_token.quantile(0.5)
        if itl_p50 is not None:
            out["inter_token_p50_s"] = round(itl_p50, 6)
        return out
