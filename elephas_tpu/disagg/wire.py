"""KV-transfer wire for disaggregated prefill/decode.

A prefill worker ships one request's prompt KV state to a decode worker
as a single ETPU frame over the zero-copy socket path (PR 5's frame
machinery: single-allocation :func:`~elephas_tpu.utils.tensor_codec.
encode_tensors`, ``recv_into`` exact reads, ``copy=False`` view
decode). Frame layout::

    [meta]  uint8 tensor — UTF-8 JSON request metadata (rid, prompt,
            sampling settings, first_token, deadline, ...)
    [kv]    KIND_KV:    per-layer paged KV block tensors
                        (:func:`~elephas_tpu.models.paged_decode.
                        export_kv_blocks` order)
            KIND_KV_Q8: the same blocks as interleaved (int8 data,
                        float32 scale) pairs
                        (:func:`~elephas_tpu.models.quantization.
                        quantize_kv_frames`) — roughly a quarter of the
                        fp32 bytes (int8 data + one f32 scale per
                        ``head_dim`` vector)

Socket protocol (:class:`KVReceiver` serves it, :class:`KVShipper`
speaks it): an optional ``b'T'`` + 55-byte traceparent frame — the SAME
trace extension the parameter-server transport uses, so one trace id
spans client -> router -> prefill -> decode -> PS — then ``b'K'`` + an
8-byte little-endian length + the frame body, answered with a 1-byte
ack once the receiver has handed the frame to its import queue. A peer
vanishing mid-transfer raises on either side (``recv_exact``'s EOF
contract), which is the shipper's signal to retry the prefill
elsewhere; a lost ACK may deliver a duplicate frame, which the decode
side deduplicates by request id.
"""
import json
import socket
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..models.quantization import dequantize_kv_frames, quantize_kv_frames
from ..obs.context import use_context
from ..utils.faults import InjectedPartition, fault_network
from ..utils.sockets import (KV_ACK, KV_OPCODE, LENGTH_BYTES,
                             TRACE_OPCODE, recv_exact, receive_traceparent,
                             send_kv_payload, send_trace_context)
from ..utils.tensor_codec import (KIND_KV, KIND_KV_Q8, MAX_FRAME_BYTES,
                                  CodecError, decode, encode)

__all__ = ["encode_kv_frame", "decode_kv_frame", "KVReceiver",
           "KVShipper"]


def encode_kv_frame(meta: Dict, arrays: Sequence[np.ndarray],
                    quant: bool = True):
    """One wire frame: JSON ``meta`` + the KV block tensors, Q8-packed
    when ``quant``. Returns the encoder's bytes-like payload (a writable
    memoryview on the Python path — sendall-ready, no copy)."""
    meta_arr = np.frombuffer(json.dumps(meta).encode("utf8"), np.uint8)
    if quant:
        body: List[np.ndarray] = quantize_kv_frames(arrays)
        kind = KIND_KV_Q8
    else:
        body = [np.asarray(a) for a in arrays]
        kind = KIND_KV
    return encode([meta_arr] + body, kind)


def decode_kv_frame(payload, copy: bool = False
                    ) -> Tuple[Dict, List[np.ndarray]]:
    """Inverse of :func:`encode_kv_frame`: ``(meta, kv_arrays)`` with Q8
    pairs already dequantized to float32. ``copy=False`` (the receive
    path's default) decodes zero-copy views of ``payload`` — fp tensors
    alias the receive buffer straight into the decode engine's install,
    and Q8 dequantization allocates its float32 output anyway."""
    arrays, kind = decode(payload, copy=copy)
    if kind not in (KIND_KV, KIND_KV_Q8):
        raise CodecError(f"not a KV frame (kind {kind})")
    if not arrays:
        raise CodecError("KV frame is missing its metadata tensor")
    try:
        meta = json.loads(bytes(arrays[0]).decode("utf8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as exc:
        raise CodecError(f"bad KV frame metadata: {exc}")
    body = arrays[1:]
    if kind == KIND_KV_Q8:
        body = dequantize_kv_frames(body)
    return meta, body


class KVReceiver:
    """Decode-worker-side KV frame server.

    Listens on ``host:port`` (0 = pick free), accepts prefill-worker
    connections, and for every delivered frame calls ``on_frame(meta,
    arrays, nbytes)`` under the shipped trace context before answering
    the 1-byte ack. ``on_frame`` runs on the connection thread and must
    only enqueue (the decode engine installs between its own steps) —
    a slow callback backpressures that shipper's connection, nothing
    else.
    """

    def __init__(self, on_frame: Callable[[Dict, List[np.ndarray], int],
                                          None],
                 host: str = "127.0.0.1", port: int = 0):
        self._on_frame = on_frame
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(16)
        self._host = host
        self._port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._conns: List[socket.socket] = []
        self._lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None

    @property
    def addr(self) -> Tuple[str, int]:
        return self._host, self._port

    def start(self) -> "KVReceiver":
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name="kv-receiver")
        self._accept_thread.start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:       # closed by stop()
                return
            with self._lock:
                self._conns.append(conn)
            # daemon threads, never joined: _serve_conn removes its
            # conn from _conns on exit, so nothing accumulates per
            # (possibly short-lived) shipper connection
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True, name="kv-receiver-conn").start()

    def _serve_conn(self, conn: socket.socket):
        """One shipper connection: opcode loop until EOF. A traceparent
        frame applies to exactly the one KV frame that follows (the PS
        protocol's convention)."""
        ctx = None
        try:
            while not self._stop.is_set():
                op = bytes(recv_exact(conn, 1))
                if op == TRACE_OPCODE:
                    ctx = receive_traceparent(conn)
                    continue
                if op != KV_OPCODE:
                    return          # protocol violation: drop the conn
                length = int.from_bytes(recv_exact(conn, LENGTH_BYTES),
                                        "little")
                if length > MAX_FRAME_BYTES:
                    return
                payload = recv_exact(conn, length)
                try:
                    meta, arrays = decode_kv_frame(payload, copy=False)
                    with use_context(ctx):
                        self._on_frame(meta, arrays, length)
                except (ConnectionError, OSError):
                    raise
                except Exception:  # noqa: BLE001 — a malformed frame
                    # (codec skew, bad block shapes in the callback) is
                    # a PROTOCOL error: close the conn (no ack — the
                    # shipper's failure signal) instead of letting the
                    # exception kill this thread with a traceback
                    return
                finally:
                    ctx = None
                # ack only after the frame reached the import queue: a
                # shipper killed before this byte retries, and the
                # decode side dedupes the replay by rid
                conn.sendall(KV_ACK)
        except (ConnectionError, OSError):
            pass                    # peer gone: routine in a kill test
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)


class KVShipper:
    """Prefill-worker-side KV frame client: one persistent connection
    per decode-worker address, byte/frame accounting per codec (the
    bench row's fp32-vs-Q8 wire-bytes evidence reads these)."""

    def __init__(self, timeout: float = 30.0):
        self._timeout = float(timeout)
        self._lock = threading.Lock()
        self._socks: Dict[Tuple[str, int], socket.socket] = {}
        self._closed = False
        #: frames / payload bytes shipped, by codec ("fp" | "q8")
        self.frames: Dict[str, int] = {"fp": 0, "q8": 0}
        self.bytes: Dict[str, int] = {"fp": 0, "q8": 0}

    def _connect(self, addr: Tuple[str, int]) -> socket.socket:
        sock = socket.create_connection(addr, timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    def ship(self, addr: Tuple[str, int], meta: Dict,
             arrays: Sequence[np.ndarray], quant: bool = True,
             ctx=None) -> int:
        """Encode + send one KV frame and wait for the ack; returns the
        payload byte count. One reconnect attempt on a stale persistent
        socket (the decode worker restarted); any other failure
        propagates — the caller's retry-the-prefill-elsewhere signal.
        ``ctx`` (a TraceContext) rides ahead of the frame when given."""
        addr = (addr[0], int(addr[1]))
        if fault_network("disagg.kv_ship", peer=f"{addr[0]}:{addr[1]}"):
            # a dropped ship surfaces exactly like a vanished peer: the
            # prefill worker's retry-elsewhere signal
            raise InjectedPartition(
                f"injected drop toward {addr[0]}:{addr[1]}")
        payload = encode_kv_frame(meta, arrays, quant=quant)
        sock, fresh = self._checkout(addr)
        try:
            self._send(sock, payload, ctx)
        except (ConnectionError, OSError):
            # a stale persistent conn gets ONE fresh retry; a fresh
            # conn failing (or a closed shipper) is real
            self._drop(addr)
            if fresh:
                raise
            sock, _ = self._checkout(addr, force_fresh=True)
            try:
                self._send(sock, payload, ctx)
            except (ConnectionError, OSError):
                self._drop(addr)
                raise
        codec = "q8" if quant else "fp"
        with self._lock:
            self.frames[codec] += 1
            self.bytes[codec] += len(payload)
        return len(payload)

    def _checkout(self, addr, force_fresh: bool = False):
        """``(socket, was_fresh)`` for ``addr``. The lock guards only
        the socket map — NEVER the connect or the send/ack round trip,
        so close() (the kill-mid-transfer path) can always grab it and
        shut a blocked transfer down from another thread (a blackholed
        connect must not pin the lock for its whole timeout)."""
        with self._lock:
            if self._closed:
                raise ConnectionError("shipper is closed")
            sock = None if force_fresh else self._socks.get(addr)
        if sock is not None:
            return sock, False
        sock = self._connect(addr)          # blocking I/O: lock NOT held
        with self._lock:
            if self._closed:
                try:
                    sock.close()
                except OSError:
                    pass
                raise ConnectionError("shipper is closed")
            self._socks[addr] = sock
        return sock, True

    @staticmethod
    def _send(sock: socket.socket, payload, ctx) -> None:
        if ctx is not None:
            send_trace_context(sock, ctx)
        send_kv_payload(sock, payload)

    def _drop(self, addr: Tuple[str, int]) -> None:
        sock = self._socks.pop(addr, None)
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self):
        """Close every connection. A ``ship`` blocked in a send/ack on
        another thread fails immediately — the kill-mid-transfer path."""
        with self._lock:
            self._closed = True
            socks = list(self._socks.values())
            self._socks.clear()
        for sock in socks:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
