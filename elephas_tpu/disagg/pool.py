"""DisaggPool: an in-process prefill-tier + decode-tier topology.

The disaggregated analog of :class:`~elephas_tpu.fleet.ReplicaPool`:
``n_prefill`` :class:`~.prefill.PrefillWorker` instances form ONE
shared prefill tier, and ``n_decode`` decode workers — each a
:class:`~.engine.DisaggEngine` behind its own
:class:`~elephas_tpu.serving_http.ServingServer` — draw on it. The two
tiers scale independently: a prompt-heavy deployment adds prefill
workers, a generation-heavy one adds decode workers, and neither
resizing touches the other tier.

A :class:`~elephas_tpu.fleet.FleetRouter` fronts the pool unchanged —
``FleetRouter(pool.urls)`` — because the decode servers speak the full
serving API; the router's consistent-hash/spill policy, health-driven
membership, and traceparent forwarding all apply, and the prefill tier
hides behind the decode tier exactly as the parameter servers do. Chaos
verbs for the failure tests: ``kill_prefill(i)`` (mid-transfer worker
death — jobs retry on siblings), ``kill_decode(i)`` / ``drain_decode``
(the router's eviction/re-route path, as with ``ReplicaPool``).

Both tiers scale at runtime (the fleet autoscaler's verbs):
``add_prefill()`` / ``add_decode()`` grow a tier, ``drain_prefill(i)``
retires a prefill worker gracefully (queued jobs re-dispatch to
siblings), and ``decommission_decode(i)`` drains a decode replica to
completion before stopping it — scale-down is never a kill.
"""
from typing import Callable, List, Optional

from ..serving_http import ServingServer
from .engine import DisaggEngine
from .prefill import PrefillWorker

__all__ = ["DisaggPool"]


class DisaggPool:
    """``n_prefill`` prefill workers + ``n_decode`` served decode
    engines, in-process.

    :param decode_factory: zero-arg callable returning a fresh decode
        :class:`~elephas_tpu.serving_engine.DecodeEngine` per decode
        worker — construct with ``tier="decode"`` so the queue-wait
        split lands on the right label (paged or contiguous both work).
    :param prefill_factory: likewise for the prefill workers' engines
        (defaults to ``decode_factory``; ``max_slots=1`` engines keep
        the prefill tier's cache allocation minimal).
    :param quant: Q8 KV frames on the wire (vs raw fp).
    :param block_size: wire block size for the KV export.
    :param prefixes: shared prompt prefixes registered on every prefill
        worker's engine BEFORE traffic (registration does not
        synchronize with in-flight prefills).
    :param server_kwargs: forwarded to every decode
        :class:`~elephas_tpu.serving_http.ServingServer`.
    """

    def __init__(self, decode_factory: Callable[[], object],
                 n_prefill: int = 1, n_decode: int = 1,
                 prefill_factory: Optional[Callable[[], object]] = None,
                 quant: bool = True, block_size: int = 64,
                 host: str = "127.0.0.1", tokenizer=None,
                 prefixes=(), max_queue: Optional[int] = None,
                 server_kwargs: Optional[dict] = None):
        if n_prefill < 1 or n_decode < 1:
            raise ValueError("need n_prefill >= 1 and n_decode >= 1")
        self._decode_factory = decode_factory
        self._prefill_factory = prefill_factory or decode_factory
        self._n_prefill = int(n_prefill)
        self._n_decode = int(n_decode)
        self._quant = bool(quant)
        self._block_size = int(block_size)
        self._host = host
        self._tokenizer = tokenizer
        self._prefixes = [list(p) for p in prefixes]
        self._max_queue = max_queue
        self._server_kwargs = dict(server_kwargs or {})
        self.prefill_workers: List[PrefillWorker] = []
        self.engines: List[DisaggEngine] = []
        self.servers: List[ServingServer] = []
        self._next_prefill = 0   # monotonic worker naming across scale
        self._decode_alive: List[bool] = []

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "DisaggPool":
        for _ in range(self._n_prefill):
            self.add_prefill()
        for _ in range(self._n_decode):
            self.add_decode()
        return self

    def add_prefill(self) -> PrefillWorker:
        """Spawn one more prefill worker (the autoscaler's prefill
        scale-up verb — also what :meth:`start` loops over) and
        register it with every live decode front end, which starts
        dispatching to it immediately."""
        engine = self._prefill_factory()
        for p in self._prefixes:
            engine.register_prefix(p)
        # prefill-tier Prometheus series live on each worker's OWN
        # (engine) registry — NOT the process default: a decode
        # server's /metrics concatenates its engine registry with
        # the default registry, and two registries both defining
        # the serving_queue_wait_seconds family would emit
        # duplicate HELP/TYPE blocks (invalid exposition). In
        # production each prefill-worker process scrapes its own
        # registry; in-process, the decode servers' /stats carries
        # the prefill tier's waits (DisaggEngine.stats reads the
        # workers directly).
        worker = PrefillWorker(
            engine, quant=self._quant, block_size=self._block_size,
            name=f"prefill-{self._next_prefill}").start()
        self._next_prefill += 1
        self.prefill_workers.append(worker)
        for deng in self.engines:
            deng.add_worker(worker)
        return worker

    def add_decode(self) -> str:
        """Spawn one more served decode worker drawing on the CURRENT
        prefill tier (workers added later propagate via
        :meth:`~.engine.DisaggEngine.add_worker`). Returns its base
        URL for :meth:`~elephas_tpu.fleet.FleetRouter.add_replica`."""
        deng = DisaggEngine(self._decode_factory(),
                            self.prefill_workers,
                            max_queue=self._max_queue,
                            host=self._host)
        srv = ServingServer(deng, host=self._host, port=0,
                            tokenizer=self._tokenizer,
                            **self._server_kwargs)
        srv.start()
        self.engines.append(deng)
        self.servers.append(srv)
        self._decode_alive.append(True)
        return f"http://{self._host}:{srv.port}"

    def stop(self):
        for srv in self.servers:
            try:
                srv.stop()
            except Exception:  # noqa: BLE001 — a killed decode server
                pass
        for deng in self.engines:
            deng.stop()
        for worker in self.prefill_workers:
            if worker.alive:
                worker.stop()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------- chaos
    def kill_prefill(self, i: int):
        """Abrupt prefill-worker death (mid-transfer included): its
        queued and in-flight jobs fail back to the dispatchers and
        retry on sibling workers."""
        self.prefill_workers[i].kill()

    def drain_prefill(self, i: int):
        """Graceful prefill scale-down — the counterpart
        :meth:`kill_prefill` never was: the worker finishes its
        CURRENT job, fails its queued jobs back to their dispatchers
        (which re-dispatch to sibling workers — recompute, never a
        failed client request), and exits. BLOCKS until the worker's
        threads joined."""
        self.prefill_workers[i].stop()

    def kill_decode(self, i: int):
        """Abrupt decode-server death — the fleet router's eviction +
        re-route scenario."""
        self._decode_alive[i] = False
        self.servers[i].stop(drain_timeout=0.0)
        self.engines[i].stop()

    def drain_decode(self, i: int):
        """Graceful decode drain: ``/ready`` flips 503, in-flight work
        finishes."""
        self.servers[i].begin_drain()

    def decommission_decode(self, i: int, drain_timeout: float = 30.0):
        """Graceful decode scale-down: drain to completion (bounded by
        ``drain_timeout``), then stop the server and its engine's KV
        receiver. BLOCKS for the drain — the autoscaler runs it on a
        background thread; chaos-kill-safe like
        :meth:`~elephas_tpu.fleet.ReplicaPool.decommission`."""
        try:
            self.servers[i].stop(drain_timeout=float(drain_timeout))
        except Exception:  # noqa: BLE001 — killed mid-drain: already down
            pass
        self.engines[i].stop()
        self._decode_alive[i] = False

    # ------------------------------------------------------------ queries
    @property
    def urls(self) -> List[str]:
        return [f"http://{self._host}:{srv.port}" for srv in self.servers]

    def alive_decode_indexes(self) -> List[int]:
        """Decode replicas not killed/decommissioned — the autoscaler
        adapter's capacity count (a chaos-killed server must not keep
        counting as capacity and block scale-up at the ceiling)."""
        return [i for i, a in enumerate(self._decode_alive) if a]
