"""Prefill workers: the compute-bound tier of disaggregated serving.

Each :class:`PrefillWorker` owns a prefill-capable engine (a
:class:`~elephas_tpu.serving_engine.DecodeEngine` used ONLY for its
prefix-aware ``export_prefill`` path — register shared prefixes on it
exactly as on a colocated engine) plus one worker thread draining a job
queue: prefill the prompt, pack the resulting paged KV blocks, ship
them to the submitting decode worker's :class:`~.wire.KVReceiver`
(Q8-quantized by default). The dispatcher
(:class:`~.engine.DisaggEngine`) owns retry policy: a job that fails —
a killed worker, a severed mid-transfer socket, an injected fault —
fails BACK to it via the job's ``on_failed`` callback and is re-queued
on a sibling, so a prefill-tier death costs recompute, never a failed
client request.

Fault sites (:mod:`~elephas_tpu.utils.faults`): ``disagg.prefill``
(``delay`` = a slow prefill, ``error`` = a prefill crash) and
``disagg.ship`` (``error`` = a mid-transfer failure) make both retry
paths deterministic in chaos tests.
"""
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..obs.events import emit as emit_event
from ..obs.metrics import MetricsRegistry
from ..utils.faults import fault_site
from .wire import KVShipper

__all__ = ["PrefillJob", "PrefillWorker"]


class PrefillJob:
    """One request's prefill assignment. Plain data plus the
    dispatcher's failure callback; everything the decode side needs to
    reconstruct the request rides in :attr:`meta` fields."""

    __slots__ = ("rid", "prompt", "max_new_tokens", "temperature",
                 "top_k", "top_p", "deadline", "target", "ctx",
                 "enqueued_t", "attempts", "on_failed", "abandoned",
                 "clock", "tenant", "priority", "seed", "resume_from")

    def __init__(self, rid: int, prompt, max_new_tokens: int,
                 temperature=None, top_k=None, top_p=None,
                 deadline: Optional[float] = None, target=None,
                 ctx=None,
                 on_failed: Optional[Callable] = None,
                 clock=time.monotonic, tenant=None, priority=None,
                 seed=None, resume_from: int = 0):
        self.rid = int(rid)
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.deadline = deadline          # absolute on ``clock``, or None
        self.clock = clock                # the DISPATCHER's time source:
        # ``deadline`` was computed on it, so the worker's expiry guard
        # must read the same clock (an injected test clock and
        # time.monotonic share no origin)
        self.target = target              # (host, port) KVReceiver addr
        self.ctx = ctx                    # TraceContext captured at submit
        self.enqueued_t = time.monotonic()
        self.attempts = 0
        self.on_failed = on_failed
        # multi-tenant QoS: the decode side's fair queueing/preemption
        # act on these — they ride the wire meta with the rest of the
        # request's reconstruction fields
        self.tenant = None if tenant is None else str(tenant)
        self.priority = priority
        # crash-safe serving fields: the per-request RNG seed keys the
        # worker's first-token sample (position-deterministic, so a
        # resumed request re-samples identically), and resume_from
        # rides to the decode engine's forced-prefix admission
        self.seed = None if seed is None else int(seed)
        self.resume_from = int(resume_from)
        #: set by the dispatcher when the request terminated while this
        #: job was queued (cancel, deadline sweep): the worker drops it
        #: without spending prefill compute or wire bandwidth
        self.abandoned = False


class PrefillWorker:
    """One prefill worker: queue thread + engine + shipper.

    :param engine: the prefill engine (its ``export_prefill`` /
        ``register_prefix`` are the only paths used; ``max_slots=1``
        keeps its decode cache allocation minimal). The prefill tier
        is TARGET-only: a speculative (draft-carrying) engine's
        ``export_prefill`` raises at job time — draft KV never ships;
        speculative belongs on the DECODE workers, which recompute
        draft KV at admission from the shipped target frames.
    :param quant: ship Q8 (int8 data + f32 scales, ~0.27x the fp32
        bytes) instead of raw-dtype KV blocks.
    :param block_size: wire block size
        (:func:`~elephas_tpu.models.paged_decode.export_kv_blocks`).
    :param prefix_cache: enable the engine's TIER-LOCAL automatic
        prefix cache (host-array-backed, at the wire block size): a
        repeat prompt head skips its prefill compute entirely before
        the KV ever hits the wire. The cached head's positions ship
        bit-identically; the recomputed remainder agrees to float
        rounding (a different XLA program than whole-prompt prefill —
        the same caveat every chunked/prefix-reuse path carries), so
        decode output parity is unchanged and the decode side needs no
        changes. Default on; the engine's ``serving_kv_cache_*``
        series (this worker's registry) measure it.
        ``register_prefix`` on the engine remains the pinning layer.
    :param registry: metrics registry; defaults to the engine's, so one
        scrape covers the worker. The worker observes
        ``serving_queue_wait_seconds{tier="prefill"}`` (dispatch-to-
        prefill-start wait — the prefill tier's half of the per-stage
        queue-wait split) and ``disagg_prefills_total``.
    :param name: label for events and the dispatcher's bookkeeping.
    """

    def __init__(self, engine, quant: bool = True, block_size: int = 64,
                 registry: Optional[MetricsRegistry] = None,
                 name: str = "prefill-0", prefix_cache: bool = True):
        self.engine = engine
        self.quant = bool(quant)
        self.block_size = int(block_size)
        self.name = str(name)
        if prefix_cache and getattr(engine, "paged", None) is None:
            # tier-local automatic prefix cache at the wire block size
            # (host-backed — this engine never decodes, its pool is its
            # export rows); paged export engines keep their own pool
            # cache for admissions and are left alone here. This is a
            # DEFAULT-ON path, so ineligible engines (speculative mode,
            # or max_len at/below the wire block size — both worked
            # before the cache existed) skip enablement instead of
            # failing worker construction.
            enable = getattr(engine, "enable_prefix_cache", None)
            if (enable is not None
                    and getattr(engine, "draft_config", None) is None
                    and self.block_size < getattr(engine, "max_len", 0)):
                enable(block_size=self.block_size)
        self.shipper = KVShipper()
        reg = (registry if registry is not None
               else getattr(engine, "registry", None))
        if reg is None:
            reg = MetricsRegistry()
        self.registry = reg
        self._m_queue_wait = reg.histogram(
            "serving_queue_wait_seconds",
            "submit-to-admission wall time per admitted request, by "
            "serving tier", labels=("tier",)).labels(tier="prefill")
        self._m_prefills = reg.counter(
            "disagg_prefills_total",
            "prefills computed and shipped by this prefill worker"
            ).labels()
        self._cond = threading.Condition()
        self._jobs: deque = deque()
        self._current: Optional[PrefillJob] = None
        self._dead = False
        self._stopping = False
        self._thread: Optional[threading.Thread] = None
        # one-slot pipelined shipper (the PS plane's _PipelinedPusher
        # shape): encode+ship of job i overlaps the EXPORT of job i+1
        # on the worker thread — the wire round trip must not serialize
        # with prefill compute. At most one ship in flight; the worker
        # blocks handing over job i+1's frame until job i's ack landed,
        # so a ship failure still fails back before a second frame
        # could pass it.
        self._ship_cond = threading.Condition()
        self._ship_item = None          # (job, meta, kv_blocks) | None
        self._worker_done = False       # the drain loop exited
        self._ship_thread: Optional[threading.Thread] = None
        #: (queue_wait_s) samples for the /stats percentile surface
        self.wait_window: deque = deque(maxlen=1024)

    # ----------------------------------------------------------- lifecycle
    def start(self) -> "PrefillWorker":
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"disagg-{self.name}")
        self._ship_thread = threading.Thread(
            target=self._ship_loop, daemon=True,
            name=f"disagg-{self.name}-ship")
        self._thread.start()
        self._ship_thread.start()
        return self

    def stop(self):
        """Graceful: finish the current job, fail the rest back to the
        dispatcher, exit."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        with self._ship_cond:
            self._ship_cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if self._ship_thread is not None:
            self._ship_thread.join(timeout=10)
        self.shipper.close()

    def kill(self):
        """Abrupt worker death (the chaos verb): the shipper's sockets
        close NOW — a ship blocked mid-transfer fails immediately — and
        every queued job fails back to the dispatcher for retry on a
        sibling. The worker never accepts work again."""
        with self._cond:
            self._dead = True
            self._cond.notify_all()
        with self._ship_cond:
            self._ship_cond.notify_all()
        self.shipper.close()

    @property
    def alive(self) -> bool:
        with self._cond:
            return not (self._dead or self._stopping)

    # ------------------------------------------------------------ dispatch
    def submit(self, job: PrefillJob) -> None:
        """Queue a job. Raises when the worker is dead/stopping — the
        dispatcher's cue to pick a sibling."""
        with self._cond:
            if self._dead or self._stopping:
                raise RuntimeError(f"prefill worker {self.name} is not "
                                   "accepting work")
            self._jobs.append(job)
            self._cond.notify_all()

    def backlog(self) -> int:
        """Jobs queued, in prefill, or awaiting their ship ack — the
        dispatcher's least-loaded placement signal."""
        with self._cond:
            n = len(self._jobs) + (1 if self._current is not None
                                   else 0)
        with self._ship_cond:
            return n + (1 if self._ship_item is not None else 0)

    # ---------------------------------------------------------------- loop
    #: a queued job older than this is served FIFO regardless of size —
    #: shortest-prompt-first must not starve long prompts forever
    MAX_SJF_WAIT_S = 0.25

    def _pick_locked(self) -> PrefillJob:
        """Shortest-prompt-first with aging: a burst of long prompts
        must not head-of-line block the short steady prefills behind it
        (prefill cost scales with prompt length, so SJF minimizes mean
        wait), while the aging cap keeps long prompts from starving
        under sustained short traffic. Called under ``_cond``."""
        head = self._jobs[0]
        if time.monotonic() - head.enqueued_t >= self.MAX_SJF_WAIT_S:
            self._jobs.popleft()
            return head
        best = min(range(len(self._jobs)),
                   key=lambda i: (len(self._jobs[i].prompt),
                                  self._jobs[i].enqueued_t))
        job = self._jobs[best]
        del self._jobs[best]
        return job

    def _fail(self, job: PrefillJob, error: str) -> None:
        if job.on_failed is not None:
            try:
                job.on_failed(job, self.name, error)
            except Exception:  # noqa: BLE001 — a dispatcher bug must
                pass           # not kill the drain loop mid-handover

    def _loop(self):
        while True:
            with self._cond:
                while not (self._jobs or self._dead or self._stopping):
                    self._cond.wait(timeout=0.5)
                if self._dead or self._stopping:
                    # the stop() contract: finish the CURRENT job (we
                    # are between jobs here), fail the queued rest back
                    # to the dispatcher — draining a deep backlog would
                    # blow past stop()'s join timeout and yank the
                    # shipper out from under a live transfer
                    orphans = list(self._jobs)
                    self._jobs.clear()
                    break
                job = self._pick_locked()
                self._current = job
            try:
                self._run_job(job)
            except Exception as exc:  # noqa: BLE001 — ANY failure fails
                # the job back to the dispatcher (killed shipper, engine
                # error, injected fault); the worker itself survives
                # unless it was killed
                self._fail(job, f"{type(exc).__name__}: {exc}")
            finally:
                with self._cond:
                    self._current = None
        for job in orphans:
            self._fail(job, "worker killed")
        with self._ship_cond:
            self._worker_done = True
            self._ship_cond.notify_all()

    def _run_job(self, job: PrefillJob) -> None:
        with self._cond:
            if self._dead:
                raise RuntimeError("worker killed")
        if job.abandoned or (job.deadline is not None
                             and job.clock() >= job.deadline):
            # cancelled / expired while queued here: prefilling it
            # would spend compute and wire bandwidth on a frame the
            # decode side is guaranteed to drop — exactly when the
            # tier is most loaded. Silently skip: the dispatcher
            # already terminated the request (or its deadline sweep
            # will), so no fail-back either.
            return
        # live weight plane: a staged swap lands between jobs — the
        # prefill engine never runs step(), so THIS is its atomic
        # point. Applying before the export (not after) means the KV
        # shipped for this job is computed — and version-stamped —
        # under the newest staged weights.
        apply_staged = getattr(self.engine, "apply_staged_params", None)
        if apply_staged is not None:
            apply_staged()
        wait = time.monotonic() - job.enqueued_t
        self._m_queue_wait.observe(wait)
        with self._cond:
            # appends serialize with wait_samples(): iterating a deque
            # another thread is appending to raises RuntimeError
            self.wait_window.append(wait)
        if job.ctx is not None:
            # the tier's queue time, retroactively, on the request's
            # tree — otherwise it reads as unattributed TTFT
            from ..obs.spans import add_span
            add_span("disagg.prefill_queue", time.time() - wait, wait,
                     stage="admission_wait", ctx=job.ctx,
                     worker=self.name)
        from ..obs.context import use_context
        from ..obs.spans import start_span

        with use_context(job.ctx), \
                start_span("disagg.prefill", stage="prefill",
                           worker=self.name):
            fault_site("disagg.prefill")
            out = self.engine.export_prefill(
                job.prompt, temperature=job.temperature,
                top_k=job.top_k, top_p=job.top_p,
                block_size=self.block_size, seed=job.seed)
        meta = {"rid": job.rid, "prompt": job.prompt,
                "max_new_tokens": job.max_new_tokens,
                "temperature": job.temperature,
                "top_k": job.top_k, "top_p": job.top_p,
                "tenant": job.tenant, "priority": job.priority,
                "seed": job.seed, "resume_from": job.resume_from,
                "deadline": job.deadline,
                "first_token": out["first_token"],
                "prompt_tokens": out["prompt_tokens"],
                "prefix_tokens": out["prefix_tokens"],
                # tokens the tier-local automatic cache served (prefill
                # compute skipped before the wire) — observability only
                "cached_tokens": out.get("cached_tokens", 0),
                "prefill_s": out["prefill_s"],
                # the weight version this KV was computed under — the
                # decode side rejects (and the dispatcher retries) a
                # frame whose stamp mismatches its live version
                "weights_version": out.get("weights_version", 0),
                "queue_wait_s": round(wait, 6),
                "worker": self.name,
                "codec": "q8" if self.quant else "fp",
                "block_size": out["block_size"]}
        self._hand_to_shipper(job, meta, out["kv_blocks"])

    def _hand_to_shipper(self, job: PrefillJob, meta: Dict,
                         kv_blocks) -> None:
        """Block until the PREVIOUS ship completed (one in flight),
        then hand this job's frame to the ship thread — pipelining the
        wire round trip behind the next job's prefill compute."""
        with self._ship_cond:
            while self._ship_item is not None and not self._dead:
                self._ship_cond.wait(timeout=0.1)
            if self._dead:
                raise RuntimeError("worker killed")
            self._ship_item = (job, meta, kv_blocks)
            self._ship_cond.notify_all()

    def _ship_loop(self):
        while True:
            with self._ship_cond:
                while (self._ship_item is None
                       and not (self._dead or self._worker_done)):
                    self._ship_cond.wait(timeout=0.2)
                item = self._ship_item
                if item is None:
                    if self._dead or self._worker_done:
                        return
                    continue
            job, meta, kv_blocks = item
            try:
                if job.abandoned:
                    continue       # finally still clears the slot
                from ..obs.context import current_context, use_context
                from ..obs.spans import start_span

                with use_context(job.ctx), \
                        start_span("disagg.ship", stage="kv_wire",
                                   worker=self.name):
                    fault_site("disagg.ship")
                    # forward the SHIP SPAN's context (not the job's):
                    # the receiver-side install then parents to this
                    # wire hop on the request's tree
                    nbytes = self.shipper.ship(
                        job.target, meta, kv_blocks, quant=self.quant,
                        ctx=current_context())
                self._m_prefills.inc()
                emit_event("disagg.prefill_shipped", rid=job.rid,
                           worker=self.name, bytes=nbytes,
                           codec="q8" if self.quant else "fp",
                           prefill_s=meta.get("prefill_s"))
            except Exception as exc:  # noqa: BLE001 — ship failures
                # (killed shipper, dead receiver, injected fault) fail
                # the job back for retry on a sibling
                self._fail(job, f"{type(exc).__name__}: {exc}")
            finally:
                with self._ship_cond:
                    self._ship_item = None
                    self._ship_cond.notify_all()

    # ------------------------------------------------------------- queries
    def wait_samples(self) -> List[float]:
        """A consistent snapshot of the queue-wait window (the worker
        thread appends concurrently — an unlocked iteration would
        intermittently raise mid-scrape)."""
        with self._cond:
            return list(self.wait_window)

    def stats(self) -> Dict:
        waits: List[float] = self.wait_samples()
        out: Dict = {"name": self.name, "alive": self.alive,
                     "backlog": self.backlog(),
                     "prefills": int(self._m_prefills.value)}
        if waits:
            from ..obs.metrics import percentile

            out["queue_wait_p50_s"] = round(percentile(waits, 0.5), 6)
            out["queue_wait_p99_s"] = round(percentile(waits, 0.99), 6)
        return out
