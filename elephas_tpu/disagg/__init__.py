"""Disaggregated prefill/decode serving.

Prefill is compute-bound and bursty; decode is latency-bound and
steady. This package splits them into independent tiers — prefill
workers compute a prompt's KV state and SHIP it (paged KV blocks,
optionally Q8 on the wire) to a decode worker over the zero-copy
codec/socket path, where it installs into a slot between decode steps.
Decode-tier queue wait is then free of prefill head-of-line blocking,
and the two tiers scale independently — the defining architecture of
high-QPS LLM serving.

- :mod:`.wire` — the KV frame format + socket shipper/receiver
- :mod:`.prefill` — prefill workers (the compute tier)
- :mod:`.engine` — :class:`DisaggEngine`, the decode-worker engine a
  :class:`~elephas_tpu.serving_http.ServingServer` fronts
- :mod:`.pool` — :class:`DisaggPool`, the in-process two-tier topology
  a :class:`~elephas_tpu.fleet.FleetRouter` can front

``docs/sources/disaggregated-serving.md`` has the topology, wire
format, Q8 trade-offs, and the ops runbook.
"""
from .engine import DisaggEngine
from .pool import DisaggPool
from .prefill import PrefillJob, PrefillWorker
from .wire import KVReceiver, KVShipper, decode_kv_frame, encode_kv_frame

__all__ = ["DisaggEngine", "DisaggPool", "PrefillJob", "PrefillWorker",
           "KVReceiver", "KVShipper", "decode_kv_frame",
           "encode_kv_frame"]
