"""DisaggEngine: the decode-worker front of disaggregated serving.

Implements the engine API a
:class:`~elephas_tpu.serving_http.ServingServer` drives (``submit`` /
``step`` / ``pending`` / ``result_info`` / ``cancel`` / ``stats`` /
flight-recorder traces), but splits the request lifecycle across two
tiers:

1. ``submit`` hands the prompt to the least-backlogged live
   :class:`~.prefill.PrefillWorker` (prefill is compute-bound and
   bursty — it runs OFF the decode engine's loop).
2. The worker prefills, packs paged KV blocks, and ships them to this
   engine's :class:`~.wire.KVReceiver` (Q8 on the wire by default).
3. ``step`` — called by the server's engine loop, the single driver of
   the device program — first INSTALLS every received frame into the
   decode engine between decode steps
   (:meth:`~elephas_tpu.serving_engine.DecodeEngine.submit_prefilled`:
   the atomic slot install), then steps the decode batch.

The decode engine never runs a prefill, so its queue-wait series
(``serving_queue_wait_seconds{tier="decode"}``) is pure decode-stage
backlog — the p99 the colocated engine's prefill head-of-line blocking
inflates. Retry policy: a prefill job that fails (killed worker,
severed transfer, injected fault) re-dispatches to a sibling worker;
with no live worker it parks and retries as workers return. A replayed
frame (ack lost mid-kill) deduplicates by request id. One trace id
spans the whole path: the context captured at submit rides the job, the
wire's traceparent frame, and the decode engine's own recorder.
"""
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..fleet.resilience import (MAX_STALE_KV_RETRIES as
                                _MAX_STALE_KV_RETRIES)
from ..fleet.resilience import (PREFILL_RETRY_BUDGET, CircuitBreaker)
from ..fleet.resilience import STALE_KV_RETRY_S as _STALE_KV_RETRY_S
from ..obs.context import current_context
from ..obs.events import FlightRecorder
from ..obs.events import emit as emit_event
from ..serving_engine import QueueFullError, validate_sampling_overrides
from .prefill import PrefillJob, PrefillWorker
from .wire import KVReceiver

__all__ = ["DisaggEngine"]


class DisaggEngine:
    """Decode worker + prefill-tier dispatcher behind one engine API.

    :param decode_engine: a
        :class:`~elephas_tpu.serving_engine.DecodeEngine` (construct it
        with ``tier="decode"`` so its queue-wait series lands on the
        decode-tier label); paged or contiguous both work, and so does
        SPECULATIVE mode — the shipped frames are the TARGET model's
        KV, which the engine installs before its first draft round
        (draft KV is recomputed locally at admission, never shipped).
        The PREFILL tier stays target-only either way: give its
        workers plain engines built from the same target params.
    :param prefill_workers: the prefill tier — shared freely between
        several DisaggEngines (that is the independent-scaling point).
    :param max_queue: bound on requests in the PREFILL stage (queued at
        workers, parked, or in transfer); breaching it sheds with
        :class:`~elephas_tpu.serving_engine.QueueFullError` (HTTP 429).
        The decode engine's own admission bounds still apply beneath.
    :param host, port: bind address for this engine's KV receiver.
    """

    def __init__(self, decode_engine, prefill_workers:
                 Sequence[PrefillWorker],
                 max_queue: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 clock=time.monotonic):
        if not prefill_workers:
            raise ValueError("need at least one prefill worker")
        self.decode = decode_engine
        self.workers = list(prefill_workers)
        self.max_queue = None if max_queue is None else int(max_queue)
        self._clock = clock
        self.registry = reg = decode_engine.registry
        self.recorder = FlightRecorder()
        self._lock = threading.Lock()
        self._next_rid = 0
        # rid -> {"state": queued|imported|decoding|done, "job",
        #         "drid", "deadline", "retries", "tenant", "ptokens"}
        self._stage: Dict[int, Dict] = {}
        # tenant -> prompt tokens currently staged in the PREFILL tier
        # (queued at workers, parked, or in transfer): the decode
        # engine's per-tenant quota only sees its own queue, which a
        # disagg request enters at KV-install time — counting staged
        # tokens at submit is what makes the quota bite at THIS front
        # end instead of letting a tenant pile work into the prefill
        # stage bounded only by the global max_queue
        self._tenant_staged: Dict[str, int] = {}
        self._rid_of_drid: Dict[int, int] = {}
        # rid -> decode rid kept for trace merging AFTER the result is
        # fetched (the live _stage entry pops then); bounded like the
        # recorder ring it serves
        self._trace_drid: "OrderedDict[int, int]" = OrderedDict()
        self._imports: deque = deque()   # (meta, arrays, nbytes)
        self._parked: deque = deque()    # jobs with no live worker
        # (job, not_before) — version-mismatch rejections waiting out
        # the rollout window before re-dispatching (see the STALE_KV_*
        # constants at the gate)
        self._stale_retry: deque = deque()
        self._results: Dict[int, Dict] = {}   # disagg-terminal outcomes
        # per-prefill-worker circuit breaker: a worker failing jobs
        # repeatedly is skipped by dispatch while siblings exist, then
        # probed with one job after the cooldown
        self._prefill_circuits = CircuitBreaker(
            registry=reg, scope="prefill_worker", clock=clock)
        self._m_requests = reg.counter(
            "disagg_requests_total",
            "requests accepted by the disaggregated front end").labels()
        self._m_retries = reg.counter(
            "disagg_prefill_retries_total",
            "prefill jobs re-dispatched after a worker failure").labels()
        self._m_frames = reg.counter(
            "disagg_kv_frames_total",
            "KV frames received and installed, by codec",
            labels=("codec",))
        self._m_kv_bytes = reg.counter(
            "disagg_kv_bytes_total",
            "KV payload bytes received, by codec", labels=("codec",))
        import weakref

        ref = weakref.ref(self)
        reg.gauge("disagg_prefill_stage_depth",
                  "requests in the prefill stage (queued at workers, "
                  "parked, or in transfer)").set_function(
            lambda: float(e._prefill_stage_depth())
            if (e := ref()) is not None else 0.0)
        self.receiver = KVReceiver(self._on_frame, host=host,
                                   port=int(port)).start()

    # ----------------------------------------------------------- lifecycle
    def stop(self):
        """Close the KV receiver. The prefill workers are a shared tier
        owned by whoever built them (:class:`~.pool.DisaggPool`)."""
        self.receiver.stop()

    # -------------------------------------------------------------- submit
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               admit: bool = True,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               priority=None,
               seed: Optional[int] = None,
               resume_from: int = 0) -> int:
        """Queue a request; the prefill tier computes its KV state and
        this engine decodes it. Same argument semantics as
        :meth:`~elephas_tpu.serving_engine.DecodeEngine.submit`
        (``admit`` is accepted for interface parity; admission is
        always deferred to the engine loop here — prefill runs
        off-thread regardless). ``tenant``/``priority`` ride the wire
        meta to the decode engine, whose QoS policy (fair queueing,
        quotas, preemption) acts on them at KV-install admission.
        ``seed``/``resume_from`` compose the same way: the seed keys
        the prefill worker's first-token sample and every decode step
        (position-deterministic), and ``resume_from`` rides the wire
        meta to the decode engine's forced-prefix admission — so a
        dead decode worker's requests resume on a sibling exactly like
        the aggregated fleet's, shipped-frame path unchanged."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        # fail fast with the decode engine's own validation messages:
        # an inadmissible request must 400 at submit, not die on a
        # worker thread after shipping
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        # the decode engine's own permanently-inadmissible rules, run
        # HERE so they 400 at submit — failing them at KV-install time
        # would raise inside the server's engine loop and read as
        # engine death (500s for everyone) instead of one bad request
        self.decode.check_admissible(int(prompt.size),
                                     int(max_new_tokens), prompt=prompt,
                                     tenant=tenant)
        validate_sampling_overrides(temperature, top_k, top_p)
        if (getattr(self.decode, "draft_config", None) is not None
                and (temperature is not None or top_k is not None
                     or top_p is not None)):
            # mirror the decode engine's own submit rule so the 400
            # lands HERE instead of at KV-install time inside the
            # engine loop (which would terminate the request late,
            # after a prefill and a wire round trip)
            raise ValueError("per-request sampling settings are not "
                             "supported in speculative mode")
        if deadline_ms is not None and not deadline_ms > 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        # the decode engine's own seed/resume rules, enforced at THIS
        # submit so they 400 here instead of dying at KV-install time
        if seed is not None:
            if getattr(self.decode, "draft_config", None) is not None:
                raise ValueError("per-request seeds are not supported "
                                 "in speculative mode")
            seed = int(seed)
            if not 0 <= seed < 2 ** 31:
                raise ValueError(
                    f"seed must be in [0, 2**31), got {seed}")
        resume_from = int(resume_from)
        if resume_from and not 0 < resume_from < prompt.size:
            raise ValueError(
                f"resume_from ({resume_from}) must leave at least one "
                f"real prompt token (prompt has {prompt.size})")
        if tenant is not None:
            # the per-tenant quota 429, enforced at THIS front end's
            # submit exactly like the decode engine's own (the shared
            # validator — a quota-breached tenant sheds identically at
            # every surface, with the quota-aware backoff hint and the
            # same counter/event bookkeeping). The tenant's tokens
            # already staged in the prefill tier count against the
            # quota too — they haven't reached the decode queue yet,
            # but they are committed work the quota exists to bound.
            with self._lock:
                staged = self._tenant_staged.get(tenant, 0)
            try:
                self.decode.check_tenant_admissible(
                    tenant, int(prompt.size) + staged)
            except QueueFullError:
                self.decode.record_shed(tenant, "tenant_quota",
                                        staged_tokens=staged)
                raise
        with self._lock:
            if (self.max_queue is not None
                    and self._prefill_depth_locked() >= self.max_queue):
                emit_event("serving.shed", reason="disagg_max_queue",
                           queue_depth=self._prefill_depth_locked())
                raise QueueFullError(
                    f"prefill stage full: {self._prefill_depth_locked()}"
                    f" requests in flight (max_queue={self.max_queue})",
                    self.decode.retry_after_ms())
            rid = self._next_rid
            self._next_rid += 1
        ctx = current_context()
        deadline = (None if deadline_ms is None
                    else self._clock() + float(deadline_ms) / 1000.0)
        self.recorder.start(
            rid, trace_id=None if ctx is None else ctx.trace_id,
            prompt_tokens=int(prompt.size),
            max_new_tokens=int(max_new_tokens),
            **({} if tenant is None else {"tenant": str(tenant)}))
        job = PrefillJob(rid, prompt, max_new_tokens,
                         temperature=temperature, top_k=top_k,
                         top_p=top_p, deadline=deadline,
                         target=self.receiver.addr, ctx=ctx,
                         on_failed=self._job_failed, clock=self._clock,
                         tenant=tenant, priority=priority,
                         seed=seed, resume_from=resume_from)
        with self._lock:
            self._stage[rid] = {"state": "queued", "job": job,
                                "drid": None, "deadline": deadline,
                                "retries": 0, "tenant": tenant,
                                # the CLIENT-submit stamp, passed to
                                # submit_prefilled at KV install so
                                # the decode engine's TTFT includes
                                # the prefill tier's queue+ship time
                                "submit_mono": time.monotonic(),
                                "ptokens": (int(prompt.size)
                                            if tenant is not None
                                            else 0)}
            if tenant is not None:
                self._tenant_staged[tenant] = (
                    self._tenant_staged.get(tenant, 0)
                    + int(prompt.size))
        self._m_requests.inc()
        self._dispatch(job)
        return rid

    def add_worker(self, worker: PrefillWorker) -> None:
        """Register a prefill worker added after construction (the
        autoscaler growing the tier): the next dispatch — including
        parked jobs retried by the engine loop — considers it like any
        sibling. Idempotent."""
        with self._lock:
            if worker not in self.workers:
                self.workers.append(worker)

    # ------------------------------------------------------------ dispatch
    def _dispatch(self, job: PrefillJob) -> None:
        """Least-backlogged live worker, or park until one returns.
        Workers whose circuit is OPEN are skipped while an allowed
        sibling exists; with every circuit open the full candidate
        list is used (fail-static beats parking forever)."""
        candidates = sorted((w for w in self.workers if w.alive),
                            key=lambda w: w.backlog())
        allowed = [w for w in candidates
                   if self._prefill_circuits.allow(w.name)]
        if allowed:
            candidates = allowed
        for worker in candidates:
            try:
                worker.submit(job)
            except RuntimeError:
                continue          # died between the check and the submit
            self.recorder.record(job.rid, "prefill_dispatched",
                                 worker=worker.name,
                                 attempt=job.attempts + 1)
            job.attempts += 1
            return
        with self._lock:
            self._parked.append(job)
        self.recorder.record(job.rid, "prefill_parked",
                             reason="no live prefill workers")

    #: retry budget per request: a job failing this many times is
    #: systemically broken (every worker rejects it, or the receiver is
    #: unreachable) — it terminates with an ``expired`` outcome instead
    #: of recomputing the same prefill in a hot loop forever. Sourced
    #: from the fleet-wide defaults in :mod:`..fleet.resilience`.
    MAX_PREFILL_RETRIES = PREFILL_RETRY_BUDGET

    #: spacing for version-mismatch KV re-dispatches: the rollout
    #: window where the prefill tier lags the decode tier heals on the
    #: prefill subscribers' poll cadence (default 0.25 s), so retrying
    #: hotter than this only burns prefill compute and wire bytes on
    #: frames guaranteed to bounce
    STALE_KV_RETRY_S = _STALE_KV_RETRY_S
    #: spaced mismatch retries before a job falls through to the
    #: systemic :data:`MAX_PREFILL_RETRIES` path (>= 10 s of rollout
    #: window at the default spacing) — a prefill tier that never
    #: converges is a dead subscriber, not a rollout
    MAX_STALE_KV_RETRIES = _MAX_STALE_KV_RETRIES

    def _job_failed(self, job: PrefillJob, worker: str, error: str):
        """A worker failed a job (its own thread calls this): re-queue
        on a sibling — the client request is retried, never failed —
        up to :data:`MAX_PREFILL_RETRIES`, past which it terminates
        (an unbounded deterministic failure must not spin a core). A
        job whose propagated deadline has already passed terminates
        NOW — a retry could never answer in time, so re-prefilling is
        pure waste — with the expiry attributed to its stage."""
        with self._lock:
            st = self._stage.get(job.rid)
            if st is None or st["state"] != "queued":
                return            # cancelled, or a duplicate completion
            st["retries"] += 1
            exhausted = st["retries"] >= self.MAX_PREFILL_RETRIES
            past_deadline = (not exhausted
                             and job.deadline is not None
                             and self._clock() >= job.deadline)
            terminal = exhausted or past_deadline
            if terminal:
                st["state"] = "done"
                self._release_stage_locked(st)
                self._results[job.rid] = {
                    "tokens": [], "timeout": True, "expired": True,
                    "stage": ("prefill_retries_exhausted" if exhausted
                              else "prefill_retry_past_deadline"),
                    "error": error}
        self._prefill_circuits.record_failure(worker)
        self._m_retries.inc()
        emit_event("disagg.prefill_retried", rid=job.rid, worker=worker,
                   error=error, exhausted=terminal)
        self.recorder.record(job.rid, "prefill_retry", worker=worker,
                             error=error)
        if terminal:
            self.recorder.record(
                job.rid, "expired",
                stage=("prefill_retries_exhausted" if exhausted
                       else "prefill_retry_past_deadline"),
                error=error)
            return
        self._dispatch(job)

    # ------------------------------------------------------------ receiver
    def _on_frame(self, meta: Dict, arrays: List[np.ndarray],
                  nbytes: int) -> None:
        """KV frame delivery (receiver connection thread): enqueue for
        installation by the next ``step``. Duplicates (a replayed frame
        after a lost ack) and frames for cancelled rids drop here."""
        rid = int(meta.get("rid", -1))
        with self._lock:
            st = self._stage.get(rid)
            if st is None or st["state"] != "queued":
                return
        # reassemble the row HERE, on the receiver thread: the engine
        # loop then pays only the device install, not the host-side
        # block unpacking (which would serialize with decode steps)
        from ..models.paged_decode import import_kv_blocks

        row = import_kv_blocks(arrays, int(meta["prompt_tokens"]),
                               self.decode.max_len)
        with self._lock:
            st = self._stage.get(rid)
            if st is None or st["state"] != "queued":
                return
            st["state"] = "imported"
            self._imports.append((meta, row, int(nbytes)))
        self.recorder.record(
            rid, "kv_transfer", bytes=int(nbytes),
            worker=meta.get("worker"),
            prefill_s=meta.get("prefill_s"),
            prefill_queue_wait_s=meta.get("queue_wait_s"))

    # ---------------------------------------------------------------- step
    @property
    def pending(self) -> int:
        """Work the engine loop can advance by calling :meth:`step`:
        frames awaiting install, parked jobs awaiting a live worker
        (counted only while one IS alive — with the whole tier down a
        parked job cannot progress, and counting it would busy-spin the
        engine loop at 100% doing nothing), prefill-stage requests
        whose deadline needs enforcing, and the decode engine's own
        pending count. Requests merely WAITING on a prefill worker do
        not count — the loop idles (5 ms cadence) instead of spinning
        while the network does its thing."""
        any_alive = any(w.alive for w in self.workers)
        with self._lock:
            n = len(self._imports)
            if any_alive:
                n += len(self._parked)
            now = self._clock()
            # stale-KV re-dispatches count only once DUE — while they
            # wait out their delay the loop idles instead of spinning
            n += sum(1 for _, at in self._stale_retry if now >= at)
            n += sum(1 for st in self._stage.values()
                     if st["state"] == "queued"
                     and st["deadline"] is not None
                     and now >= st["deadline"])
        return n + self.decode.pending

    def step(self) -> Dict[int, List[int]]:
        """Install received KV frames into the decode engine (between
        decode steps — the atomic point), retry parked jobs, enforce
        prefill-stage deadlines, then advance the decode batch. Returns
        ``{rid: [tokens]}`` keyed by THIS engine's request ids."""
        # apply any staged live-weight swap BEFORE gating frames: the
        # version gate below must compare against the version this
        # step's installs will actually decode under, not one a
        # decode.step()-internal swap is about to replace
        self.decode.apply_staged_params()
        self._sweep_deadlines()
        self._retry_stale()
        self._retry_parked()
        self._install_imports()
        emitted = self.decode.step() if self.decode.pending else {}
        if not emitted:
            return {}
        with self._lock:
            return {self._rid_of_drid.get(drid, drid): toks
                    for drid, toks in emitted.items()}

    def _sweep_deadlines(self):
        """Expire prefill-stage requests whose deadline passed before
        their KV ever arrived — the disagg mirror of the decode
        engine's shed-while-queued (HTTP 504)."""
        now = self._clock()
        expired: List[int] = []
        with self._lock:
            for rid, st in self._stage.items():
                if (st["state"] == "queued"
                        and st["deadline"] is not None
                        and now >= st["deadline"]):
                    st["state"] = "done"
                    self._release_stage_locked(st)
                    if st["job"] is not None:
                        # a worker still holding this job skips it
                        st["job"].abandoned = True
                    self._results[rid] = {"tokens": [], "timeout": True,
                                          "expired": True}
                    expired.append(rid)
            for rid in expired:
                self._drop_parked_locked(rid)
        for rid in expired:
            self.recorder.record(rid, "expired", stage="prefill")

    def _drop_parked_locked(self, rid: int) -> None:
        self._parked = deque(j for j in self._parked if j.rid != rid)
        self._stale_retry = deque((j, t) for j, t in self._stale_retry
                                  if j.rid != rid)

    def _retry_stale(self):
        """Re-dispatch version-mismatch rejections whose delay elapsed
        (their jobs recompute the prefill — under the worker's by-then
        hopefully-swapped weights)."""
        now = self._clock()
        due: List = []
        with self._lock:
            keep: deque = deque()
            for job, at in self._stale_retry:
                if now >= at:
                    due.append(job)
                else:
                    keep.append((job, at))
            self._stale_retry = keep
        for job in due:
            if not job.abandoned:
                self._dispatch(job)

    def _retry_parked(self):
        with self._lock:
            jobs = list(self._parked)
            self._parked.clear()
        for job in jobs:
            self._dispatch(job)   # re-parks itself if still no worker

    def _install_imports(self):
        with self._lock:
            batch = list(self._imports)
            self._imports.clear()
        held: List = []   # tenant-quota-blocked frames: re-queued at
        # the end WITHOUT stopping the loop — one tenant at its quota
        # must never head-of-line-block other tenants' installs
        stop: Optional[int] = None
        for i, (meta, arrays, nbytes) in enumerate(batch):
            rid = int(meta["rid"])
            with self._lock:
                st = self._stage.get(rid)
                if st is None or st["state"] != "imported":
                    continue      # cancelled while in the import queue
                job = st["job"]
            # live-weight version gate: KV computed under one weight
            # version must not install into a decode batch running
            # another — decoding would be silently WRONG output, not an
            # error. A mismatch is NORMAL for the length of a rollout
            # (decode and prefill tiers' subscribers poll
            # independently), so rejected frames re-dispatch on a
            # DELAYED schedule with their own generous budget instead
            # of burning the systemic MAX_PREFILL_RETRIES in a hot
            # recompute/reject loop — only a tier that never converges
            # (a dead subscriber) falls through to the systemic path
            # and terminates the request.
            wire_v = meta.get("weights_version")
            engine_v = int(self.decode.weights_version)
            if wire_v is not None and int(wire_v) != engine_v:
                delayed = False
                with self._lock:
                    st2 = self._stage.get(rid)
                    if st2 is None or st2["state"] != "imported":
                        continue
                    st2["state"] = "queued"   # back to the prefill stage
                    st2["stale_retries"] = st2.get("stale_retries", 0) + 1
                    if (job is not None and st2["stale_retries"]
                            <= self.MAX_STALE_KV_RETRIES):
                        self._stale_retry.append(
                            (job, self._clock() + self.STALE_KV_RETRY_S))
                        delayed = True
                emit_event("disagg.kv_version_mismatch", rid=rid,
                           frame_version=int(wire_v),
                           engine_version=engine_v,
                           worker=meta.get("worker"))
                self.recorder.record(rid, "kv_rejected",
                                     reason="weights_version_mismatch",
                                     frame_version=int(wire_v),
                                     engine_version=engine_v)
                if not delayed and job is not None:
                    self._job_failed(
                        job, str(meta.get("worker", "?")),
                        f"KV weights_version {wire_v} != decode engine "
                        f"version {engine_v} after "
                        f"{self.MAX_STALE_KV_RETRIES} spaced retries")
                continue
            deadline = meta.get("deadline")
            remaining_ms = None
            if deadline is not None:
                remaining_ms = (float(deadline) - self._clock()) * 1000.0
                if remaining_ms <= 0:
                    with self._lock:
                        st["state"] = "done"
                        self._release_stage_locked(st)
                        self._results[rid] = {"tokens": [],
                                              "timeout": True,
                                              "expired": True,
                                              "stage": "kv_import"}
                    self.recorder.record(rid, "expired",
                                         stage="kv_import")
                    continue
            # capacity pre-check WITHOUT the engine's shed bookkeeping:
            # an internal install retry runs every step, and letting it
            # hit the submit bound would inc the shed counter and emit
            # a serving.shed event PER ATTEMPT — flooding the overload
            # signal this metric exists to diagnose. The QueueFullError
            # handler below stays as the backstop for bounds the peek
            # cannot see (injected sheds).
            if self.decode.would_shed(len(meta["prompt"])):
                # GLOBAL backpressure: no frame can install until the
                # next step shrinks the backlog — put the rest back
                stop = i
                break
            tenant = meta.get("tenant")
            if tenant is not None and self.decode.would_shed(
                    len(meta["prompt"]), tenant=tenant):
                # THIS tenant's quota: hold only its frame (it waits
                # for the tenant's own decode backlog to drain,
                # without the shed bookkeeping a bounced submit would
                # record) — frames from other tenants behind it keep
                # installing
                held.append((meta, arrays, nbytes))
                continue
            codec = str(meta.get("codec", "fp"))
            from ..obs.context import use_context

            try:
                with use_context(None if job is None else job.ctx):
                    # the version stamp rides through: the engine
                    # re-gates at the actual install (a swap staged
                    # between OUR gate above and that install falls
                    # back to a local prefill instead of decoding over
                    # mismatched KV)
                    drid = self.decode.submit_prefilled(
                        meta["prompt"], int(meta["max_new_tokens"]),
                        arrays, int(meta["first_token"]),
                        temperature=meta.get("temperature"),
                        top_k=meta.get("top_k"), top_p=meta.get("top_p"),
                        admit=False, deadline_ms=remaining_ms,
                        weights_version=(None if wire_v is None
                                         else int(wire_v)),
                        tenant=meta.get("tenant"),
                        priority=meta.get("priority"),
                        seed=meta.get("seed"),
                        resume_from=int(meta.get("resume_from") or 0),
                        # TTFT measures from the CLIENT's submit: the
                        # prefill tier's queue wait, compute, and KV
                        # ship all land inside it (queue-wait series
                        # stay pure decode-stage, by design)
                        submitted_at=st.get("submit_mono"))
            except QueueFullError:
                # the decode engine's own admission bound (or an
                # injected serving.submit shed): TRANSIENT — put this
                # frame AND the rest of the drained batch back (in
                # order) and retry after the next step shrinks the
                # backlog; raising here would kill the engine loop
                stop = i
                break
            except Exception as exc:  # noqa: BLE001 — an inadmissible
                # request that slipped past submit-time validation is
                # ONE bad request, never whole-server death: terminate
                # it with the error attached
                with self._lock:
                    st2 = self._stage.get(rid)
                    if st2 is not None:
                        st2["state"] = "done"
                        self._release_stage_locked(st2)
                        self._results[rid] = {
                            "tokens": [], "timeout": True,
                            "expired": True,
                            "error": f"{type(exc).__name__}: {exc}"}
                self.recorder.record(rid, "expired",
                                     stage="kv_install_rejected",
                                     error=str(exc))
                continue
            self._m_frames.labels(codec=codec).inc()
            self._m_kv_bytes.labels(codec=codec).inc(nbytes)
            # a delivered-and-installed frame is the worker's health
            # proof: closes its circuit (and resolves a half-open
            # probe claim) after a failure streak
            worker_name = meta.get("worker")
            if worker_name is not None:
                self._prefill_circuits.record_success(str(worker_name))
            with self._lock:
                if self._stage.get(rid) is not st:
                    # cancelled between the check above and the decode
                    # submit: don't decode for nobody
                    self.decode.cancel(drid)
                    continue
                st["state"] = "decoding"
                self._release_stage_locked(st)
                st["drid"] = drid
                st["job"] = None          # the KV blocks can free now
                self._rid_of_drid[drid] = rid
                self._trace_drid[rid] = drid
                while len(self._trace_drid) > self.recorder.max_requests:
                    self._trace_drid.popitem(last=False)
            self.recorder.record(rid, "decode_submitted", decode_rid=drid)
        if held or stop is not None:
            # re-queue in ORIGINAL order: held frames arrived before
            # the globally-stopped tail
            rest = batch[stop:] if stop is not None else []
            with self._lock:
                self._imports.extendleft(reversed(held + rest))

    def _release_stage_locked(self, st: Dict) -> None:
        """Return a request's prompt tokens to its tenant's staged
        budget — called (under the lock) at EVERY transition out of
        the prefill stage: decode handoff, expiry, retry exhaustion,
        cancel. Idempotent: the entry's ``ptokens`` zeroes on first
        release."""
        n, tenant = st.get("ptokens", 0), st.get("tenant")
        st["ptokens"] = 0
        if not n or tenant is None:
            return
        left = self._tenant_staged.get(tenant, 0) - n
        if left > 0:
            self._tenant_staged[tenant] = left
        else:
            self._tenant_staged.pop(tenant, None)

    def _prefill_depth_locked(self) -> int:
        return sum(1 for st in self._stage.values()
                   if st["state"] in ("queued", "imported"))

    def _prefill_stage_depth(self) -> int:
        with self._lock:
            return self._prefill_depth_locked()

    # -------------------------------------------------------------- results
    def result_info(self, rid: int) -> Optional[Dict]:
        with self._lock:
            if rid in self._results:
                self._stage.pop(rid, None)
                return self._results.pop(rid)
            st = self._stage.get(rid)
            drid = None if st is None else st["drid"]
        if drid is None:
            return None           # unknown or still in the prefill stage
        out = self.decode.result_info(drid)
        if out is not None:
            with self._lock:
                self._stage.pop(rid, None)
                self._rid_of_drid.pop(drid, None)
        return out

    def result(self, rid: int) -> Optional[List[int]]:
        info = self.result_info(rid)
        return None if info is None else info["tokens"]

    def cancel(self, rid: int) -> bool:
        with self._lock:
            st = self._stage.get(rid)
            if st is None:
                return False
            if st["state"] == "done":
                # already terminal in the prefill stage (expired /
                # retries exhausted): cancel of a finished request is
                # False by the engine convention — and must NOT fall
                # through to decode.cancel(drid=None). Drop the parked
                # result so an expire-then-cancel client cannot leak
                # an entry per request.
                self._stage.pop(rid, None)
                self._results.pop(rid, None)
                return False
            if st["state"] in ("queued", "imported"):
                # the prefill may still complete on its worker; the
                # late frame (or a replay) drops in _on_frame because
                # the state is no longer "queued" — and the worker
                # skips the job outright if it has not started yet
                if st["job"] is not None:
                    st["job"].abandoned = True
                st["state"] = "done"
                self._release_stage_locked(st)
                self._stage.pop(rid, None)
                self._results.pop(rid, None)
                self._drop_parked_locked(rid)
                self._imports = deque(
                    (m, a, b) for m, a, b in self._imports
                    if int(m.get("rid", -1)) != rid)
                self.recorder.record(rid, "cancelled", stage="prefill")
                return True
            drid = st["drid"]
        cancelled = self.decode.cancel(drid)
        if cancelled:
            with self._lock:
                self._stage.pop(rid, None)
                self._rid_of_drid.pop(drid, None)
        # cancel == False means the decode engine already FINISHED the
        # request (its result is fetchable) — keep the mapping so the
        # client's next poll still collects it, matching the engine's
        # cancel-after-completion contract
        return cancelled

    # -------------------------------------------------------- live weights
    @property
    def params(self):
        """The DECODE engine's live parameter pytree (what a
        :class:`~elephas_tpu.weightsync.WeightSubscriber`'s default
        converter derives its tree structure and dtypes from)."""
        return self.decode.params

    @property
    def weights_version(self) -> int:
        """The DECODE engine's live weight version (what `/stats` and
        the version gate on incoming KV frames read). The prefill
        tier's engines version independently — subscribe each worker's
        engine alongside this one and the KV version gate + retry path
        absorb the rollout window where they briefly differ."""
        return int(self.decode.weights_version)

    def stage_params(self, params, version: int, trace_id=None) -> None:
        """Stage new params for the decode engine (swap applied by the
        engine loop between decode steps, exactly as on a colocated
        engine). NOTE: this updates the decode half only — roll the
        prefill workers' engines through their own subscribers."""
        self.decode.stage_params(params, version, trace_id=trace_id)

    @property
    def draft_config(self):
        """The decode engine's draft config (None on non-speculative
        decode workers) — what a draft-channel
        :class:`~elephas_tpu.weightsync.WeightSubscriber` probes for."""
        return getattr(self.decode, "draft_config", None)

    @property
    def draft_params(self):
        """The decode engine's live DRAFT parameter pytree (speculative
        decode workers; the draft subscriber channel's treedef/dtype
        source)."""
        return getattr(self.decode, "draft_params", None)

    @property
    def draft_weights_version(self) -> int:
        return int(getattr(self.decode, "draft_weights_version", 0))

    def stage_draft_params(self, draft_params, version: int,
                           trace_id=None) -> None:
        """Stage new DRAFT params for a speculative decode engine (the
        draft freshness channel — applied at the same between-steps
        point as target swaps; a stale draft costs acceptance rate,
        never correctness, so no KV gate is needed on this channel)."""
        self.decode.stage_draft_params(draft_params, version,
                                       trace_id=trace_id)

    def apply_staged_params(self):
        """Delegates to the decode engine (the engine loop's step()
        already applies staged swaps; this exists so loop-less drivers
        can force one, mirroring DecodeEngine's surface)."""
        return self.decode.apply_staged_params()

    @property
    def gamma(self) -> Optional[int]:
        """The decode engine's CURRENT speculative depth (the adaptive
        controller's operating point; equal to the ctor gamma on
        fixed-depth engines, None on non-speculative decode workers).
        A fleet prober comparing this against ``gamma_ceiling`` in
        `/stats` sees draft staleness the moment the controller reacts,
        without waiting for the acceptance alert."""
        if getattr(self.decode, "draft_config", None) is None:
            return None
        return int(self.decode._gamma_now)

    @property
    def kernel(self) -> str:
        """The decode engine's RESOLVED attention kernel ("gather", or
        "pallas" when the paged-decode Pallas kernel is actually
        compiled for this backend — a requested-but-fallen-back engine
        reports "gather" here and flags ``kernel_requested`` in
        `/stats`)."""
        return str(getattr(self.decode, "kernel", "gather"))

    # ---------------------------------------------------------------- misc
    def register_prefix(self, tokens) -> None:
        """Register a shared prompt prefix on EVERY prefill worker's
        engine (prefill is where prefix reuse pays). Call before
        traffic — registration does not synchronize with in-flight
        prefills."""
        for worker in self.workers:
            worker.engine.register_prefix(tokens)

    @property
    def stats(self) -> Dict:
        """The decode engine's stats (tier="decode" queue waits and all)
        plus the prefill tier's: per-worker backlog/waits, parked and
        in-transfer counts, retry totals, and KV wire accounting — the
        whole disaggregated story on one ``/stats`` read."""
        out = dict(self.decode.stats)
        out["tier"] = "disagg"
        with self._lock:
            queued = self._prefill_depth_locked()
            parked = len(self._parked)
            imports = len(self._imports)
        waits: List[float] = []
        for w in self.workers:
            sample = getattr(w, "wait_samples", None)
            waits.extend(sample() if sample is not None
                         else list(w.wait_window))
        tier: Dict = {
            "stage_depth": queued,
            "parked": parked,
            "imports_pending": imports,
            "workers_alive": sum(1 for w in self.workers if w.alive),
            "workers": [w.stats() for w in self.workers],
            "prefill_retries": int(self._m_retries.value),
        }
        if waits:
            from ..obs.metrics import percentile

            tier["queue_wait_p50_s"] = round(percentile(waits, 0.5), 6)
            tier["queue_wait_p99_s"] = round(percentile(waits, 0.99), 6)
        out["prefill_tier"] = tier
        out["kv_wire"] = {
            "frames": {c: int(child.value) for c, child in
                       self._frames_by_codec().items()},
            "bytes": {c: int(child.value) for c, child in
                      self._bytes_by_codec().items()},
        }
        return out

    def _frames_by_codec(self):
        return {labels[0]: child
                for labels, child in self._m_frames.series().items()}

    def _bytes_by_codec(self):
        return {labels[0]: child
                for labels, child in self._m_kv_bytes.series().items()}

    # ---------------------------------------------------------- tracing
    def request_trace(self, rid: int) -> Optional[Dict]:
        """The request's merged timeline: this engine's events (queued /
        dispatched / kv_transfer / decode_submitted) interleaved with
        the decode engine's (admitted / kv_install / steps / terminal),
        ordered by wall clock — the KV-transfer stage visible in ONE
        flight-recorder read."""
        own = self.recorder.trace(rid)
        if own is None:
            return None
        with self._lock:
            drid = self._trace_drid.get(rid)
        if drid is not None:
            dec = self.decode.request_trace(drid)
            if dec is not None:
                merged = own["events"] + [
                    dict(e, decode_rid=drid) for e in dec["events"]]
                merged.sort(key=lambda e: e.get("at", 0.0))
                own["events"] = merged
        return own

    def recent_traces(self, limit: int = 32) -> List[Dict]:
        out = []
        for t in self.recorder.recent(limit):
            merged = self.request_trace(t["id"])
            out.append(merged if merged is not None else t)
        return out
