"""Continuous-batching decode engine (slot-based online serving).

``DecodeEngine`` keeps a fixed device batch of ``max_slots`` decode
slots, each at its OWN sequence position — requests join a running
batch the moment a slot frees up (vLLM-style continuous batching,
without paged attention: each slot owns a contiguous cache row). This
rides the vector-position support in
:func:`~elephas_tpu.models.transformer.decode_step`: one jitted step
advances every active slot regardless of where in its sequence each
one is, so short requests never wait for long ones and the chip never
idles between requests.

Per-request output is token-identical to running
:func:`~elephas_tpu.models.transformer.generate` alone on that request
(greedy; the parity oracle in ``tests/test_serving_engine.py``) — slots
are isolated by the batch axis and the per-row causal length mask. One
caveat applies to ALL cross-program comparisons: under bf16 compute the
engine's per-step program and ``generate``'s fused scan round
differently (~5e-4 on logits), so an argmax near-tie can resolve
differently between them; f32 compute is deterministic.

The step loop is host-driven by design: an online server admits and
retires requests between steps, which is exactly the host round trip.
For offline batch generation, :func:`generate`'s single fused scan is
the faster shape.

With a draft model (``draft_params``/``draft_config``), stepping
switches to SPECULATIVE rounds: each ``step()`` runs one
draft-propose / target-verify round per slot, so a slot advances by
``1 + accepted`` tokens per host round trip — continuous batching and
speculative decoding compose because both ride the same per-row cache
positions (rows accept different counts and simply advance
independently). Speculative mode is a first-class SERVING mode: it
composes with the paged pool (the verify pass scatters into the slot's
own blocks — admission budgets ``gamma`` positions of verify slack per
slot, and rejected positions are masked in the slot's own allocation,
never a neighbor's), with the automatic prefix cache (the TARGET
model's KV is the cacheable state — chain keys, admission, parking all
unchanged; draft KV is recomputed at admission and never cached), and
with disaggregated decode (``submit_prefilled`` installs shipped
TARGET KV, then prefills the draft locally before the first round).
Draft params hot-swap through their own channel
(:meth:`stage_draft_params`) so a continuously re-distilled draft
stays fresh: a stale draft costs acceptance rate — the verify pass is
exact with respect to the target — never output correctness.

Automatic prefix caching: with ``prefix_cache`` on (the DEFAULT in
paged mode), the engine content-addresses every FULL ``block_size``
block of every admitted prompt by the hash chain of its token contents
and the live ``weights_version``
(:mod:`~elephas_tpu.models.block_cache`). Admission walks the longest
chain of cached blocks first and prefills only the remainder — no
registration, no operator curation: any two requests sharing a prompt
head share its KV. In paged mode the cached blocks live IN the pool
and a hit installs table POINTERS (zero copy, zero recompute; entries
are refcounted while any slot's table points at them and parked on an
LRU free list when unreferenced, so pool pressure reclaims cold
prefixes instead of failing admission — correctness needs no
copy-on-write because decode only ever writes the private blocks past
the prompt's full-block head). On a contiguous engine (or a
disaggregated prefill worker) the cache stores host block arrays: a
hit pays one host-to-device copy instead of the prefix's prefill
FLOPs. Keying on ``weights_version`` means a live hot-swap (PR 8)
invalidates the whole cache BY CONSTRUCTION — post-swap chains hash
differently, no flush pause, and old-version blocks age out of the
LRU rather than ever being served.

``register_prefix`` survives as the explicit PINNING layer on top of
the automatic cache: it precomputes a shared prompt head (a system
prompt) ahead of traffic and pins its full blocks with a refcount
floor of one — never parked, never evicted — while sub-block tails
keep riding the registered row (longest registered match wins when it
covers more than the block chain).

Multi-tenant QoS (``qos=``, :mod:`~elephas_tpu.serving_qos`): requests
carry a ``tenant`` + priority class; admission replaces the FIFO pop
with token-budget weighted fair queueing across tenants
(deficit-round-robin over queued tokens), per-tenant quotas shed with
429 + a quota-aware ``retry_after_ms`` while under-quota tenants keep
admitting, and — in paged mode with the prefix cache — a
strictly-higher-priority request under pool pressure PREEMPTS a
low-priority in-flight decode: the victim's full KV blocks park in the
block cache (release → LRU), the request re-queues at the front of its
tenant lane, and on re-admission the chain walk reclaims the parked
blocks, so resume ≈ a prefix-cache hit plus a short remainder prefill
— greedy output token-identical to the never-preempted run.

The reference has no serving path at all (inference is Spark
``mapPartitions`` batch prediction, ``elephas/spark_model.py:235-272``);
continuous batching is a beyond-parity serving feature.
"""
import contextlib
import threading
import time
from collections import deque
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .models.transformer import (NEG_INF, TransformerConfig, chunked_blocks,
                                 decode_block, decode_step, init_kv_cache,
                                 prefill_cache)
from .obs.context import current_context, use_context
from .obs.events import FlightRecorder
from .obs.events import emit as emit_event
from .obs.metrics import (MetricsRegistry, counter_baseline,
                          since_baseline)
from .obs.profiler import LoopProfiler
from .obs.spans import add_span, default_span_store, start_span
from .obs.trace import span_if_counted
from .serving_qos import (DEFAULT_TENANT, FairQueue, QueuedRequest,
                          TenantQoS)
from .utils.faults import InjectedFault, fault_site


class QueueFullError(RuntimeError):
    """Admission rejected: accepting the request would exceed the
    engine's queue-depth or queued-token bound (or a ``serving.submit``
    fault-plan ``drop`` simulated the same). Carries ``retry_after_ms``,
    a backoff hint derived from recent request latency and the current
    backlog — the HTTP layer forwards it with its 429."""

    def __init__(self, message: str, retry_after_ms: int = 100):
        super().__init__(message)
        self.retry_after_ms = int(retry_after_ms)


class DeadlineExceededError(RuntimeError):
    """A request's deadline passed before any work was dispatched for
    it (the blocking :class:`~elephas_tpu.serving.TextGenerator` path;
    the engine itself never raises this — it sheds expired requests and
    marks their results instead)."""


def _filter_logits_rows(logits: jnp.ndarray, top_k: jnp.ndarray,
                        top_p: jnp.ndarray) -> jnp.ndarray:
    """Per-ROW top-k / nucleus filters over ``(B, V)`` logits — the
    vectorized form of the scalar
    :func:`~elephas_tpu.models.transformer._filter_logits` (same
    keep-until-mass-passes semantics, always keeping the top token).
    ``top_k[b] <= 0`` and ``top_p[b] >= 1`` disable the respective
    filter for that row, so one batched program serves every mix of
    per-request settings."""
    v = logits.shape[-1]
    # top-k first, then the nucleus over the top-k SURVIVORS — the same
    # sequential composition as the scalar filter (the nucleus mass is
    # renormalized within the top-k set, so the two are not independent)
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    kidx = jnp.clip(top_k - 1, 0, v - 1)
    kth = jnp.take_along_axis(sorted_desc, kidx[:, None], axis=-1)
    k_thr = jnp.where(((top_k > 0) & (top_k < v))[:, None], kth, -jnp.inf)
    logits = jnp.where(logits >= k_thr, logits, NEG_INF)
    # top-k masking cannot reorder survivors, so masking the FIRST sort
    # gives the sorted view of the masked logits — no second sort
    sorted_desc = jnp.where(sorted_desc >= k_thr, sorted_desc, NEG_INF)
    probs = jax.nn.softmax(sorted_desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_sorted = jnp.concatenate(
        [jnp.ones_like(cum[:, :1], bool), cum[:, :-1] < top_p[:, None]],
        axis=-1)
    p_kth = jnp.min(jnp.where(keep_sorted, sorted_desc, jnp.inf),
                    axis=-1, keepdims=True)
    p_thr = jnp.where(top_p[:, None] < 1.0, p_kth, -jnp.inf)
    return jnp.where(logits >= p_thr, logits, NEG_INF)

__all__ = ["DecodeEngine", "QueueFullError", "DeadlineExceededError",
           "validate_sampling_overrides", "INTER_TOKEN_BUCKETS"]

#: bucket bounds for ``serving_inter_token_seconds`` — finer at the
#: bottom than the latency defaults (a healthy decode step is
#: sub-millisecond on-chip; chunked emission's intra-chunk gaps are ~0)
INTER_TOKEN_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                       0.1, 0.25, 0.5, 1.0, 2.5)

#: reusable no-op context for profiler-less engines (nullcontext is
#: stateless, so one instance serves every section site)
_NULL_SECTION = contextlib.nullcontext()

#: adaptive-gamma controller: EWMA smoothing of per-round pooled
#: acceptance. 0.4 weights the last ~4 rounds — fast enough to catch a
#: draft going stale mid-request, smooth enough that one unlucky round
#: doesn't move the depth
GAMMA_EWMA_ALPHA = 0.4

#: adaptive-gamma controller: rounds between depth adjustments (and
#: each adjustment moves ONE step). Hysteresis against chattering —
#: recompiles are cached per depth, but verify-cost thrash is not free
GAMMA_ADJUST_EVERY = 4

#: interleaved prefill: iterations between prefill-budget recomputes.
#: The budget reads the profiler's utilization(), which walks the
#: ring-buffer under a lock — cheap, but not every-iteration cheap
#: against a sub-millisecond decode step (<2% overhead budget)
PREFILL_BUDGET_EVERY = 16

#: interleaved prefill: most chunks one iteration may feed. The budget
#: scales from 1 (decode-saturated loop — in-flight requests first) up
#: to this (decode mostly idle — drain the pending prompt fast)
MAX_INTERLEAVE_CHUNKS = 4


def validate_sampling_overrides(temperature, top_k, top_p) -> None:
    """THE per-request sampling validation — shared by every submit
    surface (engine submit, prefill export, the disaggregated front
    end), so an admission-rule change cannot silently diverge their
    400-at-submit behavior. ``None`` always means "engine default"."""
    if temperature is not None:
        if not (temperature >= 0 and np.isfinite(temperature)):
            raise ValueError("temperature must be >= 0 and finite, "
                             f"got {temperature}")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")


class DecodeEngine:
    """Slot-based continuous batching over one parameter pytree.

    :param params: transformer parameters (replicated or GSPMD-sharded)
    :param config: the model's :class:`TransformerConfig`
    :param max_slots: device batch width (concurrent requests)
    :param max_len: cache length per slot (default
        ``config.max_seq_len``); each request needs
        ``len(prompt) + max_new_tokens <= max_len``
    :param temperature: 0 = greedy (parity with ``generate``),
        otherwise categorical sampling
    :param eos_id: optional stop token — a request finishes early when
        it emits this id (the id itself is not part of the output)
    :param draft_params: optional draft-model parameters switching every
        slot to SPECULATIVE stepping: each ``step()`` runs one
        draft-propose / target-verify round
        (:func:`~elephas_tpu.models.speculative.speculative_round`), so
        a slot advances by ``1 + accepted`` tokens per step instead of
        one — continuous batching composed with speculative decoding.
        Per-request greedy output is unchanged (still ≡ solo
        ``generate``); only the number of host steps shrinks.
    :param draft_config: the draft model's config (same vocabulary)
    :param gamma: draft tokens proposed per round (speculative mode)
    :param steps_per_sync: decode steps fused into each :meth:`step`
        dispatch (plain mode): one jitted ``lax.scan`` advances every
        slot by this many tokens per host round trip. Where dispatch
        latency dominates (remote/tunneled chips), throughput scales
        almost linearly with it; the cost is scheduling granularity —
        admission/retirement happen every ``steps_per_sync`` tokens, and
        a slot that hits eos/budget mid-chunk wastes the remainder.
        Per-slot output is still exactly its solo greedy decode.
    :param prefill_chunk: when set, admission prefills prompts in
        fixed ``prefill_chunk``-token blocks (plus one natural-size
        tail), so jit compilation stops scaling with distinct prompt
        lengths: an online server sees at most ``prefill_chunk`` block
        shapes ever, instead of one compile per new length. Numerically
        identical to whole-prompt prefill; composes with prefix caching
        (the suffix is what gets chunked).
    :param paged: ``(num_blocks, block_size)`` switches the KV cache to
        a shared block pool with per-slot block tables (vLLM's paged
        memory model): cache memory scales with tokens in flight
        instead of ``max_slots × max_len``, requests queue while the
        pool is momentarily empty, and blocks return on retirement —
        a CAPACITY lever for oversubscribed serving (each step pays one
        extra gather pass over the cache; see
        :mod:`~elephas_tpu.models.paged_decode`). Composes with prefix
        caching, chunked prefill, multi-step, and speculative mode
        (each slot's allocation budgets ``gamma`` extra positions of
        verify slack); not with ``kv_cache_quant`` or MoE.
    :param max_queue: admission bound on the backlog of queued
        (not-yet-admitted) requests; a :meth:`submit` that would push the
        backlog past it raises :class:`QueueFullError` instead of
        queueing forever (``None`` = unbounded, the pre-overload-safety
        behavior). Must be >= 1: the HTTP server submits with
        ``admit=False``, so every request passes through the queue even
        when a slot is free.
    :param max_queued_tokens: companion bound on the TOTAL prompt tokens
        waiting in the queue — a few enormous prompts can exhaust
        prefill capacity long before ``max_queue`` counts them.
    :param clock: monotonic time source for deadline bookkeeping
        (``time.monotonic``); injectable so chaos tests drive expiry
        deterministically without sleeping.
    :param tier: the serving tier this engine plays in a disaggregated
        topology — the ``tier`` label on its
        ``serving_queue_wait_seconds`` series. ``"colocated"`` (the
        default) is the classic one-engine-does-both deployment, whose
        queue wait INCLUDES head-of-line prefill blocking;
        ``"decode"`` marks a decode worker fed precomputed KV
        (:meth:`submit_prefilled`), whose queue wait is pure
        decode-stage backlog. The prefill tier's companion series is
        observed by :class:`~elephas_tpu.disagg.PrefillWorker` under
        ``tier="prefill"``.
    :param prefix_cache: the AUTOMATIC content-addressed KV block cache
        (see the module docstring). ``None`` means "on in paged mode,
        off otherwise"; pass ``False`` to disable (the bench A/B
        baseline) or ``True`` to enable the host-array-backed cache on
        a contiguous engine. Composes with speculative mode: the
        TARGET model's KV is what gets cached (draft KV is recomputed
        at admission, never cached), so chain keys stay seeded by the
        target's ``weights_version`` and a draft swap invalidates
        nothing.
    :param prefix_cache_block_size: cache granularity in tokens for the
        HOST-mode cache (contiguous engines; default 64). Paged engines
        always cache at the pool's ``block_size`` — passing a different
        value raises.
    :param prefix_cache_capacity: host-mode bound on cached blocks
        (LRU-evicted past it; default 1024; pinned registered-prefix
        blocks are exempt). Ignored in paged mode, where the pool
        itself is the capacity and reclaim happens under admission
        pressure.
    :param qos: a :class:`~elephas_tpu.serving_qos.TenantQoS` (or its
        ctor-kwargs dict) switching admission to per-tenant weighted
        fair queueing with quotas and priority preemption (see the
        module docstring). ``None`` (the default) keeps the exact
        FIFO semantics tenants or not — requests still carry a
        ``tenant`` for attribution, but no policy acts on it.
    :param registry: the :class:`~elephas_tpu.obs.MetricsRegistry` this
        engine's series land in. Defaults to a FRESH per-engine registry
        (not the process default): the registry counters are the single
        source of truth behind :attr:`stats`, which is a per-engine
        surface. Injecting a shared registry supports the sequential
        weight-reload flow — the replacement engine snapshots the
        counters at construction, so its stats start at zero while the
        scraped series keep pooled totals — but two CONCURRENTLY-live
        engines on one registry do pool counts (and the newest engine's
        queue gauges win); keep simultaneous engines on their default
        fresh registries. The HTTP server merges this registry with the
        process default registry on its ``GET /metrics`` route.
    :param profiler: the engine-loop continuous profiler
        (:class:`~elephas_tpu.obs.LoopProfiler`): per-iteration phase
        accounting (swap/admit/prefill/decode/emit + idle) published
        as ``serving_loop_utilization{phase}`` gauges, with jit
        compiles tracked separately. ``None`` (the default) creates
        one on this engine's registry — measured overhead is <2%
        tokens/s (the ``slo_plane`` bench row), cheap enough to be
        always-on. Pass ``False`` to disable (the bench A/B baseline)
        or an instance to share one across wrappers.
    :param kernel: paged decode-attention inner loop: ``"gather"``
        (default — materialize each row's blocks, full-row softmax) or
        ``"pallas"`` (fused block-gather flash kernel,
        :mod:`~elephas_tpu.ops.paged_attention`; TPU only — off-TPU the
        engine falls back to gather with a ``serving.kernel_fallback``
        event, and ``stats["kernel"]`` reports what actually runs).
    :param kernel_interpret: force (``True``) the Pallas interpreter
        for the ``"pallas"`` kernel, disabling the off-TPU fallback —
        a test/debug path, orders of magnitude slower than either
        production path.
    :param adaptive_gamma: steer the speculation depth per engine from
        measured draft acceptance: ``gamma`` becomes the CEILING (all
        capacity/slack accounting stays sized to it, so shrinking is
        always safe) and the operating depth walks between
        ``gamma_min`` and the ceiling as the acceptance EWMA moves — a
        stale draft shrinks gamma within a few rounds (recovering the
        wasted draft steps long before fleet-level acceptance alerts),
        and a draft re-stage resets it to the ceiling. Greedy engines
        stay token-identical under ANY gamma schedule (the verify emit
        is an exact argmax-prefix match).
    :param gamma_min: adaptive gamma's floor (default 1 = one draft
        token per round at zero acceptance).
    :param interleave_prefill: schedule chunked admission prefills
        BETWEEN decode steps instead of running each to completion at
        admission: every engine iteration feeds at most a budgeted
        number of ``prefill_chunk``-token chunks (budget derived from
        the profiler's decode-phase utilization), so a long prompt's
        admission no longer stalls in-flight decodes — their
        inter-token latency stays flat while the long request's TTFT
        degrades gracefully. Requires ``prefill_chunk``. Outputs are
        token-identical to run-to-completion admission (same chunk
        shapes, same math; slots are isolated).
    """

    #: flight-recorder decode sampling: one ``step`` timeline event per
    #: this many emitted tokens per request (every token would blow the
    #: per-request event cap on long generations for no diagnostic gain)
    TRACE_STEP_EVERY = 8

    def __init__(self, params: Dict, config: TransformerConfig,
                 max_slots: int = 8, max_len: Optional[int] = None,
                 temperature: float = 0.0, eos_id: Optional[int] = None,
                 seed: int = 0, draft_params: Optional[Dict] = None,
                 draft_config: Optional[TransformerConfig] = None,
                 gamma: int = 4, steps_per_sync: int = 1,
                 prefill_chunk: Optional[int] = None,
                 paged: Optional[Tuple[int, int]] = None,
                 max_queue: Optional[int] = None,
                 max_queued_tokens: Optional[int] = None,
                 clock=time.monotonic, tier: str = "colocated",
                 registry: Optional[MetricsRegistry] = None,
                 prefix_cache: Optional[bool] = None,
                 prefix_cache_block_size: Optional[int] = None,
                 prefix_cache_capacity: Optional[int] = None,
                 qos: Optional[TenantQoS] = None,
                 profiler: Union[None, bool, LoopProfiler] = None,
                 kv_spill=None, session_store=None,
                 kernel: str = "gather",
                 kernel_interpret: Optional[bool] = None,
                 adaptive_gamma: bool = False, gamma_min: int = 1,
                 interleave_prefill: bool = False):
        self.params = params
        self.config = config
        self.max_slots = int(max_slots)
        self.max_len = int(max_len or config.max_seq_len)
        if self.max_len > config.max_seq_len:
            raise ValueError(f"max_len {self.max_len} exceeds "
                             f"config.max_seq_len {config.max_seq_len}")
        self.temperature = float(temperature)
        self.eos_id = eos_id
        if (draft_params is None) != (draft_config is None):
            raise ValueError("draft_params and draft_config go together")
        if draft_config is not None:
            if draft_config.vocab_size != config.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_config.vocab_size} != target "
                    f"vocab {config.vocab_size}")
            if gamma < 1:
                raise ValueError("gamma must be >= 1")
            if self.max_len > draft_config.max_seq_len:
                raise ValueError(
                    f"max_len {self.max_len} exceeds draft max_seq_len "
                    f"{draft_config.max_seq_len}")
        self.draft_params = draft_params
        self.draft_config = draft_config
        self.gamma = int(gamma)
        # adaptive speculative gamma: ``self.gamma`` is the CEILING —
        # every capacity rule (verify slack, the paged per-slot block
        # budget) stays sized to it, so the acceptance controller can
        # only ever SHRINK the speculation depth below what admission
        # reserved, never outgrow it. ``_gamma_now`` is the operating
        # depth, steered per engine from measured acceptance (see
        # ``_steer_gamma``); fixed-gamma engines keep it pinned.
        self.adaptive_gamma = bool(adaptive_gamma)
        self.gamma_min = int(gamma_min)
        if self.adaptive_gamma and draft_config is None:
            raise ValueError("adaptive_gamma requires a draft model "
                             "(draft_params/draft_config)")
        if draft_config is not None and not (
                1 <= self.gamma_min <= self.gamma):
            raise ValueError(f"gamma_min {self.gamma_min} must satisfy "
                             f"1 <= gamma_min <= gamma ({self.gamma})")
        self._gamma_now = self.gamma
        # EWMA of per-round batch acceptance fraction (None until the
        # first speculative round samples it) + rounds since the last
        # gamma adjustment (hysteresis: move at most one step every
        # GAMMA_ADJUST_EVERY rounds)
        self._accept_ewma: Optional[float] = None
        self._rounds_since_adjust = 0
        # verify slack: a speculative round writes up to gamma positions
        # past the last emitted token, so every capacity rule (the
        # max_len bound AND the paged per-slot block budget) reserves
        # gamma extra positions per slot — the CEILING, under adaptive
        # gamma, so shrinking mid-flight is always safe
        self._slack = self.gamma if draft_config is not None else 0
        self.steps_per_sync = int(steps_per_sync)
        if self.steps_per_sync < 1:
            raise ValueError("steps_per_sync must be >= 1")
        self.prefill_chunk = (None if prefill_chunk is None
                              else int(prefill_chunk))
        if self.prefill_chunk is not None and self.prefill_chunk < 1:
            raise ValueError("prefill_chunk must be >= 1")
        self.paged = None
        if paged is not None:
            from .models.paged_decode import validate_paged_config

            num_blocks, block_size = int(paged[0]), int(paged[1])
            validate_paged_config(config)
            if block_size < 1 or num_blocks < 2:
                raise ValueError("paged needs block_size >= 1 and "
                                 "num_blocks >= 2 (block 0 is the "
                                 "reserved scratch sink)")
            self.paged = (num_blocks, block_size)
            # per-slot table width: enough blocks to cover max_len
            self._mb = -(-self.max_len // block_size)
        # paged decode-attention kernel selection: "gather" (default)
        # materializes each row's blocks; "pallas" fuses the gather into
        # a flash-style online-softmax kernel
        # (:mod:`~elephas_tpu.ops.paged_attention`). The compiled kernel
        # needs a TPU: elsewhere the engine FALLS BACK to gather (a
        # ``serving.kernel_fallback`` event; ``stats["kernel"]`` reports
        # what actually runs) unless ``kernel_interpret=True`` forces
        # the Pallas interpreter — a test/debug path, orders of
        # magnitude slower than either production path.
        self.kernel_requested = str(kernel)
        if self.kernel_requested not in ("gather", "pallas"):
            raise ValueError(f"unknown kernel {kernel!r}; expected "
                             "'gather' or 'pallas'")
        if self.kernel_requested == "pallas" and self.paged is None:
            raise ValueError("kernel='pallas' is the paged decode-"
                             "attention kernel; it requires "
                             "paged=(num_blocks, block_size)")
        self._kernel_interpret = kernel_interpret
        self.kernel = self.kernel_requested
        if self.kernel == "pallas" and not kernel_interpret:
            from .ops.paged_attention import pallas_supported

            if not pallas_supported():
                self.kernel = "gather"
                emit_event("serving.kernel_fallback",
                           requested="pallas",
                           backend=jax.default_backend())
        # chunked-prefill interleaving (ctor docstring): pending
        # admissions whose prompt is still being fed chunk-by-chunk
        # between decode steps. slot -> state dict (see
        # _begin_interleaved_prefill for the fields); the slot is
        # RESERVED (excluded from _free_slots) but not yet decoding.
        self.interleave_prefill = bool(interleave_prefill)
        if self.interleave_prefill and self.prefill_chunk is None:
            raise ValueError("interleave_prefill requires prefill_chunk")
        self._pending_prefill: Dict[int, Dict] = {}
        # chunks-per-iteration budget, recomputed from the profiler's
        # decode-phase utilization every PREFILL_BUDGET_EVERY iterations
        # (one utilization() ring walk costs ~the profiler's whole
        # per-step budget, so it is cached, not read per step)
        self._prefill_budget = 1
        self._budget_age = 0
        if self.steps_per_sync > 1 and draft_config is not None:
            raise ValueError("steps_per_sync > 1 applies to plain "
                             "stepping; speculative mode already "
                             "amortizes dispatches via draft rounds")
        self._key = jax.random.PRNGKey(seed)
        if self.paged is not None:
            from .models.paged_decode import init_paged_pool

            nb, bsz = self.paged
            self.cache = None        # the pool replaces the contiguous cache
            self.pool = init_paged_pool(config, nb, bsz)
            self._tables = np.zeros((self.max_slots, self._mb), np.int32)
            self._free_block_ids = deque(range(1, nb))  # 0 = scratch
            self._slot_blocks: List[List[int]] = [
                [] for _ in range(self.max_slots)]
        else:
            self.cache = init_kv_cache(config, self.max_slots,
                                       self.max_len)
        # per-slot SHARED prefix-cache entries the slot's table points
        # at (refcounted; released on retirement) — disjoint from
        # _slot_blocks, which holds the slot's PRIVATE block ids
        self._slot_cached: List[List] = [[] for _ in range(self.max_slots)]
        self.draft_cache = (init_kv_cache(draft_config, self.max_slots,
                                          self.max_len)
                            if draft_config is not None else None)
        # host-side slot state: position of the last PROCESSED token,
        # the pending (emitted, not yet processed) token, budgets
        self._pos = np.zeros(self.max_slots, np.int32)
        self._last = np.zeros(self.max_slots, np.int32)
        self._budget = np.zeros(self.max_slots, np.int32)
        self._temp = np.full(self.max_slots, self.temperature, np.float32)
        self._topk = np.zeros(self.max_slots, np.int32)    # 0 = off
        self._topp = np.ones(self.max_slots, np.float32)   # 1 = off
        # per-slot request seed (-1 = unseeded: the engine's shared
        # key samples, exactly as before per-request seeds existed)
        self._slot_seed = np.full(self.max_slots, -1, np.int32)
        self._rid = [None] * self.max_slots
        # multi-tenant QoS: the policy object (None = plain FIFO) and
        # the admission queue enforcing it; per-slot tenant/priority/
        # prompt metadata backs preemption and per-tenant accounting
        self.qos = TenantQoS.coerce(qos)
        self._queue: FairQueue = FairQueue(self.qos)
        self._slot_prompt: List[Optional[np.ndarray]] = (
            [None] * self.max_slots)
        # output tokens already FOLDED INTO _slot_prompt: a resumed
        # request's admission prompt is original-prompt + everything
        # emitted before its preemption, so a SECOND preemption must
        # only append the tokens emitted since (else they duplicate)
        self._slot_prior = np.zeros(self.max_slots, np.int64)
        self._slot_tenant: List[Optional[str]] = [None] * self.max_slots
        self._slot_priority = np.zeros(self.max_slots, np.int32)
        # weights_version each slot was ADMITTED under: a preempted
        # slot's KV only parks when the engine still serves that
        # version (post-swap chain keys would address old-weight KV)
        self._slot_wv = np.zeros(self.max_slots, np.int64)
        # tiered KV spill + resumable sessions (:mod:`~elephas_tpu.
        # kvtier`) — wired up after the prefix-cache block below;
        # the slot state lives here with its siblings. _slot_lossy
        # taints a slot that admitted over a LOSSY (Q8-round-tripped)
        # promoted block: nothing it computes may register, park, or
        # persist under chain keys (the lossy-parity rule).
        self._kv_spill = None
        self._session_store = None
        self._lossy_promote = False
        # (rid, version, start_block, promos) — the tier walk's memo,
        # invalidated whenever the DEVICE hit count at the same rid
        # changes (another admission may have registered more of the
        # chain while this candidate waited, shifting the walk start)
        self._promo_memo: Optional[Tuple] = None
        # per-admission demotion tally: set around the allocation loop
        # so a large allocation's evictions flush as ONE kv_demote
        # event instead of flooding the per-rid recorder cap
        self._demote_accum: Optional[Dict[str, int]] = None
        self._m_spill_demote = None
        self._m_spill_promote = None
        self._m_spill_bytes = None
        self._m_session_hits = None
        self._m_session_misses = None
        self._slot_lossy = [False] * self.max_slots
        # slot -> [(SpilledBlock, source_tier)] claimed by _admit's
        # tier walk, consumed by the admission prefill's install
        self._slot_promos: Dict[int, List] = {}
        # rid -> session id (rid-keyed so it survives preemption
        # re-queues, like _seed); dropped at retirement/cancel
        self._session: Dict[int, str] = {}
        # rid -> {"outputs": [...], "preempts": n} for requests
        # preempted mid-decode and re-queued for resume
        self._resume: Dict[int, Dict] = {}
        # rid -> per-request RNG seed: rid-keyed (not queue-item state)
        # so it survives preemption re-queues; dropped at retirement
        self._seed: Dict[int, int] = {}
        self._outputs: Dict = {}
        self._done: Dict = {}
        # rid -> [tokens]: admission-time tokens awaiting step() — a
        # list, because a request preempted before its first step and
        # resumed owes the stream BOTH admissions' first tokens
        self._fresh: Dict = {}
        # rid -> (kv_blocks, first_token) for requests whose prefill
        # happened off-engine (submit_prefilled); consumed at admission
        self._prefilled_kv: Dict[int, Tuple] = {}
        self._next_rid = 0
        # overload safety: admission bounds + per-request deadlines
        self.max_queue = None if max_queue is None else int(max_queue)
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError("max_queue must be None or >= 1 (the HTTP "
                             "server's admit=False submits always pass "
                             "through the queue)")
        self.max_queued_tokens = (None if max_queued_tokens is None
                                  else int(max_queued_tokens))
        if (self.max_queued_tokens is not None
                and self.max_queued_tokens < 1):
            raise ValueError("max_queued_tokens must be None or >= 1")
        self._clock = clock
        self._queued_tokens = 0              # prompt tokens in the queue
        self._deadline: Dict[int, float] = {}  # rid -> absolute deadline
        # distributed tracing: the context captured at submit (the HTTP
        # handler thread's), restored around THIS request's share of the
        # engine loop's work, plus the per-request flight recorder the
        # trace endpoints read (every event stamped with the trace id)
        self._trace_ctx: Dict[int, object] = {}
        self.recorder = FlightRecorder()
        self._expired: set = set()   # shed while queued (never prefilled)
        self._timed_out: set = set()  # deadline hit mid-decode (partial)
        # observability: the registry is the single store behind .stats
        # (per-engine by default — see the registry param docstring)
        self.registry = reg = (registry if registry is not None
                               else MetricsRegistry())
        # label-less children are resolved ONCE (.labels() with no
        # labels): per-token hot paths pay one child-lock inc, never a
        # family lock + dict lookup per token
        self._m_steps = reg.counter(
            "serving_steps_total",
            "device round trips (engine steps)").labels()
        self._m_emitted = reg.counter(
            "serving_tokens_emitted_total", "output tokens emitted"
            ).labels()
        self._m_finished = reg.counter(
            "serving_requests_finished_total",
            "requests retired at eos or budget").labels()
        self._m_shed = reg.counter(
            "serving_requests_shed_total",
            "admission rejections (queue full / injected shed; HTTP 429)"
            ).labels()
        self._m_expired = reg.counter(
            "serving_requests_expired_total",
            "deadline passed while queued — shed before prefill (504)"
            ).labels()
        self._m_timed_out = reg.counter(
            "serving_requests_timed_out_total",
            "deadline passed mid-decode — partial output returned"
            ).labels()
        # flight-recorder ring evictions, split by whether the evicted
        # request was still in flight: a truncated ACTIVE timeline is
        # the one that reads as "request never existed"
        self.recorder.bind_eviction_counter(reg.counter(
            "flight_recorder_evictions_total",
            "flight-recorder timelines evicted by the ring bound, "
            "by request state at eviction", labels=("state",)))
        # gauge callbacks hold a WEAK reference: with an injected
        # long-lived registry, a discarded engine (weight reload) must
        # not be pinned — with its params — by its own scrape callbacks
        import weakref

        ref = weakref.ref(self)
        self._m_queue_depth = reg.gauge(
            "serving_queue_depth", "requests backlogged, not yet admitted")
        self._m_queue_depth.set_function(
            lambda: float(len(e._queue))
            if (e := ref()) is not None else 0.0)
        self._m_queued_tokens = reg.gauge(
            "serving_queued_tokens", "prompt tokens waiting in the queue")
        self._m_queued_tokens.set_function(
            lambda: float(e._queued_tokens)
            if (e := ref()) is not None else 0.0)
        self._m_step_latency = reg.histogram(
            "serving_step_latency_seconds",
            "wall time of one engine step (admission + device dispatch)"
            ).labels()
        self._m_request_latency = reg.histogram(
            "serving_request_latency_seconds",
            "submit-to-retirement wall time per finished request",
            exemplars=True).labels()
        # labeled by serving tier: a disaggregated deployment's headline
        # claim — decode-tier queue wait free of prefill head-of-line
        # blocking — must be readable straight off /metrics, next to the
        # prefill tier's series (PrefillWorker observes tier="prefill"
        # into the same family)
        self.tier = str(tier)
        self._m_queue_wait = reg.histogram(
            "serving_queue_wait_seconds",
            "submit-to-admission wall time per admitted request, by "
            "serving tier", labels=("tier",)).labels(tier=self.tier)
        # per-request wall-clock: submit time per rid + a bounded window
        # of completed (queue_wait_s, total_s) samples for percentiles
        # (kept alongside the histograms: _retry_after_ms needs raw
        # medians over exactly this window)
        self._submit_t: Dict[int, float] = {}
        self._admit_t: Dict[int, float] = {}
        self._latency_window: deque = deque(maxlen=1024)
        # user-experienced latency decomposition: time-to-first-token
        # (submit -> first output token; exemplar-enabled so a p99
        # outlier links to its flight-recorder timeline) and the gap
        # between consecutive tokens of one request. These observe off
        # HOST dicts keyed by rid — never the bounded flight-recorder
        # ring, whose eviction must not cost a histogram sample.
        self._m_ttft = reg.histogram(
            "serving_ttft_seconds",
            "submit-to-first-token wall time per request (disagg "
            "front ends pass their submit stamp through, so the "
            "prefill tier's queue+ship time lands inside)",
            exemplars=True).labels()
        self._m_inter_token = reg.histogram(
            "serving_inter_token_seconds",
            "wall time between consecutive output tokens of one "
            "request (chunked/speculative emission: intra-chunk gaps "
            "are ~0 with one chunk-interval sample — exactly what a "
            "non-streaming client experiences)",
            buckets=INTER_TOKEN_BUCKETS).labels()
        # rid -> monotonic stamp of the FRONT-END submit, when it
        # precedes this engine's own (submit_prefilled's submitted_at);
        # rid -> last token emission stamp; rid -> observed ttft for
        # the terminal flight-recorder event
        self._ttft_origin: Dict[int, float] = {}
        self._last_tok_t: Dict[int, float] = {}
        self._ttft_val: Dict[int, float] = {}
        # engine-loop continuous profiler (see the ctor docstring):
        # False disables, None builds one on this registry
        if profiler is False:
            self.profiler: Optional[LoopProfiler] = None
        elif profiler is None or profiler is True:
            self.profiler = LoopProfiler(reg)
        else:
            self.profiler = profiler
        self._m_accepted = reg.counter(
            "serving_draft_tokens_accepted_total",
            "speculative draft tokens accepted by the target model"
            ).labels()
        self._m_proposed = reg.counter(
            "serving_draft_tokens_proposed_total",
            "speculative draft tokens proposed").labels()
        if draft_config is not None:
            self._m_spec_rounds = reg.counter(
                "serving_speculative_rounds_total",
                "draft-propose/target-verify rounds run (one per "
                "active slot per step)").labels()
            # the registry half of per-engine acceptance: the live
            # accepted/proposed ratio as a scrapeable gauge (the same
            # number stats/the fleet prober read — baselined like
            # stats, so an injected shared registry's predecessor
            # counts never pool in). NaN (not 0.0) before any
            # proposal, mirroring stats' None: an idle replica must
            # not trip a stale-draft (low-acceptance) alert
            reg.gauge(
                "serving_speculative_acceptance",
                "draft acceptance rate (accepted / proposed draft "
                "tokens, engine lifetime; NaN before any proposal)"
                ).set_function(
                lambda: (e._since_init(e._m_accepted) / p
                         if (e := ref()) is not None
                         and (p := e._since_init(e._m_proposed))
                         else float("nan")))
            # the adaptive controller's operating depth (== the ctor
            # gamma, constantly, when adaptive_gamma is off). Watching
            # this gauge against serving_speculative_acceptance shows
            # the control loop working: an acceptance dip drags gamma
            # down within a few rounds, a draft re-stage snaps it back
            # to the ceiling
            reg.gauge(
                "serving_gamma",
                "speculative depth currently proposed per round "
                "(adaptive engines steer this between gamma_min and "
                "the ctor gamma ceiling)").set_function(
                lambda: (float(e._gamma_now) if (e := ref()) is not None
                         else 0.0))
        # rid -> [accepted, proposed] draft-token counts for the
        # request's flight-recorder terminal event (per-request
        # acceptance observability; survives preemption — keyed by rid)
        self._accept: Dict[int, List[int]] = {}
        if self.paged is not None:
            reg.gauge("serving_paged_blocks_free",
                      "allocatable KV blocks currently free"
                      ).set_function(
                lambda: float(len(e._free_block_ids))
                if (e := ref()) is not None else 0.0)
        self._m_interleaved = reg.counter(
            "serving_prefill_chunks_interleaved_total",
            "prompt-prefill chunks fed between decode steps by the "
            "interleaving scheduler (0 on run-to-completion engines)"
            ).labels()
        # live weight plane: params staged by a WeightSubscriber (any
        # thread) swap in atomically between decode steps — the same
        # point KV installs use. weights_version names what the engine
        # is CURRENTLY serving (0 = construction-time params; a
        # subscriber stamps it with the parameter plane's version).
        self.weights_version = 0
        self._staged_lock = threading.Lock()
        self._staged_params: Optional[Tuple] = None
        # the DRAFT's own staging channel (speculative mode): a second
        # WeightSubscriber keeps a continuously re-distilled draft
        # fresh. Versioned independently of the target — draft chain
        # keys never exist (draft KV is not cached), so a draft swap
        # invalidates nothing and costs only the registered prefixes'
        # draft-row recompute.
        self.draft_weights_version = 0
        self._staged_draft: Optional[Tuple] = None
        if draft_config is not None:
            reg.gauge("serving_draft_weights_version",
                      "draft-model weight version currently proposing "
                      "(0 = construction-time draft params)"
                      ).set_function(
                lambda: float(e.draft_weights_version)
                if (e := ref()) is not None else 0.0)
        reg.gauge("serving_weights_version",
                  "weight version the engine is currently serving "
                  "(0 = construction-time params)").set_function(
            lambda: float(e.weights_version)
            if (e := ref()) is not None else 0.0)
        self._m_weight_swaps = reg.counter(
            "serving_weight_swaps_total",
            "live weight hot-swaps applied between decode steps"
            ).labels()
        self._m_swap_pause = reg.histogram(
            "serving_weight_swap_seconds",
            "engine-loop blockage per weight swap (param pointer swap "
            "+ registered-prefix recompute)").labels()
        self._m_preemptions = reg.counter(
            "serving_preemptions_total",
            "in-flight decodes preempted by a higher-priority "
            "admission (KV parked, request re-queued)").labels()
        if self.qos is not None:
            # per-tenant series: configured tenants get their own label
            # (client-chosen names fold into "other" — label domains
            # must stay bounded); the queued-tokens gauge children are
            # registered lazily per label with weakref callbacks, the
            # engines' gauge convention
            self._m_tenant_queued = reg.gauge(
                "serving_tenant_queued_tokens",
                "prompt tokens waiting in the queue, by tenant",
                labels=("tenant",))
            self._m_tenant_admitted = reg.counter(
                "serving_tenant_admitted_total",
                "requests admitted to a decode slot, by tenant",
                labels=("tenant",))
            self._m_tenant_preempt = reg.counter(
                "serving_tenant_preemptions_total",
                "in-flight decodes preempted, by (victim) tenant",
                labels=("tenant",))
            self._m_tenant_shed = reg.counter(
                "serving_tenant_sheds_total",
                "admission rejections by tenant and reason "
                "(tenant_quota = the per-tenant 429)",
                labels=("tenant", "reason"))
            self._tenant_gauge_labels: set = set()

        cfg = config
        temp = self.temperature

        def _sample_tok(logits, temps, topk, topp, seeds, pos, key):
            # per-slot sampling settings: each request samples at its
            # own temperature (0 = greedy) / top-k / top-p inside one
            # batched step — all branches are computed and where() picks
            # per row, one sort + categorical over (B, V), noise next to
            # the model forward. THE sampling body: every step variant
            # (plain/fused, contiguous/paged) calls it, so modes cannot
            # drift. Order matches generate: temperature scales first,
            # THEN the nucleus is chosen on the scaled logits
            key, sub = jax.random.split(key)
            safe = jnp.maximum(temps, 1e-6)[:, None]
            # the sort/softmax/cumsum filter only runs when some SAMPLED
            # row asked for it — the default all-greedy engine pays
            # nothing (one compiled program either way via cond)
            need = jnp.any(((topk > 0) | (topp < 1.0)) & (temps > 0))
            filtered = jax.lax.cond(
                need, lambda x: _filter_logits_rows(x, topk, topp),
                lambda x: x, logits / safe)
            sampled = jax.random.categorical(sub, filtered, axis=-1)
            # per-request seeds (seed >= 0): the row's key is a pure
            # function of (seed, absolute position of the token being
            # sampled) — independent of batch composition, engine-key
            # history, and sibling slots — so a request resumed on
            # ANOTHER replica (or after preemption) re-samples its
            # remaining tokens identically. Unseeded rows keep the
            # shared engine key bit-for-bit as before.
            any_seeded = jnp.any((seeds >= 0) & (temps > 0))

            def _seeded_rows(f):
                row_keys = jax.vmap(lambda s, p: jax.random.fold_in(
                    jax.random.PRNGKey(s), p + 1))(seeds, pos)
                return jax.vmap(jax.random.categorical)(row_keys, f)

            seeded = jax.lax.cond(any_seeded, _seeded_rows,
                                  lambda f: sampled, filtered)
            sampled = jnp.where(seeds >= 0, seeded, sampled)
            tok = jnp.where(temps > 0, sampled,
                            jnp.argmax(logits, axis=-1))
            return tok.astype(jnp.int32), key

        def _one_step(params, cache, last, pos, temps, topk, topp,
                      seeds, key):
            logits, cache = decode_step(params, cache, last, pos, cfg)
            tok, key = _sample_tok(logits, temps, topk, topp, seeds,
                                   pos, key)
            return tok, cache, key

        @partial(jax.jit, donate_argnums=(1,))
        def _step(params, cache, last, pos, temps, topk, topp, seeds,
                  key):
            return _one_step(params, cache, last, pos, temps, topk, topp,
                             seeds, key)

        n_sync = self.steps_per_sync

        @partial(jax.jit, donate_argnums=(1,))
        def _multi_step(params, cache, last, pos, temps, topk, topp,
                        seeds, key):
            # steps_per_sync decode steps in one lax.scan: each slot's
            # chain stays autoregressive (its sampled token feeds the
            # next step), so per-slot output is exactly the solo decode;
            # only the host's admission/retirement granularity changes.
            # Slots that retire mid-chunk keep decoding; the host
            # discards their surplus tokens, and their surplus cache
            # writes land in a freed row (dead until the next prefill)
            def body(carry, _):
                cache, last, pos, key = carry
                tok, cache, key = _one_step(params, cache, last, pos,
                                            temps, topk, topp, seeds,
                                            key)
                return (cache, tok, pos + 1, key), tok

            (cache, _, _, key), toks = jax.lax.scan(
                body, (cache, last, pos, key), None, length=n_sync)
            return jnp.swapaxes(toks, 0, 1), cache, key   # (B, K)

        if self.paged is not None:
            from .models.paged_decode import decode_step_paged

            kern, kern_interp = self.kernel, self._kernel_interpret

            def _one_step_paged(params, pool, tables, last, pos, temps,
                                topk, topp, seeds, key):
                logits, pool = decode_step_paged(params, pool, tables,
                                                 last, pos, cfg,
                                                 kernel=kern,
                                                 interpret=kern_interp)
                tok, key = _sample_tok(logits, temps, topk, topp, seeds,
                                       pos, key)
                return tok, pool, key

            @partial(jax.jit, donate_argnums=(1,))
            def _step_paged(params, pool, tables, last, pos, temps,
                            topk, topp, seeds, key):
                return _one_step_paged(params, pool, tables, last, pos,
                                       temps, topk, topp, seeds, key)

            @partial(jax.jit, donate_argnums=(1,))
            def _multi_step_paged(params, pool, tables, last, pos, temps,
                                  topk, topp, seeds, key):
                def body(carry, _):
                    pool, last, pos, key = carry
                    tok, pool, key = _one_step_paged(
                        params, pool, tables, last, pos, temps, topk,
                        topp, seeds, key)
                    return (pool, tok, pos + 1, key), tok

                (pool, _, _, key), toks = jax.lax.scan(
                    body, (pool, last, pos, key), None, length=n_sync)
                return jnp.swapaxes(toks, 0, 1), pool, key

            self._step_paged_fn = _step_paged
            self._multi_step_paged_fn = _multi_step_paged

        @partial(jax.jit, donate_argnums=(0,))
        def _install(cache, row_cache, slot):
            # slot is traced: one compilation serves every slot index;
            # the engine cache is donated (like _step's) so neither hot
            # path copies the multi-layer k/v buffers
            return jax.tree_util.tree_map(
                lambda big, row: jax.lax.dynamic_update_index_in_dim(
                    big, row[0], slot, 0), cache, row_cache)

        max_len = self.max_len

        @jax.jit
        def _prefill(params, prompt):
            # jit caches one executable per prompt-length shape: the
            # "one compile per distinct prompt length" admission cost
            return prefill_cache(params, prompt, cfg, max_len)

        def _make_extend(xcfg, donate=False):
            # two variants: the non-donating one serves shared prefix
            # entries (reused by every admission that hits them); the
            # donating one serves engine-OWNED rows — fresh prefill rows
            # and every chunk after the first — so chunked admission
            # rewrites one buffer instead of copying the full row cache
            # per block
            def _extend(params, row_cache, suffix, pos0):
                # continue a batch-1 prefill past what the row cache
                # already holds: the suffix attends to the cached k/v
                logits, row_cache = decode_block(params, row_cache,
                                                 suffix, pos0, xcfg)
                return logits[:, -1], row_cache
            if donate:
                return partial(jax.jit, donate_argnums=(1,))(_extend)
            return jax.jit(_extend)

        self._step_fn = _step
        self._multi_step_fn = _multi_step
        self._install_fn = _install
        self._prefill_fn = _prefill
        self._extend_fn = _make_extend(cfg)
        self._extend_owned_fn = _make_extend(cfg, donate=True)
        self._fresh_row_fn = lambda: init_kv_cache(cfg, 1, max_len)
        # registered shared prompt prefixes, longest first:
        # (tokens, last-position logits, target row cache, draft row cache)
        self._prefixes: List = []
        self._m_prefix_hits = reg.counter(
            "serving_prefix_hits_total",
            "admissions that reused a registered prompt prefix").labels()
        self._m_prefix_tokens = reg.counter(
            "serving_prefix_tokens_reused_total",
            "prompt tokens whose prefill was skipped via a prefix hit"
            ).labels()
        # automatic content-addressed KV block cache (module docstring):
        # default ON in paged mode, opt-in host-backed otherwise
        self._kv_cache = None
        self._kv_cache_bs: Optional[int] = None
        if prefix_cache is None:
            prefix_cache = self.paged is not None
        if prefix_cache:
            self.enable_prefix_cache(
                block_size=prefix_cache_block_size,
                capacity=prefix_cache_capacity)
        elif (prefix_cache_block_size is not None
                or prefix_cache_capacity is not None):
            raise ValueError("prefix_cache_block_size/"
                             "prefix_cache_capacity given with "
                             "prefix_cache disabled")
        # tiered KV spill / resumable sessions: True = defaults, a
        # dict = enable_* kwargs, an instance = share it (the shared-
        # instance form is the cross-replica session topology)
        if kv_spill:
            if kv_spill is True:
                self.enable_kv_spill()
            elif isinstance(kv_spill, dict):
                self.enable_kv_spill(**kv_spill)
            else:
                self.enable_kv_spill(spill=kv_spill)
        if session_store:
            if session_store is True:
                self.enable_session_store()
            elif isinstance(session_store, dict):
                self.enable_session_store(**session_store)
            else:
                self.enable_session_store(store=session_store)
        # construction-time baselines: an INJECTED shared registry may
        # already carry a predecessor engine's totals (weight-reload
        # flow) — stats must report THIS engine's deltas, never pooled
        # counts. With the default fresh registry every baseline is
        # zero and stats equals the scraped series exactly.
        self._stat_base = counter_baseline(
            self._m_steps, self._m_emitted, self._m_finished,
            self._m_shed, self._m_expired, self._m_timed_out,
            self._m_accepted, self._m_proposed,
            self._m_prefix_hits, self._m_prefix_tokens,
            self._m_weight_swaps,
            *([self._m_spec_rounds] if draft_config is not None
              else []))

        if draft_config is not None:
            from .models.speculative import speculative_round

            dcfg = draft_config

            # per-gamma compiled speculative rounds: gamma is baked into
            # the traced program (the draft-propose python loop), so an
            # adaptive engine holds one executable per depth it has
            # visited — bounded by [gamma_min, gamma], compiled lazily.
            # Fixed-gamma engines only ever build the ceiling's.
            def _make_spec_step(g):
                @partial(jax.jit, donate_argnums=(2, 3))
                def _spec_step(params, draft_params, cache, d_cache,
                               last, pos, key):
                    emit, a, nxt, cache, d_cache, key = (
                        speculative_round(
                            params, draft_params, cache, d_cache, last,
                            pos, g, cfg, dcfg,
                            jnp.float32(temp if temp > 0 else 1.0),
                            key, not temp > 0))
                    return emit, a, nxt, cache, d_cache, key

                return _spec_step

            self._spec_fns: Dict[int, object] = {}

            def _spec_step_for(g: int):
                fn = self._spec_fns.get(g)
                if fn is None:
                    fn = self._spec_fns[g] = _make_spec_step(g)
                return fn

            self._spec_step_for = _spec_step_for

            @jax.jit
            def _prefill_draft(draft_params, prompt):
                return prefill_cache(draft_params, prompt, dcfg, max_len)

            # _install handles any cache pytree (jit specializes per
            # structure), so the draft cache reuses it
            self._install_draft_fn = _install
            self._prefill_draft_fn = _prefill_draft
            self._extend_draft_fn = _make_extend(dcfg)
            self._extend_draft_owned_fn = _make_extend(dcfg, donate=True)
            self._fresh_draft_row_fn = lambda: init_kv_cache(dcfg, 1,
                                                             max_len)
            if self.paged is not None:
                from .models.speculative import speculative_round_paged

                def _make_spec_step_paged(g):
                    @partial(jax.jit, donate_argnums=(2, 3))
                    def _spec_step_paged(params, draft_params, pool,
                                         d_cache, tables, last, pos,
                                         key):
                        # paged speculative round: the target verifies
                        # into the slots' own block tables (verify slack
                        # budgeted at admission — at the gamma CEILING,
                        # so every depth <= it fits); the draft cache
                        # stays contiguous
                        emit, a, nxt, pool, d_cache, key = (
                            speculative_round_paged(
                                params, draft_params, pool, tables,
                                d_cache, last, pos, g, cfg, dcfg,
                                jnp.float32(temp if temp > 0 else 1.0),
                                key, not temp > 0))
                        return emit, a, nxt, pool, d_cache, key

                    return _spec_step_paged

                self._spec_fns_paged: Dict[int, object] = {}

                def _spec_step_paged_for(g: int):
                    fn = self._spec_fns_paged.get(g)
                    if fn is None:
                        fn = self._spec_fns_paged[g] = (
                            _make_spec_step_paged(g))
                    return fn

                self._spec_step_paged_for = _spec_step_paged_for

    # ------------------------------------------------------------ warmup
    def warmup(self, prompt_lengths: Sequence[int] = ()):
        """Compile the hot programs BEFORE traffic arrives: the decode
        step (plain or fused, paged or contiguous) plus, for each
        length in ``prompt_lengths``, the admission prefill path exactly
        as a real admission runs it (chunked block shapes when
        ``prefill_chunk`` is set, whole-prompt prefill otherwise) and
        the cache-install program. Call on an IDLE engine (it scribbles
        into free slots' cache rows, which the next admission
        overwrites); afterwards the first real request pays no jit
        latency for any warmed shape."""
        if (any(r is not None for r in self._rid) or self._queue
                or self._pending_prefill):
            raise RuntimeError("warmup() needs an idle engine")
        dummy = dict(last=jnp.zeros(self.max_slots, jnp.int32),
                     pos=jnp.zeros(self.max_slots, jnp.int32),
                     temps=jnp.asarray(self._temp),
                     topk=jnp.asarray(self._topk),
                     topp=jnp.asarray(self._topp),
                     seeds=jnp.asarray(self._slot_seed),
                     key=jax.random.PRNGKey(0))
        # the step fns donate the cache argument, so warming on the
        # engine's OWN cache (idle: every slot free, paged writes land
        # on scratch block 0) costs zero extra device memory — an
        # engine sized to fill the chip can still warm up
        if self.paged is not None and self.draft_config is not None:
            out = self._spec_step_paged_for(self._gamma_now)(
                self.params, self.draft_params, self.pool,
                self.draft_cache, jnp.asarray(self._tables),
                dummy["last"], dummy["pos"], dummy["key"])
            self.pool, self.draft_cache = out[3], out[4]
        elif self.paged is not None:
            fn = (self._multi_step_paged_fn if self.steps_per_sync > 1
                  else self._step_paged_fn)
            _, self.pool, _ = fn(
                self.params, self.pool, jnp.asarray(self._tables),
                dummy["last"], dummy["pos"], dummy["temps"],
                dummy["topk"], dummy["topp"], dummy["seeds"],
                dummy["key"])
        elif self.draft_config is not None:
            out = self._spec_step_for(self._gamma_now)(
                self.params, self.draft_params, self.cache,
                self.draft_cache, dummy["last"], dummy["pos"],
                dummy["key"])
            self.cache, self.draft_cache = out[3], out[4]
        else:
            fn = (self._multi_step_fn if self.steps_per_sync > 1
                  else self._step_fn)
            _, self.cache, _ = fn(
                self.params, self.cache, dummy["last"], dummy["pos"],
                dummy["temps"], dummy["topk"], dummy["topp"],
                dummy["seeds"], dummy["key"])
        for length in sorted(set(int(n) for n in prompt_lengths)):
            if not 1 <= length < self.max_len:
                raise ValueError(f"prompt length {length} out of range")
            fake = np.zeros(length, np.int32)
            _, row = self._prefill_with_prefixes(
                fake, self._extend_fn, self._extend_owned_fn,
                self._prefill_fn, self.params, None, 2,
                self._fresh_row_fn)
            if self.paged is not None:
                from .models.paged_decode import install_row_paged

                nprefill = -(-length // self.paged[1])
                self.pool = install_row_paged(
                    self.pool, row, self._tables[0], nprefill)
            else:
                self.cache = self._install_fn(self.cache, row, 0)
            if self.draft_config is not None:
                _, d_row = self._prefill_with_prefixes(
                    fake, self._extend_draft_fn,
                    self._extend_draft_owned_fn, self._prefill_draft_fn,
                    self.draft_params, None, 3, self._fresh_draft_row_fn)
                self.draft_cache = self._install_draft_fn(
                    self.draft_cache, d_row, 0)

    # ---------------------------------------------------------- prefixes
    def register_prefix(self, tokens: Sequence[int]) -> None:
        """Precompute and pin the KV state of a shared prompt prefix
        (e.g. a system prompt). Any subsequent request whose prompt
        starts with these tokens skips the prefix's share of prefill:
        admission installs the cached k/v and runs one
        :func:`~elephas_tpu.models.transformer.decode_block` over just
        the suffix. Longest registered match wins. Each registration
        holds one batch-1 cache row (``num_layers × kv_heads × max_len ×
        head_dim`` k+v, per model) on device until
        :meth:`clear_prefixes`."""
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        if tokens.size < 1:
            raise ValueError("prefix must hold at least one token")
        if tokens.size >= self.max_len:
            raise ValueError(f"prefix ({tokens.size}) must leave room "
                             f"below max_len {self.max_len}")
        if self.prefill_chunk is not None:
            # registration rides the same bounded block shapes as
            # admission — distinct prefix lengths cost no new compiles
            logits, row = self._extend_chunked(
                self.params, self._fresh_row_fn(), tokens, 0,
                self._extend_fn, self._extend_owned_fn, owned=True)
        else:
            logits, row = self._prefill_fn(self.params,
                                           jnp.asarray(tokens[None]))
        d_row = None
        if self.draft_config is not None:
            if self.prefill_chunk is not None:
                _, d_row = self._extend_chunked(
                    self.draft_params, self._fresh_draft_row_fn(),
                    tokens, 0, self._extend_draft_fn,
                    self._extend_draft_owned_fn, owned=True)
            else:
                _, d_row = self._prefill_draft_fn(
                    self.draft_params, jnp.asarray(tokens[None]))
        self._prefixes.append((tokens, logits[0], row, d_row))
        self._prefixes.sort(key=lambda e: -e[0].size)
        if self._kv_cache is not None:
            self._pin_prefix_blocks(tokens, row)

    def _pin_prefix_blocks(self, tokens: np.ndarray, row) -> None:
        """The pinning layer over the automatic cache: a registered
        prefix's FULL blocks enter the block cache with a refcount
        floor of one (never parked, never evicted), so every matching
        admission hits them through the ordinary chain walk. The
        sub-block tail keeps riding the registered row. A pool too
        full to hold a pin skips it (the row still serves matches) and
        says so on the event log."""
        from .models.block_cache import chain_keys

        cache, bs = self._kv_cache, self._kv_cache_bs
        nfull = tokens.size // bs
        if nfull == 0:
            return
        keys = chain_keys(tokens[:nfull * bs], bs, self.weights_version)
        if self.paged is not None:
            from .models.paged_decode import install_row_paged

            # batch consecutive absent keys into ONE install each: a
            # per-block install would compile one (start, nblocks)
            # specialization per block — K compiles for a K-block
            # system prompt, again on every post-hot-swap re-pin
            pend_start, pend_ids = None, []

            def flush():
                if not pend_ids:
                    return
                n = pend_start + len(pend_ids)
                ids = np.zeros(n, np.int32)
                ids[pend_start:] = pend_ids
                self.pool = install_row_paged(self.pool, row, ids, n,
                                              start=pend_start)

            for i, key in enumerate(keys):
                entry = cache.get(key)
                if entry is None:
                    if (not self._free_block_ids
                            and not cache.reclaimable_count()):
                        flush()
                        emit_event("serving.prefix_pin_skipped",
                                   tokens=int(tokens.size),
                                   pinned_blocks=i)
                        return
                    bid = self._alloc_block()
                    if (pend_start is None
                            or pend_start + len(pend_ids) != i):
                        flush()
                        pend_start, pend_ids = i, []
                    pend_ids.append(bid)
                    entry = cache.insert(key, bid, (i + 1) * bs)
                cache.pin(entry)
            flush()
            return
        missing = [i for i, key in enumerate(keys)
                   if cache.get(key) is None]
        payloads = dict(zip(missing,
                            self._host_cache_payloads(row, missing)))
        for i, key in enumerate(keys):
            entry = cache.get(key)
            if entry is None:
                entry = cache.insert(key, payloads[i], (i + 1) * bs)
            cache.pin(entry)

    def clear_prefixes(self) -> None:
        """Drop every registered prefix (frees their device cache rows
        and lifts the block cache's pins — unpinned entries park on the
        LRU reclaim list and age out under pressure)."""
        self._prefixes = []
        if self._kv_cache is not None:
            self._kv_cache.unpin_all()

    def _match_prefix(self, prompt: np.ndarray):
        for entry in self._prefixes:  # longest first
            p = entry[0]
            if p.size <= prompt.size and np.array_equal(prompt[:p.size], p):
                return entry
        return None

    def _extend_chunked(self, params, row, tokens: np.ndarray, pos0: int,
                        extend_fn, extend_owned_fn, owned: bool):
        """Feed ``tokens`` (1-D) through the extend fns in
        ``prefill_chunk``-sized blocks — at most ``prefill_chunk``
        distinct block shapes ever compile, regardless of how many
        prompt lengths an online server sees. ``owned`` marks the INPUT
        row as engine-owned (donatable); blocks after the first always
        operate on engine-owned intermediates."""
        def block(cache, blk, pos, first):
            fn = extend_owned_fn if (owned or not first) else extend_fn
            return fn(params, cache, jnp.asarray(blk), jnp.int32(pos))

        return chunked_blocks(block, row, tokens[None], int(pos0),
                              self.prefill_chunk)

    def _prefill_with_prefixes(self, prompt: np.ndarray, extend_fn,
                               extend_owned_fn, prefill_fn, params, entry,
                               cache_idx: int, fresh_fn):
        """Batch-1 prefill that reuses a matched prefix entry's cache row.
        Returns (last-position logits (vocab,), row cache)."""
        chunked = self.prefill_chunk is not None
        if entry is None:
            if chunked:
                logits, row = self._extend_chunked(
                    params, fresh_fn(), prompt, 0, extend_fn,
                    extend_owned_fn, owned=True)
                return logits[0], row
            logits, row = prefill_fn(params, jnp.asarray(prompt[None]))
            return logits[0], row
        ptoks, plogits = entry[0], entry[1]
        row = entry[cache_idx]
        if prompt.size == ptoks.size:
            return plogits, row
        if chunked:
            logits, row = self._extend_chunked(
                params, row, prompt[ptoks.size:], int(ptoks.size),
                extend_fn, extend_owned_fn, owned=False)
            return logits[0], row
        suffix = jnp.asarray(prompt[None, ptoks.size:])
        logits, row = extend_fn(params, row, suffix,
                                jnp.int32(ptoks.size))
        return logits[0], row

    # ------------------------------------------------- automatic KV cache
    def enable_prefix_cache(self, block_size: Optional[int] = None,
                            capacity: Optional[int] = None) -> None:
        """Turn on the automatic content-addressed KV block cache (see
        the module docstring) — paged engines have it on by default;
        contiguous engines (a fleet replica, a disaggregated prefill
        worker's export engine) call this to get the host-array-backed
        variant. Call BEFORE traffic: enabling is not synchronized
        against a running engine loop. No-op when already enabled."""
        if self._kv_cache is not None:
            return
        from .models.block_cache import BlockCache

        if self.paged is not None:
            if (block_size is not None
                    and int(block_size) != self.paged[1]):
                raise ValueError(
                    f"paged engines cache at the pool block size "
                    f"{self.paged[1]}, got prefix_cache_block_size="
                    f"{block_size}")
            self._kv_cache_bs = self.paged[1]
            # pooled mode: the pool IS the capacity; eviction returns
            # the entry's block to the free list (reclaim-over-shed)
            self._kv_cache = BlockCache(on_evict=self._on_cache_evict)
        else:
            self._kv_cache_bs = int(block_size or 64)
            if not 1 <= self._kv_cache_bs < self.max_len:
                raise ValueError(
                    f"prefix_cache_block_size {self._kv_cache_bs} out "
                    f"of range [1, max_len={self.max_len})")
            self._kv_cache = BlockCache(
                capacity=1024 if capacity is None else int(capacity),
                on_evict=self._on_cache_evict)
        self._chain_memo = None   # (rid, version, walk_keys, ins_keys)
        reg = self.registry
        self._m_kv_hits = reg.counter(
            "serving_kv_cache_hits_total",
            "admissions/exports that reused >= 1 cached KV block"
            ).labels()
        self._m_kv_misses = reg.counter(
            "serving_kv_cache_misses_total",
            "admissions/exports with >= 1 full block and zero cache "
            "reuse").labels()
        self._m_kv_evictions = reg.counter(
            "serving_kv_cache_evictions_total",
            "cold cached blocks reclaimed under pool/capacity pressure"
            ).labels()
        import weakref

        ref = weakref.ref(self)
        reg.gauge("serving_kv_cache_blocks",
                  "KV blocks currently held by the prefix cache"
                  ).set_function(
            lambda: float(len(e._kv_cache))
            if (e := ref()) is not None and e._kv_cache is not None
            else 0.0)
        reg.gauge("serving_kv_cache_reclaimable_blocks",
                  "cached blocks on the LRU free list (zero-ref, "
                  "unpinned — reclaimable by admission pressure)"
                  ).set_function(
            lambda: float(e._kv_cache.reclaimable_count())
            if (e := ref()) is not None and e._kv_cache is not None
            else 0.0)

    # ------------------------------------------------- tiered KV spill
    def enable_kv_spill(self, spill=None, *,
                        host_capacity_blocks: Optional[int] = 4096,
                        storage_url: Optional[str] = None,
                        storage_compress: str = "q8",
                        storage_capacity_blocks: Optional[int] = None,
                        lossy_promote: bool = False):
        """Turn on the tiered KV spill plane (:mod:`~elephas_tpu.
        kvtier`): block-cache evictions DEMOTE to host RAM (and
        optionally to ``storage_url``'s object store, Q8-compressed)
        instead of discarding, and admission chain walks fall through
        device → host → storage, promoting spilled blocks back with
        one host→device copy each. Implies the prefix cache. Call
        BEFORE traffic, like :meth:`enable_prefix_cache`.

        ``lossy_promote`` opts in to promoting Q8 (storage-tier)
        blocks: the dequantized KV serves the admitting request —
        saving its re-prefill at a bounded-error cost — but the slot
        is tainted so nothing computed over it ever registers, parks,
        or persists under chain keys (lossy-parity rule; default off
        keeps outputs bit-identical to spill-off). Returns the
        :class:`~elephas_tpu.kvtier.TieredSpill` (pass ``spill`` to
        share one across engines)."""
        if self._kv_spill is not None:
            return self._kv_spill
        if self._kv_cache is None:
            self.enable_prefix_cache()
        from .kvtier import TieredSpill

        if spill is None:
            spill = TieredSpill(
                host_capacity_blocks=host_capacity_blocks,
                storage_url=storage_url,
                storage_compress=storage_compress,
                storage_capacity_blocks=storage_capacity_blocks)
        self._kv_spill = spill
        self._lossy_promote = bool(lossy_promote)
        self._ensure_spill_metrics()
        spill.bind_metrics(self._m_spill_demote, self._m_spill_bytes)
        return spill

    def enable_session_store(self, store=None, *,
                             url: Optional[str] = None,
                             compress: str = "none",
                             capacity_blocks: Optional[int] = 16384):
        """Turn on resumable cross-request sessions (:mod:`~elephas_tpu.
        kvtier`): a request submitted with ``session=<id>`` persists
        its final sequence's full KV blocks here at retirement, keyed
        by content-addressed chain + ``weights_version``, and a later
        request for the same conversation admits as a chain hit — on
        ANY engine sharing the backend (pass one
        :class:`~elephas_tpu.kvtier.SessionStore` instance to several
        engines, or point them at one ``url``). Persistence needs a
        paged engine (blocks are exported straight off the pool);
        lookup/promotion works on any engine with the prefix cache.
        Hot-swap invalidation is free by construction — post-swap
        chains hash differently. Implies the prefix cache."""
        if self._session_store is not None:
            return self._session_store
        if self._kv_cache is None:
            self.enable_prefix_cache()
        from .kvtier import SessionStore

        if store is None:
            store = SessionStore(url=url, compress=compress,
                                 capacity_blocks=capacity_blocks)
        self._session_store = store
        self._ensure_spill_metrics()
        return store

    def _ensure_spill_metrics(self) -> None:
        """The spill/session metric families, shared by both enable
        paths (promotions may source from either plane). Baselined
        like every engine counter so stats stays per-engine on an
        injected shared registry."""
        if self._m_spill_promote is not None:
            return
        reg = self.registry
        self._m_spill_demote = reg.counter(
            "serving_kv_spill_demotions_total",
            "KV blocks demoted into a spill tier, by destination tier",
            labels=("tier",))
        self._m_spill_promote = reg.counter(
            "serving_kv_spill_promotions_total",
            "spilled KV blocks promoted back to device, by source "
            "tier ('session' = the session store)", labels=("tier",))
        self._m_spill_bytes = reg.counter(
            "serving_kv_spill_bytes_total",
            "payload bytes written into a spill tier, by tier",
            labels=("tier",))
        self._m_session_hits = reg.counter(
            "serving_kv_session_hits_total",
            "session-tagged admissions that reused >= 1 chain block "
            "(device, spill, or session tier)").labels()
        self._m_session_misses = reg.counter(
            "serving_kv_session_misses_total",
            "session-tagged admissions with a walkable chain and "
            "zero reuse (cold resume: full re-prefill)").labels()
        self._spill_stat_base = counter_baseline(
            self._m_session_hits, self._m_session_misses)
        import weakref

        ref = weakref.ref(self)
        g_blocks = reg.gauge(
            "serving_kv_tier_blocks",
            "KV blocks resident per spill/session tier",
            labels=("tier",))
        g_bytes = reg.gauge(
            "serving_kv_tier_bytes",
            "payload bytes resident per spill/session tier",
            labels=("tier",))

        def _tier_stat(tier, field):
            e = ref()
            if e is None:
                return 0.0
            if tier == "session":
                return (float(e._session_store.stats()[field])
                        if e._session_store is not None else 0.0)
            spill = e._kv_spill
            if spill is None:
                return 0.0
            if tier == "storage" and spill.storage is None:
                return 0.0
            src = spill.host if tier == "host" else spill.storage
            return float(len(src) if field == "blocks" else src.nbytes)

        for tier in ("host", "storage", "session"):
            g_blocks.labels(tier=tier).set_function(
                partial(_tier_stat, tier, "blocks"))
            g_bytes.labels(tier=tier).set_function(
                partial(_tier_stat, tier, "bytes"))

    def _pool_block_payload(self, bid: int) -> Dict:
        """One pool block as a host payload dict — the demotion read.
        Must run BEFORE the block id is reused (i.e. inside the
        eviction callback, before the free list hands it out)."""
        return {name: (np.asarray(lc["k"][bid]), np.asarray(lc["v"][bid]))
                for name, lc in self.pool.items()}

    def _on_cache_evict(self, entry) -> None:
        spill = self._kv_spill
        if spill is not None and int(getattr(entry, "tokens", 0)) > 0:
            # demote instead of discard. Inside an allocation loop
            # (_demote_accum set) paged payloads are STAGED and read
            # out in one batched per-layer gather at the flush — a
            # per-eviction device read syncs the stream once per block
            # and dominates warm-TTFT otherwise. The staged block id
            # may rejoin the free list and even be re-allocated to the
            # admitting request, but its pool contents are untouched
            # until that request installs — which happens strictly
            # after the flush.
            if self.paged is not None:
                if self._demote_accum is not None:
                    self._demote_accum.setdefault("staged", []).append(
                        (entry.key, int(entry.payload),
                         int(entry.tokens)))
                else:
                    # eviction outside an admission (register_prefix
                    # pressure): read out NOW, before the id rejoins
                    # the free list. Sources are always EXACT — lossy
                    # blocks never become cache entries.
                    spill.demote(
                        entry.key,
                        self._pool_block_payload(int(entry.payload)),
                        entry.tokens)
            else:
                spill.demote(entry.key, entry.payload, entry.tokens)
                if self._demote_accum is not None:
                    self._demote_accum["blocks"] = (
                        self._demote_accum.get("blocks", 0) + 1)
        if self.paged is not None:
            self._free_block_ids.append(entry.payload)
        self._m_kv_evictions.inc()

    def _flush_demotions(self, accum) -> int:
        """Batch-demote the evictions an allocation loop staged: ONE
        device->host gather per layer for every staged block (the
        export_pool_blocks path), then the per-key spill puts. Returns
        the number of blocks demoted (staged + contiguous-mode
        immediates)."""
        staged = accum.get("staged", ())
        if staged:
            from .models.paged_decode import export_pool_blocks

            payloads = export_pool_blocks(
                self.pool, [bid for _, bid, _ in staged])
            for (key, _, tokens), payload in zip(staged, payloads):
                self._kv_spill.demote(key, payload, tokens)
        return accum.get("blocks", 0) + len(staged)

    def _tier_lookup(self, key: bytes):
        """One chain key's spill/session resolution: ``(block,
        source_tier)`` or ``None`` — spill tiers first (host RAM beats
        a storage read), then the session store."""
        if self._kv_spill is not None:
            found = self._kv_spill.lookup(key)
            if found is not None:
                return found
        if self._session_store is not None:
            block = self._session_store.get_block(key)
            if block is not None:
                return block, "session"
        return None

    def _tier_walk(self, rid: Optional[int], keys, start: int,
                   allow_lossy: bool = False) -> List:
        """Continue an admission's chain walk past the device cache:
        the longest run of consecutive ``keys`` resolvable in the
        spill tiers / session store, as ``[(SpilledBlock, tier)]``.
        Memoized per (rid, version, start): a queue head waiting for
        capacity re-walks every step, and the tier reads (a storage
        GET per key) are the expensive half. ``start`` — the device
        hit count — keys the memo because another admission may
        register more of the chain while this candidate waits; promos
        computed at the old offset would then overlap the new hits."""
        if self._kv_spill is None and self._session_store is None:
            return []
        memo = self._promo_memo
        if (rid is not None and memo is not None and memo[0] == rid
                and memo[1] == self.weights_version
                and memo[2] == start):
            return memo[3]
        promos: List = []
        for key in keys:
            found = self._tier_lookup(key)
            if found is None:
                break
            block, src = found
            if block.lossy:
                if allow_lossy:
                    # a lossy block still ends the walk: everything
                    # after it is served freshly anyway once the slot
                    # is tainted, and stopping bounds the blast radius
                    promos.append((block, src))
                break
            promos.append((block, src))
        if rid is not None:
            self._promo_memo = (rid, self.weights_version, start,
                                promos)
        return promos

    def _cache_chain_keys(self, prompt: np.ndarray):
        """(walk_keys, insert_keys) for ``prompt``: insert keys cover
        every full block (``size // bs``); the WALK is capped one block
        earlier when the prompt is block-aligned (``(size-1) // bs``)
        so the remainder prefill is never empty — it is what produces
        the final-position logits the first token samples from."""
        from .models.block_cache import chain_keys

        bs = self._kv_cache_bs
        nfull = prompt.size // bs
        ins_keys = chain_keys(prompt[:nfull * bs], bs,
                              self.weights_version)
        return ins_keys[:(prompt.size - 1) // bs], ins_keys

    def _chain_keys_for(self, rid: Optional[int], prompt: np.ndarray):
        """Memoized :meth:`_cache_chain_keys` keyed on (rid, version):
        one admission consults the chain up to three times (the
        availability walk, the prefill walk, the insert), and a queue
        head waiting for capacity re-walks EVERY step — the prompt and
        version are unchanged throughout, so hash once. ``rid=None``
        (exports) skips the memo."""
        if rid is None:
            return self._cache_chain_keys(prompt)
        memo = self._chain_memo
        if (memo is not None and memo[0] == rid
                and memo[1] == self.weights_version):
            return memo[2], memo[3]
        walk, ins = self._cache_chain_keys(prompt)
        self._chain_memo = (rid, self.weights_version, walk, ins)
        return walk, ins

    def _alloc_block(self) -> int:
        """One free block id — reclaiming the coldest parked cache
        entry when the free list is dry (callers checked availability
        = free + reclaimable inside the admission math)."""
        if not self._free_block_ids:
            self._kv_cache.evict_lru()     # on_evict refills free list
        return self._free_block_ids.popleft()

    def _insert_full_blocks(self, slot: int, prompt: np.ndarray,
                            skip: int = 0,
                            rid: Optional[int] = None) -> None:
        """Register the slot's freshly prefilled full blocks
        (``skip..nfull``) in the pooled cache: each absent chain key's
        block moves from the slot's PRIVATE list to its SHARED list,
        refcounted by this slot from birth — a same-prefix request
        admitted one step later already hits."""
        if self._slot_lossy[slot]:
            # the slot admitted over a lossy promoted block: its fresh
            # blocks were computed attending to dequantized KV and must
            # never register as the exact content their tokens address
            return
        cache, bs = self._kv_cache, self._kv_cache_bs
        nfull = prompt.size // bs
        if nfull <= skip:
            return
        _, ins_keys = self._chain_keys_for(rid, prompt)
        for i in range(skip, nfull):
            key = ins_keys[i]
            if cache.get(key) is not None:
                # an equal-content entry exists elsewhere (another
                # slot inserted it first, or an orphaned chain tail
                # survived an eviction): keep ours private
                continue
            bid = int(self._tables[slot, i])
            entry = cache.insert(key, bid, (i + 1) * bs, acquire=True)
            self._slot_blocks[slot].remove(bid)
            self._slot_cached[slot].append(entry)

    def _host_cache_payloads(self, row, indices):
        """Host payloads for blocks ``indices`` of a device row — ONE
        device-to-host transfer per layer k/v (not per block: a long
        prompt's miss would otherwise issue 2·layers·blocks small
        blocking transfers on the prefill hot path), sliced and copied
        host-side so a payload never pins the whole row."""
        if not indices:
            return []
        bs = self._kv_cache_bs
        host = {name: (np.asarray(lc["k"][0]), np.asarray(lc["v"][0]))
                for name, lc in row.items()}
        return [{name: (k[:, i * bs:(i + 1) * bs].copy(),
                        v[:, i * bs:(i + 1) * bs].copy())
                 for name, (k, v) in host.items()}
                for i in indices]

    def _host_cache_row(self, hits):
        """Device row whose head positions ``[0, len(hits)*bs)`` are the
        cached host blocks — the host-mode hit's one copy (vs the
        prefix's prefill FLOPs)."""
        from .models.paged_decode import import_kv_blocks

        flat = []
        names = sorted(hits[0].payload,
                       key=lambda n: int(n.split("_", 1)[1]))
        for name in names:
            flat.append(np.stack([e.payload[name][0] for e in hits]))
            flat.append(np.stack([e.payload[name][1] for e in hits]))
        row_np = import_kv_blocks(flat, len(hits) * self._kv_cache_bs,
                                  self.max_len)
        return jax.tree_util.tree_map(
            lambda a: jnp.asarray(a, self.config.dtype), row_np)

    def _extend_remainder(self, row, prompt: np.ndarray, pos0: int):
        """Prefill ``prompt[pos0:]`` on top of a row holding
        ``[0, pos0)`` — the remainder half of every cache hit. ``row``
        is always engine-owned here (a fresh gather/import), so the
        donating extend variants apply. Returns (last-position logits
        ``(vocab,)``, full row)."""
        suffix = prompt[pos0:]
        if self.prefill_chunk is not None:
            logits, row = self._extend_chunked(
                self.params, row, suffix, pos0, self._extend_fn,
                self._extend_owned_fn, owned=True)
            return logits[0], row
        logits, row = self._extend_owned_fn(
            self.params, row, jnp.asarray(suffix[None]),
            jnp.int32(pos0))
        return logits[0], row

    def _host_cache_prefill(self, rid: Optional[int],
                            prompt: np.ndarray):
        """The host-mode cached prefill shared by contiguous admission
        and :meth:`export_prefill`: longest cached chain (or the longer
        registered row) supplies the prompt head, the remainder
        prefills, and the freshly computed full blocks insert. Returns
        (last-position logits ``(vocab,)``, row, cache_tokens_reused,
        registered_tokens_reused) — at most one of the two reuse counts
        is nonzero (whichever layer covered more served)."""
        cache, bs = self._kv_cache, self._kv_cache_bs
        walk_keys, ins_keys = self._chain_keys_for(rid, prompt)
        hits = cache.match_chain(walk_keys)
        # host-mode tier fall-through: LOSSLESS spilled blocks only
        # (the payload joins the row head exactly like a cache hit, and
        # re-registers below — a lossy payload could do neither without
        # slot-taint machinery the contiguous engine doesn't carry)
        promos = self._tier_walk(rid, walk_keys[len(hits):], len(hits))
        j = len(hits) + len(promos)
        entry = self._match_prefix(prompt)
        reg_len = 0 if entry is None else int(entry[0].size)
        reg_used = 0
        if reg_len > j * bs:
            # the pinned row covers more (a sub-block registered head,
            # or a cold cache): classic registered-prefix path — the
            # computed row still warms the cache below
            if entry is not None:
                self._m_prefix_hits.inc()
                self._m_prefix_tokens.inc(reg_len)
                reg_used = reg_len
            logits, row = self._prefill_with_prefixes(
                prompt, self._extend_fn, self._extend_owned_fn,
                self._prefill_fn, self.params, entry, 2,
                self._fresh_row_fn)
            j, reused, promos = 0, 0, []
        elif j > 0:
            for e in hits:
                cache.touch(e)
            reused = j * bs
            self._m_kv_hits.inc()
            self._m_prefix_tokens.inc(reused)
            cache.record_walk(j, True)
            if rid is not None:
                self.recorder.record(rid, "kv_cache_hit", blocks=j,
                                     tokens_reused=reused,
                                     promoted=len(promos))
            row = self._host_cache_row(
                hits + [blk for blk, _ in promos])
            logits, row = self._extend_remainder(row, prompt, reused)
            for blk, src in promos:
                if self._m_spill_promote is not None:
                    self._m_spill_promote.labels(tier=src).inc()
                if cache.get(blk.key) is None:
                    # exact payload: re-register under the chain key
                    # so the next same-chain admission device-hits
                    cache.insert(blk.key, blk.payload, blk.tokens)
                if self._kv_spill is not None:
                    self._kv_spill.consumed(blk.key)
            if promos:
                self._promo_memo = None
                if rid is not None:
                    self.recorder.record(rid, "kv_promote",
                                         blocks=len(promos))
                emit_event("serving.kv_promote", rid=rid,
                           blocks=len(promos))
        else:
            if walk_keys:
                self._m_kv_misses.inc()
            cache.record_walk(0, bool(walk_keys))
            logits, row = self._prefill_with_prefixes(
                prompt, self._extend_fn, self._extend_owned_fn,
                self._prefill_fn, self.params, None, 2,
                self._fresh_row_fn)
            reused = 0
        missing = [i for i in range(j, len(ins_keys))
                   if cache.get(ins_keys[i]) is None]
        for i, payload in zip(missing, self._host_cache_payloads(row,
                                                                 missing)):
            cache.insert(ins_keys[i], payload, (i + 1) * bs)
        return logits, row, reused, reg_used

    # ------------------------------------------------------- live weights
    def stage_params(self, params: Dict, version: int,
                     trace_id: Optional[str] = None) -> None:
        """Stage a new parameter pytree for an atomic hot-swap. Safe
        from ANY thread (a :class:`~elephas_tpu.weightsync.
        WeightSubscriber`'s background puller): the engine applies it
        between decode steps — the same atomic point KV installs use —
        on its next :meth:`step` (or via an explicit
        :meth:`apply_staged_params` on engines that never step, e.g. a
        prefill worker's). Latest staging wins; in-flight requests
        finish on whichever version they step under. ``params`` should
        already be device arrays in the engine's tree structure — the
        conversion belongs OFF the engine loop, which is why staging
        and applying are split. ``trace_id`` (the stager's active
        trace) rides to the ``weights.swapped`` event so a canary
        rollout's whole story joins on one id.

        Speculative mode swaps only the TARGET params: speculative
        sampling is exact with respect to the target model, so a stale
        draft costs acceptance rate, never correctness."""
        with self._staged_lock:
            self._staged_params = (params, int(version), trace_id,
                                   time.monotonic())

    def stage_draft_params(self, draft_params: Dict, version: int,
                           trace_id: Optional[str] = None) -> None:
        """Stage new DRAFT-model params for the same atomic
        between-decode-steps swap as :meth:`stage_params` — the second
        :class:`~elephas_tpu.weightsync.WeightSubscriber` channel that
        keeps a continuously re-distilled draft
        (:mod:`~elephas_tpu.models.distill`) fresh alongside the
        target. Versioned independently (``draft_weights_version``);
        safe from any thread, latest staging wins. A draft swap can
        never change output: speculative sampling is exact with respect
        to the TARGET model, so draft freshness buys acceptance rate
        (tokens per round) and nothing else — which is also why draft
        KV is never cached and no chain key ever hashes the draft
        version."""
        if self.draft_config is None:
            raise ValueError("stage_draft_params needs a speculative "
                             "engine (draft_params/draft_config)")
        with self._staged_lock:
            self._staged_draft = (draft_params, int(version), trace_id,
                                  time.monotonic())

    def apply_staged_params(self) -> Optional[int]:
        """Apply a staged swap NOW, if any; returns the new version (or
        None). Must be called from whatever context owns the engine's
        step/prefill serialization — ``step()`` calls it between decode
        steps, and a :class:`~elephas_tpu.disagg.PrefillWorker` calls
        it between jobs. Registered prefixes are recomputed under the
        new params before the swap returns (their cached KV was
        computed under the old weights — serving it after the swap
        would hand out stale state the same way an unstamped shipped-KV
        frame would), so the swap pause scales with the number of
        pinned prefixes; the ``serving_weight_swap_seconds`` histogram
        measures exactly this blockage."""
        with self._staged_lock:
            staged, self._staged_params = self._staged_params, None
            staged_draft, self._staged_draft = self._staged_draft, None
        if staged_draft is not None:
            self._apply_staged_draft(staged_draft)
        if staged is None:
            return None
        params, version, trace_id, staged_t = staged
        t0 = time.monotonic()
        self.params = params
        self.weights_version = int(version)
        if self._kv_cache is not None:
            # version-keyed invalidation by construction: post-swap
            # chains hash under the NEW version, so every old entry
            # simply stops matching — no flush pause. Lifting old pins
            # here lets old-version pinned blocks park and age out of
            # the LRU (the recompute below re-pins under the new
            # version); an in-use old block stays referenced until its
            # request retires, then parks, never to be served again.
            self._kv_cache.unpin_all()
        if self._kv_spill is not None:
            # spilled blocks share the construction: old-version chains
            # can never match again, so the host tier's RAM comes back
            # NOW rather than at LRU age-out (storage entries are
            # equally unreachable and age out under write-capacity LRU)
            self._kv_spill.clear_host()
        self._promo_memo = None
        if self._prefixes:
            # re-pin every registered prefix under the new weights;
            # register_prefix re-sorts, so matching behavior is
            # unchanged
            tokens = [entry[0] for entry in self._prefixes]
            self._prefixes = []
            for toks in tokens:
                self.register_prefix(toks)
        pause = time.monotonic() - t0
        self._m_weight_swaps.inc()
        self._m_swap_pause.observe(pause)
        emit_event("weights.swapped", trace_id=trace_id,
                   version=int(version), tier=self.tier,
                   prefixes_recomputed=len(self._prefixes),
                   staged_for_s=round(t0 - staged_t, 6),
                   pause_s=round(pause, 6))
        return int(version)

    def _apply_staged_draft(self, staged: Tuple) -> None:
        """Swap the draft params in (between decode steps — the caller
        is :meth:`apply_staged_params`). In-flight requests keep their
        draft KV computed under the OLD draft: mixed draft state skews
        what the draft proposes, which only moves the acceptance rate —
        the target's verify pass makes output exact regardless, so
        unlike a target swap nothing needs recomputing for correctness.
        Registered prefixes' draft rows ARE refreshed (one batch-1
        draft prefill per pin) so steady-state acceptance doesn't decay
        for pinned heads."""
        draft_params, version, trace_id, staged_t = staged
        t0 = time.monotonic()
        self.draft_params = draft_params
        self.draft_weights_version = int(version)
        # a fresh draft resets the adaptive-gamma controller to the
        # ceiling: the EWMA's memory of the STALE draft's acceptance
        # would otherwise hold the depth down for dozens of rounds
        # after the cause is gone
        self._gamma_now = self.gamma
        self._accept_ewma = None
        self._rounds_since_adjust = 0
        if self._prefixes:
            fresh = []
            for entry in self._prefixes:
                toks = entry[0]
                if self.prefill_chunk is not None:
                    _, d_row = self._extend_chunked(
                        self.draft_params, self._fresh_draft_row_fn(),
                        toks, 0, self._extend_draft_fn,
                        self._extend_draft_owned_fn, owned=True)
                else:
                    _, d_row = self._prefill_draft_fn(
                        self.draft_params, jnp.asarray(toks[None]))
                fresh.append((entry[0], entry[1], entry[2], d_row))
            self._prefixes = fresh
        emit_event("weights.draft_swapped", trace_id=trace_id,
                   version=int(version), tier=self.tier,
                   prefixes_recomputed=len(self._prefixes),
                   staged_for_s=round(t0 - staged_t, 6),
                   pause_s=round(time.monotonic() - t0, 6))

    # ------------------------------------------------------------ queue
    def check_admissible(self, prompt_size: int,
                         max_new_tokens: int,
                         prompt: Optional[np.ndarray] = None,
                         tenant: Optional[str] = None) -> None:
        """Raise ``ValueError`` when a request is PERMANENTLY
        inadmissible on this engine — it exceeds ``max_len`` (plus the
        speculative verify slack), could never fit the paged block
        pool, or its prompt alone exceeds ``max_queued_tokens``. A
        retryable :class:`QueueFullError` (429 + backoff) for these
        would have well-behaved clients retrying forever. THE shared
        validator: the engine's own submit paths and the disaggregated
        front end (:class:`~elephas_tpu.disagg.DisaggEngine`) both call
        it, so an inadmissible request always 400s at submit instead of
        failing at KV-install time inside an engine loop."""
        # speculative rounds write verify blocks up to gamma positions
        # past the last emitted token
        slack = self._slack
        if prompt_size + max_new_tokens + slack > self.max_len:
            raise ValueError(
                f"prompt ({prompt_size}) + max_new_tokens "
                f"({max_new_tokens})"
                + (f" + gamma ({slack})" if slack else "")
                + f" exceeds max_len {self.max_len}")
        if self.paged is not None:
            # the same slack bounds the paged budget: verify writes land
            # up to gamma positions past the budgeted output, so the
            # slot's table must own those blocks too
            needed = -(-(prompt_size + max_new_tokens + slack)
                       // self.paged[1])
            allocatable = self.paged[0] - 1     # block 0 never allocates
            if self._kv_cache is not None:
                # PINNED registered-prefix blocks are never reclaimable
                # (the refcount floor), so they permanently shrink what
                # a request can allocate — EXCEPT the leading pinned
                # blocks the prompt itself would reuse, which need no
                # allocation (its table points at them). Unpinned cache
                # entries don't count: admission pressure reclaims them.
                pinned = self._kv_cache.pinned_count()
                if pinned and prompt is not None:
                    walk_keys, _ = self._cache_chain_keys(
                        np.asarray(prompt, np.int32).reshape(-1))
                    # only the LEADING RUN of pinned entries is a
                    # permanent guarantee — a transient entry between
                    # pinned ones may be evicted, breaking the walk
                    for e in self._kv_cache.match_chain(walk_keys):
                        if not e.pinned:
                            break
                        needed -= 1
                allocatable -= pinned
            if needed > allocatable:
                raise ValueError(
                    f"request needs {needed} blocks but the pool only "
                    f"has {allocatable} allocatable — it could "
                    "never be admitted")
        if (self.max_queued_tokens is not None
                and prompt_size > self.max_queued_tokens):
            raise ValueError(
                f"prompt of {prompt_size} tokens exceeds "
                f"max_queued_tokens={self.max_queued_tokens} — it could "
                "never be admitted")
        if self.qos is not None and tenant is not None:
            # per-tenant quota, permanent half: a prompt LARGER than
            # its tenant's token quota can never be queued — that is a
            # 400 at submit, not a retryable 429 (the transient half
            # lives in check_tenant_admissible)
            _, token_quota = self.qos.quota(tenant)
            if token_quota is not None and prompt_size > token_quota:
                raise ValueError(
                    f"prompt of {prompt_size} tokens exceeds tenant "
                    f"{tenant!r}'s max_queued_tokens quota "
                    f"{token_quota} — it could never be admitted")

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               temperature: Optional[float] = None,
               top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               admit: bool = True,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               priority=None,
               seed: Optional[int] = None,
               resume_from: int = 0,
               session: Optional[str] = None) -> int:
        """Queue a request; returns its id. Admission happens lazily on
        the next :meth:`step` (or immediately if a slot is free).
        ``temperature``/``top_k``/``top_p`` override the engine defaults
        for THIS request (plain stepping only — speculative mode samples
        every slot at the engine temperature, since the accept/resample
        rule is compiled for one setting). ``admit=False`` skips the
        immediate admission attempt entirely, deferring it — and any
        prefill jit compile a new prompt length triggers — to the next
        :meth:`step`; callers that serialize engine access behind a lock
        (the HTTP server) use this so submitting never holds that lock
        across a multi-second compile.

        ``deadline_ms`` bounds the request's TOTAL time in the engine:
        if it is still queued when the deadline passes it is shed before
        prefill (``result_info`` reports ``expired``); if the deadline
        passes mid-decode the slot is freed and the tokens emitted so
        far become the final output (``timeout``). Raises
        :class:`QueueFullError` when ``max_queue``/``max_queued_tokens``
        is configured and the backlog is at capacity — overload answers
        immediately instead of queueing unboundedly.

        ``tenant`` names who this request belongs to (``"default"``
        when omitted) and ``priority`` overrides the tenant's class
        (a :data:`~elephas_tpu.serving_qos.PRIORITY_CLASSES` name or
        int) — with a ``qos`` policy configured these drive weighted
        fair queueing, per-tenant quotas (a breach sheds with the
        quota-aware 429), and priority preemption; without one they
        are attribution only.

        ``seed`` pins THIS request's sampling RNG: each sampled token's
        key derives purely from ``(seed, absolute position)``, so the
        same seeded request replays the same output on any engine —
        and a request resumed elsewhere (``resume_from``) continues
        sampling exactly the sequence the original would have emitted.
        Plain stepping only (speculative mode shares one engine key).
        Greedy requests ignore it.

        ``resume_from=N`` declares the LAST ``N`` tokens of ``prompt``
        to be output this request already emitted elsewhere (a killed
        replica's journaled stream, a checkpointed session): admission
        prefills the full sequence as a forced prefix — often a
        prefix-cache chain hit — and the request's output starts with
        those ``N`` tokens followed by ``max_new_tokens`` freshly
        decoded ones, exactly as the uninterrupted request would have
        continued (token-identical under greedy decoding).

        ``session`` names a resumable conversation (needs
        :meth:`enable_session_store`): at retirement the request's
        final sequence's full KV blocks persist, content-addressed by
        chain + ``weights_version``, and the conversation's NEXT
        request — whose prompt starts with this one's prompt +
        completion — admits as a chain hit on any engine sharing the
        store, paying a short remainder prefill instead of the whole
        history's."""
        return self._submit_impl(prompt, max_new_tokens, temperature,
                                 top_k, top_p, admit, deadline_ms, None,
                                 tenant, priority, seed=seed,
                                 resume_from=resume_from,
                                 session=session)

    def submit_prefilled(self, prompt: Sequence[int],
                         max_new_tokens: int, kv_blocks, first_token: int,
                         temperature: Optional[float] = None,
                         top_k: Optional[int] = None,
                         top_p: Optional[float] = None,
                         admit: bool = True,
                         deadline_ms: Optional[float] = None,
                         weights_version: Optional[int] = None,
                         tenant: Optional[str] = None,
                         priority=None,
                         submitted_at: Optional[float] = None,
                         seed: Optional[int] = None,
                         resume_from: int = 0) -> int:
        """Queue a request whose prefill ALREADY HAPPENED off-engine —
        the decode half of disaggregated serving. ``kv_blocks`` is the
        prompt's KV state in wire-block form
        (:func:`~elephas_tpu.models.paged_decode.export_kv_blocks`, as
        produced by :meth:`export_prefill` on a prefill worker) and
        ``first_token`` the token its final-position logits emitted.
        Admission installs the shipped blocks into the slot's cache row
        (or paged blocks) between decode steps — the same atomic point
        where ordinary admissions install their own prefill — so the
        request's queue wait is pure decode-stage backlog. Everything
        else (admission bounds, deadlines, sampling overrides for the
        DECODE steps, cancel, results) behaves exactly like
        :meth:`submit`. On a SPECULATIVE engine the shipped blocks are
        the TARGET model's KV (the prefill tier runs target-only);
        admission prefills the draft locally before the first round —
        draft KV never crosses the wire.

        ``weights_version`` stamps which LIVE weight version the KV was
        computed under: admission re-checks it against the engine's
        current version at the moment of install — the caller's own
        gate (the disaggregated front end's) necessarily runs earlier,
        and a hot-swap staged in between would otherwise decode this
        request's whole output over mismatched state. A stale stamp
        falls back to a LOCAL prefill of the prompt (correct output,
        one admission's worth of extra compute on this engine) rather
        than failing the request; ``None`` skips the check.

        ``submitted_at`` is the FRONT END's ``time.monotonic()`` stamp
        of the original client submit: when given, this engine's
        ``serving_ttft_seconds`` measures first-token latency from
        THAT moment — so the prefill tier's queue wait, compute, and
        KV ship time land inside TTFT, where the user experienced
        them — while queue-wait/request-latency series keep measuring
        this engine's own decode stage (the disaggregation headline
        those series exist to isolate)."""
        # shape/coverage validation happens HERE, at submit: a malformed
        # KV payload failing at admission time would raise inside the
        # server's engine loop and read as engine death (500s for
        # everyone) instead of one bad request's 400
        prompt_size = int(np.asarray(prompt).size)
        if isinstance(kv_blocks, dict):
            # prebuilt batch-1 row cache (``import_kv_blocks`` output):
            # a receiver thread can do the block reassembly OFF the
            # engine loop and hand the row in directly — admission then
            # only pays the device install
            blocks = kv_blocks
            if len(blocks) != self.config.num_layers:
                raise ValueError(
                    f"prebuilt KV row must hold {self.config.num_layers}"
                    f" layers, got {len(blocks)}")
            for name, lc in blocks.items():
                for part in ("k", "v"):
                    arr = lc[part]
                    if arr.ndim != 4 or arr.shape[2] < prompt_size:
                        raise ValueError(
                            f"prebuilt KV row {name}/{part} must be "
                            f"(1, heads, >= {prompt_size}, head_dim), "
                            f"got shape {tuple(arr.shape)}")
        else:
            blocks = [np.asarray(b) for b in kv_blocks]
            expected = 2 * self.config.num_layers
            if len(blocks) != expected:
                raise ValueError(f"expected {expected} KV block tensors "
                                 f"(k, v per layer), got {len(blocks)}")
            for b in blocks:
                if b.ndim != 4:
                    raise ValueError(
                        "KV block tensors must be (nblocks, heads, "
                        f"block_size, head_dim), got shape "
                        f"{tuple(b.shape)}")
                if b.shape[0] * b.shape[2] < prompt_size:
                    raise ValueError(
                        f"{b.shape[0]} blocks of {b.shape[2]} positions"
                        f" cannot cover the {prompt_size}-token prompt")
        return self._submit_impl(
            prompt, max_new_tokens, temperature, top_k, top_p, admit,
            deadline_ms,
            (blocks, int(first_token),
             None if weights_version is None else int(weights_version)),
            tenant, priority, submitted_at=submitted_at, seed=seed,
            resume_from=resume_from)

    def _submit_impl(self, prompt, max_new_tokens, temperature, top_k,
                     top_p, admit, deadline_ms, prefilled,
                     tenant=None, priority=None,
                     submitted_at=None, seed=None,
                     resume_from=0, session=None) -> int:
        if (temperature is not None or top_k is not None
                or top_p is not None):
            if self.draft_config is not None:
                raise ValueError("per-request sampling settings are not "
                                 "supported in speculative mode")
        if seed is not None:
            if self.draft_config is not None:
                raise ValueError("per-request seeds are not supported "
                                 "in speculative mode (the accept/"
                                 "resample rule samples every slot "
                                 "from one engine key)")
            seed = int(seed)
            if not 0 <= seed < 2 ** 31:
                raise ValueError(
                    f"seed must be in [0, 2**31), got {seed}")
        validate_sampling_overrides(temperature, top_k, top_p)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        resume_from = int(resume_from)
        if resume_from and not 0 < resume_from < prompt.size:
            raise ValueError(
                f"resume_from ({resume_from}) must leave at least one "
                f"true prompt token below the {prompt.size}-token "
                "prompt (it counts already-emitted output folded into "
                "the prompt's tail)")
        tenant = DEFAULT_TENANT if tenant is None else str(tenant)
        prio = (self.qos.priority(tenant, priority)
                if self.qos is not None
                else TenantQoS._parse_class(
                    "normal" if priority is None else priority))
        self.check_admissible(int(prompt.size), int(max_new_tokens),
                              prompt=prompt, tenant=tenant)
        if deadline_ms is not None and not deadline_ms > 0:
            raise ValueError(f"deadline_ms must be > 0, got {deadline_ms}")
        # expired backlog entries must not hold capacity against a live
        # admission decision
        self._shed_expired_queued()
        if fault_site("serving.submit"):
            # a plan 'drop' here is a deterministic shed: the request is
            # rejected exactly as if the queue were at capacity
            self.record_shed(tenant, "injected")
            raise QueueFullError("admission rejected (injected shed)",
                                 self._retry_after_ms())
        if (self.max_queue is not None
                and len(self._queue) >= self.max_queue):
            self.record_shed(tenant, "max_queue",
                             queue_depth=len(self._queue))
            raise QueueFullError(
                f"queue full: {len(self._queue)} requests backlogged "
                f"(max_queue={self.max_queue})", self._retry_after_ms())
        if (self.max_queued_tokens is not None
                and self._queued_tokens + prompt.size
                > self.max_queued_tokens):
            self.record_shed(tenant, "max_queued_tokens",
                             queued_tokens=self._queued_tokens)
            raise QueueFullError(
                f"queue full: {self._queued_tokens} prompt tokens "
                f"backlogged + {prompt.size} would exceed "
                f"max_queued_tokens={self.max_queued_tokens}",
                self._retry_after_ms())
        try:
            self.check_tenant_admissible(tenant, int(prompt.size))
        except QueueFullError:
            # the per-tenant quota 429: the offender sheds while
            # under-quota tenants keep admitting through the very same
            # submit path
            self.record_shed(tenant, "tenant_quota",
                             tenant_queued_tokens=self._queue
                             .tenant_queued_tokens(tenant))
            raise
        rid = self._next_rid
        self._next_rid += 1
        self._submit_t[rid] = time.monotonic()
        if submitted_at is not None:
            # the front end's own submit stamp: TTFT measures from the
            # moment the CLIENT's request entered the serving stack,
            # not from this engine's (later) decode-stage submit
            self._ttft_origin[rid] = float(submitted_at)
        # capture the submitter's trace context HERE: the engine loop
        # thread that admits/steps/retires this request later runs
        # without it, so the flight recorder stamps every event with
        # the id now, and _admit restores the context per request
        ctx = current_context()
        if ctx is not None:
            # the request's tree root is a CHILD of the submitter's
            # span: every span this engine records for rid (admission
            # wait, spill promote/demote, prefill, decode) parents to
            # the request-root span id, and the root span itself is
            # materialized retroactively at retirement
            self._trace_ctx[rid] = ctx.child()
            ctx = self._trace_ctx[rid]
        self.recorder.start(rid,
                            trace_id=None if ctx is None else ctx.trace_id,
                            prompt_tokens=int(prompt.size),
                            max_new_tokens=int(max_new_tokens),
                            tenant=tenant, priority=prio,
                            **({"prefilled": True} if prefilled is not None
                               else {}))
        if prefilled is not None:
            self._prefilled_kv[rid] = prefilled
        if seed is not None:
            self._seed[rid] = seed
        if session is not None:
            self._session[rid] = str(session)
        if resume_from:
            # ride the preemption-resume machinery: admission pops this
            # entry, pre-seeds the request's outputs with the forced
            # prefix (so result()/streams carry the FULL output and the
            # router's token-index dedupe works), sets _slot_prior, and
            # emits the ``resumed`` flight-recorder event
            self._resume[rid] = {
                "outputs": [int(t) for t in prompt[-resume_from:]],
                "preempts": 0}
        if deadline_ms is not None:
            self._deadline[rid] = self._clock() + deadline_ms / 1000.0
        self._queue.append(QueuedRequest(
            rid, prompt, int(max_new_tokens),
            self.temperature if temperature is None
            else float(temperature),
            0 if top_k is None else int(top_k),
            1.0 if top_p is None else float(top_p), tenant, prio,
            session=None if session is None else str(session)))
        self._queued_tokens += int(prompt.size)
        self._tenant_gauge(tenant)
        if admit:
            self._admit()
        return rid

    def record_shed(self, tenant: str, reason: str,
                    **event_attrs) -> None:
        """Admission-rejection bookkeeping: the global shed counter,
        the per-tenant labeled counter (QoS only), and the
        tenant-stamped ``serving.shed`` event — one helper so every
        shed path tells the same story. Public because front ends that
        enforce this engine's tenant quotas at their own submit (the
        disaggregated engine) owe the same bookkeeping."""
        self._m_shed.inc()
        if self.qos is not None:
            self._m_tenant_shed.labels(
                tenant=self.qos.label(tenant), reason=reason).inc()
        emit_event("serving.shed", reason=reason, tenant=tenant,
                   **event_attrs)

    def _tenant_gauge(self, tenant: str) -> None:
        """Lazily register the ``serving_tenant_queued_tokens`` gauge
        child for ``tenant``'s label (weakref callback over the fair
        queue, the engines' gauge convention). No-op without QoS."""
        if self.qos is None:
            return
        label = self.qos.label(tenant)
        if label in self._tenant_gauge_labels:
            return
        self._tenant_gauge_labels.add(label)
        import weakref

        ref = weakref.ref(self)
        self._m_tenant_queued.labels(tenant=label).set_function(
            lambda label=label: float(
                e._queue.tokens_for_label(label, e.qos))
            if (e := ref()) is not None else 0.0)

    def export_prefill(self, prompt: Sequence[int],
                       temperature: Optional[float] = None,
                       top_k: Optional[int] = None,
                       top_p: Optional[float] = None,
                       block_size: int = 64,
                       seed: Optional[int] = None) -> Dict:
        """Run this engine's prefix-aware prefill path for ``prompt``
        and EXPORT the result instead of occupying a slot — the prefill
        half of disaggregated serving. Rides exactly the machinery an
        ordinary admission uses (``_prefill``/chunked ``decode_block``
        extends, registered-prefix reuse, the engine's sampling rule for
        the first token), so a shipped prefill is token-identical to a
        colocated one.

        Returns ``{"first_token", "kv_blocks", "block_size",
        "prompt_tokens", "prefix_tokens", "prefill_s"}`` where
        ``kv_blocks`` is the host-side block-unit KV export
        (:func:`~elephas_tpu.models.paged_decode.export_kv_blocks`) a
        decode worker feeds to :meth:`submit_prefilled` — directly, or
        over the wire via :mod:`elephas_tpu.disagg`. Not supported on a
        SPECULATIVE engine: draft KV never ships — run the prefill tier
        on plain target-only engines and give the DECODE workers the
        draft (they recompute draft KV at admission)."""
        from .models.paged_decode import export_kv_blocks

        if self.draft_config is not None:
            raise ValueError(
                "export_prefill does not compose with speculative mode:"
                " draft KV never ships — run the prefill tier on plain "
                "(target-only) engines; speculative DECODE workers "
                "accept shipped target KV via submit_prefilled and "
                "recompute draft KV at admission")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must hold at least one token")
        if prompt.size >= self.max_len:
            raise ValueError(f"prompt ({prompt.size}) must leave room "
                             f"below max_len {self.max_len}")
        validate_sampling_overrides(temperature, top_k, top_p)
        temp = (self.temperature if temperature is None
                else float(temperature))
        topk = 0 if top_k is None else int(top_k)
        topp = 1.0 if top_p is None else float(top_p)
        start = time.monotonic()
        cached_tokens = 0
        if self._kv_cache is not None and self.paged is None:
            # the prefill TIER's automatic cache: a repeat prefix skips
            # its prefill compute BEFORE the KV ever hits the wire (the
            # shipped frame is identical either way — the decode side
            # cannot tell a cached export from a computed one)
            logits, row, cached_tokens, reg_used = (
                self._host_cache_prefill(None, prompt))
            prefix_tokens = max(cached_tokens, reg_used)
        else:
            entry = self._match_prefix(prompt)
            if entry is not None:
                self._m_prefix_hits.inc()
                self._m_prefix_tokens.inc(int(entry[0].size))
            logits, row = self._prefill_with_prefixes(
                prompt, self._extend_fn, self._extend_owned_fn,
                self._prefill_fn, self.params, entry, 2,
                self._fresh_row_fn)
            prefix_tokens = 0 if entry is None else int(entry[0].size)
        t0 = self._sample_first(logits, temp, topk, topp, seed=seed,
                                fold=int(prompt.size))
        blocks = export_kv_blocks(row, int(prompt.size), int(block_size))
        return {"first_token": t0, "kv_blocks": blocks,
                "block_size": int(block_size),
                "prompt_tokens": int(prompt.size),
                "prefix_tokens": int(prefix_tokens),
                "cached_tokens": int(cached_tokens),
                # the version this KV was computed under: a disagg
                # decode engine REJECTS a frame whose stamp mismatches
                # its own live version (decoding new-weight steps over
                # old-weight KV is silently wrong output, not a crash)
                "weights_version": int(self.weights_version),
                "prefill_s": round(time.monotonic() - start, 6)}

    def would_shed(self, prompt_tokens: int,
                   tenant: Optional[str] = None) -> bool:
        """Whether a submit of ``prompt_tokens`` would be shed RIGHT NOW
        by the admission bounds (``max_queue`` / ``max_queued_tokens``,
        plus ``tenant``'s per-tenant quotas when given and QoS is
        configured) — the same arithmetic :meth:`submit` applies,
        exposed so front ends (the disaggregated install retry) can
        pre-check without the shed bookkeeping a real rejected submit
        records (counter + event per attempt). Keep in lockstep with
        ``_submit_impl``'s bound checks."""
        if (self.max_queue is not None
                and len(self._queue) >= self.max_queue):
            return True
        if (self.max_queued_tokens is not None
                and self._queued_tokens + int(prompt_tokens)
                > self.max_queued_tokens):
            return True
        if self.qos is not None and tenant is not None:
            try:
                self.check_tenant_admissible(tenant, int(prompt_tokens))
            except QueueFullError:
                return True
        return False

    def check_tenant_admissible(self, tenant: str,
                                prompt_tokens: int) -> None:
        """Raise :class:`QueueFullError` (the HTTP 429) when queueing
        ``prompt_tokens`` for ``tenant`` would breach its per-tenant
        quota — THE shared transient-quota validator: the engine's own
        submit paths and the disaggregated front end both call it, so
        a quota-breached tenant sheds identically at every surface
        while under-quota tenants keep admitting. No-op without a QoS
        config. Callers own the shed bookkeeping (counter + event);
        this only decides."""
        if self.qos is None:
            return
        depth_quota, token_quota = self.qos.quota(tenant)
        if (depth_quota is not None
                and self._queue.tenant_depth(tenant) >= depth_quota):
            raise QueueFullError(
                f"tenant {tenant!r} quota: "
                f"{self._queue.tenant_depth(tenant)} requests "
                f"backlogged (max_queue={depth_quota})",
                self._retry_after_ms(tenant))
        if token_quota is not None:
            queued = self._queue.tenant_queued_tokens(tenant)
            if queued + int(prompt_tokens) > token_quota:
                raise QueueFullError(
                    f"tenant {tenant!r} quota: {queued} prompt tokens "
                    f"backlogged + {int(prompt_tokens)} would exceed "
                    f"max_queued_tokens={token_quota}",
                    self._retry_after_ms(tenant))

    def retry_after_ms(self, tenant: Optional[str] = None) -> int:
        """Public read of the shed-backoff hint a
        :class:`QueueFullError` would carry right now (quota-aware
        when ``tenant`` is given — see :meth:`_retry_after_ms`)."""
        return self._retry_after_ms(tenant)

    def _retry_after_ms(self, tenant: Optional[str] = None) -> int:
        """Backoff hint for a shed request: roughly how long until the
        backlog drains enough to retry, from the median observed request
        latency scaled by the queue's depth relative to slot capacity
        (clamped to a sane window; 100ms before any sample exists).
        With ``tenant`` and a QoS config the depth is the OFFENDING
        tenant's own backlog — a quota 429's hint scales with how far
        over its share that tenant is, not with the global queue."""
        depth = len(self._queue)
        if tenant is not None and self.qos is not None:
            depth = self._queue.tenant_depth(tenant)
        if self._latency_window:
            med = float(np.quantile(
                [t for _, t, _ in self._latency_window], 0.5))
            est = 1000.0 * med * max(1, depth) / self.max_slots
        else:
            est = 100.0
        return int(min(10000.0, max(50.0, est)))

    def cancel(self, rid: int) -> bool:
        """Abort a request: drop it from the queue, or free its slot and
        discard its partial output. Returns whether anything was
        cancelled (False for unknown or already-finished ids —
        :meth:`result` still serves finished ones)."""
        item = self._queue.remove_rid(rid)
        if item is not None:
            self._queued_tokens -= int(item.prompt.size)
            self._submit_t.pop(rid, None)
            self._deadline.pop(rid, None)
            cctx = self._trace_ctx.pop(rid, None)
            if cctx is not None:
                # close the tree (client-initiated, not an SLO story:
                # retained only if it ranks slowest-k, i.e. never
                # without a latency — this is the store's GC path)
                default_span_store().finish(cctx.trace_id)
            self._prefilled_kv.pop(rid, None)
            self._resume.pop(rid, None)
            self._seed.pop(rid, None)
            self._session.pop(rid, None)
            # a preempted-then-re-queued request may still hold an
            # un-surfaced admission token: the next step() must not
            # report tokens for a cancelled rid
            self._fresh.pop(rid, None)
            self._accept.pop(rid, None)
            # a preempted-then-re-queued rid still carries token-time
            # stamps from its first life
            self._ttft_origin.pop(rid, None)
            self._last_tok_t.pop(rid, None)
            self._ttft_val.pop(rid, None)
            self.recorder.record(rid, "cancelled", stage="queued")
            return True
        for slot, st in list(self._pending_prefill.items()):
            if st["rid"] != rid:
                continue
            # mid-interleaved-prefill: the chunks already computed are
            # discarded with the slot's blocks — nothing was emitted yet
            self._abort_pending_prefill(slot)
            self._submit_t.pop(rid, None)
            self._admit_t.pop(rid, None)
            self._deadline.pop(rid, None)
            cctx = self._trace_ctx.pop(rid, None)
            if cctx is not None:
                default_span_store().finish(cctx.trace_id)
            self._seed.pop(rid, None)
            self._session.pop(rid, None)
            self._fresh.pop(rid, None)
            self._accept.pop(rid, None)
            self._ttft_origin.pop(rid, None)
            self._last_tok_t.pop(rid, None)
            self._ttft_val.pop(rid, None)
            self.recorder.record(rid, "cancelled", stage="prefilling")
            return True
        for slot, r in enumerate(self._rid):
            # the explicit None guard matters: a caller holding a
            # None/absent id must not "cancel" a FREE slot (None == None)
            if r is not None and r == rid:
                tokens = len(self._outputs.get(rid, ()))
                self._outputs.pop(rid, None)
                self._fresh.pop(rid, None)
                self._accept.pop(rid, None)
                self._rid[slot] = None
                self._release_blocks(slot)
                self._clear_slot_meta(slot)
                self._submit_t.pop(rid, None)
                self._admit_t.pop(rid, None)
                self._deadline.pop(rid, None)
                cctx = self._trace_ctx.pop(rid, None)
                if cctx is not None:
                    default_span_store().finish(cctx.trace_id)
                self._seed.pop(rid, None)
                self._session.pop(rid, None)
                self._ttft_origin.pop(rid, None)
                self._last_tok_t.pop(rid, None)
                self._ttft_val.pop(rid, None)
                self.recorder.record(rid, "cancelled", stage="decoding",
                                     tokens=tokens)
                return True
        return False

    def _free_slots(self) -> List[int]:
        # a slot mid-interleaved-prefill is reserved, not free: its rid
        # is unset (the decode loop must treat it as inactive) but its
        # blocks/cache row belong to the pending request
        return [s for s in range(self.max_slots)
                if self._rid[s] is None and s not in self._pending_prefill]

    def _shed_expired_queued(self):
        """Drop every queued request whose deadline already passed —
        BEFORE it ever reaches prefill. Each becomes a finished result
        with no tokens, marked ``expired`` (the HTTP layer's 504)."""
        if not self._deadline or not len(self._queue):
            return
        now = self._clock()
        dropped = self._queue.remove_if(
            lambda item: (dl := self._deadline.get(item.rid)) is not None
            and now >= dl)
        for item in dropped:
            rid = item.rid
            self._queued_tokens -= int(item.prompt.size)
            self._deadline.pop(rid, None)
            self._prefilled_kv.pop(rid, None)
            t_sub = self._submit_t.pop(rid, None)
            saved = self._resume.pop(rid, None)
            self._seed.pop(rid, None)
            self._session.pop(rid, None)
            ectx = self._trace_ctx.pop(rid, None)
            if ectx is not None:
                # a deadline miss is exactly the SLO-violating trace
                # the tail-based store exists to keep
                default_span_store().finish(
                    ectx.trace_id,
                    latency_s=(None if t_sub is None
                               else time.monotonic() - t_sub),
                    violated=True)
            self._ttft_origin.pop(rid, None)
            self._last_tok_t.pop(rid, None)
            self._ttft_val.pop(rid, None)
            if saved is not None:
                # preempted mid-decode and the deadline passed while
                # re-queued: the tokens already emitted are the final
                # (partial) output — a mid-decode timeout, not an
                # expired-before-prefill shed
                self._done[rid] = saved["outputs"]
                self._timed_out.add(rid)
                self._m_timed_out.inc()
                a_p = self._accept.pop(rid, None)
                self.recorder.record(
                    rid, "timed_out", stage="preempted_queued",
                    tokens=len(saved["outputs"]),
                    **({} if a_p is None
                       else {"draft_accepted": a_p[0],
                             "draft_proposed": a_p[1]}))
            else:
                self._done[rid] = []
                self._expired.add(rid)
                self._m_expired.inc()
                self.recorder.record(
                    rid, "expired",
                    queue_wait_s=(None if t_sub is None
                                  else round(time.monotonic() - t_sub,
                                             6)))

    def _enforce_active_deadlines(self):
        """Retire every ACTIVE slot whose request deadline passed: the
        slot (and its paged blocks) frees immediately and the tokens
        emitted so far become the final output, marked ``timeout``."""
        if not self._deadline:
            return
        now = self._clock()
        for slot, rid in enumerate(self._rid):
            if rid is None or self._deadline.get(rid, now + 1) > now:
                continue
            # _fresh stays: an admission-time token not yet surfaced by
            # step() still reaches streaming clients on the next call
            self._retire_slot(slot, "timed_out")
            self._timed_out.add(rid)
            self._m_timed_out.inc()

    def _admit(self):
        # profiled as one "admit" section whose nested prefill/swap
        # children are EXCLUDED (the profiler's exclusive accounting),
        # so admission scheduling cost and prefill compute are separate
        # answers on serving_loop_utilization. The steady-decode case —
        # empty queue, nothing staged — skips the sections entirely:
        # _admit runs twice per step, and timing its ~µs no-op as
        # "admit" would double the profiler's per-step cost to
        # attribute time that belongs in idle anyway.
        if self.profiler is None or (not len(self._queue)
                                     and self._staged_params is None
                                     and self._staged_draft is None):
            return self._admit_impl()
        with self.profiler.section("admit"):
            self._admit_impl()

    def _admit_impl(self):
        # a staged live-weight swap lands FIRST — admission prefills
        # must run under the params their requests will decode under
        # (this covers both entry points: step()'s between-decode-steps
        # call and an immediate submit(admit=True) admission)
        if self._staged_params is None and self._staged_draft is None:
            # unlocked peek is safe: a staging racing this read lands
            # on the next step — exactly the contract stage_params has
            self.apply_staged_params()
        else:
            with self._psec("swap"):
                self.apply_staged_params()
        self._shed_expired_queued()
        self._enforce_active_deadlines()
        self._enforce_pending_deadlines()
        while len(self._queue):
            slots = self._free_slots()
            if not slots:
                # every slot busy: a strictly-higher-priority candidate
                # may preempt a lower-priority in-flight decode (QoS
                # with the paged cache only) — otherwise admission
                # waits for a retirement exactly as before
                if not self._maybe_preempt_for(self._queue.peek()):
                    return
                continue
            slot = slots[0]
            if self.paged is not None:
                # allocate BEFORE popping: when the pool is momentarily
                # empty the scheduled candidate simply waits (no
                # overtaking past the fair-queue choice, so no
                # starvation)
                cand = self._queue.peek()
                nxt_rid, nxt_prompt, nxt_max_new = (cand.rid, cand.prompt,
                                                    cand.max_new)
                bsz = self.paged[1]
                # verify slack rides every paged allocation in
                # speculative mode (zero otherwise) — the blocks the
                # rejected-tail writes are confined to
                needed = -(-(nxt_prompt.size + nxt_max_new
                             + self._slack) // bsz)
                hits = []
                promos = []
                if (self._kv_cache is not None
                        and nxt_rid not in self._prefilled_kv):
                    # cached full blocks need no allocation: the slot's
                    # table will POINT at them
                    walk_keys, _ = self._chain_keys_for(nxt_rid,
                                                        nxt_prompt)
                    hits = self._kv_cache.match_chain(walk_keys)
                    # HBM miss != re-prefill: the walk falls through to
                    # the spill tiers / session store. Promoted blocks
                    # DO allocate (they install into private blocks),
                    # so they don't change `needed` below — they trade
                    # the remainder's prefill FLOPs, not its HBM. The
                    # walk's tier reads (a storage GET per key) run
                    # under the candidate's trace context so the spill
                    # layer's spans land on its tree.
                    with use_context(self._trace_ctx.get(nxt_rid)):
                        promos = self._tier_walk(
                            nxt_rid, walk_keys[len(hits):], len(hits),
                            allow_lossy=self._lossy_promote)
                    if hits or promos:
                        # longest registered match still wins: when the
                        # pinned ROW covers more than the block chain
                        # (a sub-block tail, or a partially pinned
                        # prefix), skip the claim and let the classic
                        # registered path serve the whole head — but
                        # ONLY when a full private allocation is
                        # permanently satisfiable. check_admissible
                        # admitted this request crediting its leading
                        # pinned run; dropping the claim while pins
                        # make `needed` private blocks impossible
                        # would wedge the FIFO head forever for a
                        # sub-block tail's worth of reuse.
                        reg = self._match_prefix(nxt_prompt)
                        if (reg is not None and int(reg[0].size)
                                > (len(hits) + len(promos)) * bsz
                                and needed <= self.paged[0] - 1
                                - self._kv_cache.pinned_count()):
                            hits, promos = [], []
                avail = len(self._free_block_ids)
                if self._kv_cache is not None:
                    # parked (zero-ref) cached blocks are reclaimable —
                    # minus any this very admission is about to reuse
                    avail += (self._kv_cache.reclaimable_count()
                              - sum(1 for e in hits
                                    if self._kv_cache.is_parked(e)))
                if avail < needed - len(hits):
                    # pool pressure: a higher-priority candidate may
                    # preempt a lower-priority decode (its blocks park
                    # or free, and the loop re-evaluates availability);
                    # otherwise the candidate keeps its turn and waits
                    if not self._maybe_preempt_for(cand):
                        return
                    continue
                # claim the hit chain FIRST (refcount++, unpark): the
                # remainder allocation below may evict LRU entries and
                # must never reclaim the blocks this request reuses
                for e in hits:
                    self._kv_cache.acquire(e)
                self._slot_cached[slot] = list(hits)
                # demotions this allocation triggers flush as ONE
                # kv_demote event (per-block events would flood the
                # recorder's per-rid cap on a large allocation)
                self._demote_accum = {}
                blocks = [self._alloc_block()
                          for _ in range(needed - len(hits))]
                accum, self._demote_accum = self._demote_accum, None
                if accum.get("staged") or accum.get("blocks"):
                    # demotions bill to the ADMITTING request (its
                    # allocation forced them): flush under its context
                    # as a spill_demote stage span
                    with use_context(self._trace_ctx.get(nxt_rid)), \
                            start_span("serving.kv_demote",
                                       stage="spill_demote"):
                        demoted = self._flush_demotions(accum)
                else:
                    demoted = self._flush_demotions(accum)
                if demoted:
                    self.recorder.record(nxt_rid, "kv_demote",
                                         blocks=demoted)
                    emit_event("serving.kv_demote", rid=nxt_rid,
                               blocks=demoted)
                if promos:
                    self._slot_promos[slot] = promos
                self._slot_blocks[slot] = blocks
                self._tables[slot, :] = 0      # unused entries -> scratch
                self._tables[slot, :needed] = (
                    [e.payload for e in hits] + blocks)
            item = self._queue.pop()
            rid, prompt, max_new = item.rid, item.prompt, item.max_new
            temp, topk, topp = item.temperature, item.top_k, item.top_p
            self._queued_tokens -= int(prompt.size)
            resume = self._resume.pop(rid, None)
            # queue wait ends HERE — prefill compute/compile time below
            # belongs to total latency, not to time-spent-queued
            self._admit_t[rid] = time.monotonic()
            t_sub = self._submit_t.get(rid)
            self.recorder.record(
                rid, "admitted", slot=slot, tenant=item.tenant,
                # the weight version this request will decode under —
                # the flight-recorder half of "which weights served
                # this request" (a mid-decode swap shows up as
                # weights.swapped events between its step events)
                weights_version=self.weights_version,
                queue_wait_s=(None if t_sub is None
                              else round(self._admit_t[rid] - t_sub, 6)),
                # the sampling seed, when pinned — the repro handle: a
                # trace reader can replay THIS request's exact output
                **({"seed": self._seed[rid]}
                   if rid in self._seed else {}))
            if t_sub is not None and rid in self._trace_ctx:
                # queue time as a retroactive stage span: monotonic
                # wait projected back from the current wall clock
                wait_s = self._admit_t[rid] - t_sub
                add_span("serving.admission_wait", time.time() - wait_s,
                         wait_s, stage="admission_wait",
                         ctx=self._trace_ctx[rid])
            # per-request context restore: this loop runs on the engine
            # thread, but prefill (and any span/fault/event it emits)
            # belongs to the request whose context was captured at
            # submit — None for requests submitted without one
            pre = self._prefilled_kv.pop(rid, None)
            with use_context(self._trace_ctx.get(rid)):
                if (pre is not None and len(pre) > 2
                        and pre[2] is not None
                        and int(pre[2]) != int(self.weights_version)):
                    # the shipped KV's weight-version stamp went stale
                    # between the caller's gate and THIS install (a
                    # hot-swap staged in the window): decoding over it
                    # would be silently wrong output. Fall back to a
                    # local prefill — correct, never a failed request,
                    # one admission's worth of extra compute.
                    self.recorder.record(
                        rid, "kv_install_stale",
                        frame_version=int(pre[2]),
                        engine_version=int(self.weights_version),
                        fallback="local_prefill")
                    emit_event("serving.kv_install_stale",
                               frame_version=int(pre[2]),
                               engine_version=int(self.weights_version))
                    pre = None
                if pre is not None:
                    # disaggregated admission: the shipped KV blocks
                    # install straight into the slot (between decode
                    # steps — this loop IS the atomic point); no
                    # prefill compute, no prefix lookup
                    # shipped frames deliberately do NOT seed the
                    # decode-side cache: a pure-disagg decode tier
                    # never walks it for prefilled requests (dead
                    # entries would only inflate eviction churn), and
                    # a Q8 frame's dequantized KV is content-addressed
                    # by TOKENS — letting a later LOCAL admission hit
                    # lossy blocks would break its cache-off parity
                    with self._psec("prefill"), \
                            start_span("serving.kv_install",
                                       stage="prefill"):
                        t0 = self._install_prefilled(slot, prompt, pre)
                    self.recorder.record(
                        rid, "kv_install",
                        prompt_tokens=int(prompt.size),
                        duration_s=round(
                            time.monotonic() - self._admit_t[rid], 6))
                else:
                    if self._interleave_ok(slot, prompt):
                        # defer the chunk loop: the slot is reserved
                        # (blocks allocated, hit chain claimed) but its
                        # prompt feeds between the coming decode steps
                        # — _interleave_prefills() finishes the
                        # admission when the last chunk lands
                        self._begin_interleaved_prefill(
                            rid, slot, item, prompt, resume, temp,
                            topk, topp)
                        continue
                    with self._psec("prefill"), \
                            start_span("serving.prefill",
                                       stage="prefill"):
                        t0 = self._admit_prefill(rid, slot, prompt,
                                                 temp, topk, topp)
            self._rid[slot] = rid
            # a RESUMED request keeps the tokens it emitted before its
            # preemption — the new first token (sampled from the full
            # resubmitted sequence's final-position logits) is exactly
            # the next token the never-preempted decode would emit
            self._outputs[rid] = ([] if resume is None
                                  else resume["outputs"])
            self._slot_prompt[slot] = prompt
            self._slot_prior[slot] = len(self._outputs[rid])
            self._slot_tenant[slot] = item.tenant
            self._slot_priority[slot] = item.priority
            self._slot_wv[slot] = self.weights_version
            self._pos[slot] = prompt.size - 1
            self._last[slot] = t0
            self._budget[slot] = max_new
            self._temp[slot] = temp
            self._topk[slot] = topk
            self._topp[slot] = topp
            self._slot_seed[slot] = self._seed.get(rid, -1)
            if self.qos is not None:
                self._m_tenant_admitted.labels(
                    tenant=self.qos.label(item.tenant)).inc()
            if resume is not None:
                self.recorder.record(
                    rid, "resumed", tokens_so_far=len(self._outputs[rid]),
                    remaining_tokens=int(max_new),
                    preemptions=resume["preempts"])
            if self._record(slot, t0):
                # surfaced by the next step(); append — a preempted-
                # and-resumed request may still owe its PREVIOUS
                # admission's un-surfaced first token
                self._fresh.setdefault(rid, []).append(t0)

    # --------------------------------------------------------- preemption
    @property
    def _preempt_enabled(self) -> bool:
        """Preemption needs somewhere cheap to PARK the victim's KV:
        the paged pool + block cache (park = release to LRU, resume =
        chain-walk reclaim). QoS on other engine shapes still gets
        fair queueing and quotas, never preemption."""
        return (self.qos is not None and self.qos.preempt
                and self.paged is not None
                and self._kv_cache is not None)

    def _maybe_preempt_for(self, cand) -> bool:
        """Preempt ONE in-flight decode of strictly lower priority
        than queued candidate ``cand`` (lowest class first; among
        equals the slot with the fewest emitted tokens — the cheapest
        resume). Returns whether a victim was preempted; the admission
        loop re-evaluates capacity after each one."""
        if cand is None or not self._preempt_enabled:
            return False
        victim = None
        for slot, rid in enumerate(self._rid):
            if rid is None:
                continue
            prio = int(self._slot_priority[slot])
            if prio >= int(cand.priority):
                continue
            key = (prio, len(self._outputs.get(rid, ())))
            if victim is None or key < victim[0]:
                victim = (key, slot)
        if victim is None:
            return False
        self._preempt_slot(victim[1])
        return True

    def _preempt_slot(self, slot: int) -> None:
        """Evict the slot's request mid-decode, parking its KV: every
        full block of the sequence decoded so far enters the block
        cache (release → LRU — resident but reclaimable, exactly like
        a retired request's shared prefix), and the request re-queues
        at the FRONT of its tenant lane with prompt = original prompt
        + tokens emitted so far and budget = what remains. On
        re-admission the chain walk reclaims the parked blocks, so
        resume costs a short remainder prefill, not a recompute — and
        greedy output is token-identical to the never-preempted run.

        ``serving.preempt`` fault site: ``delay`` = a slow park,
        ``drop``/``error`` = the parking path failing — the blocks
        free instead of parking and the request still re-queues
        (resume recomputes; a preemption fault may cost compute, never
        the request)."""
        rid = self._rid[slot]
        tenant = self._slot_tenant[slot] or DEFAULT_TENANT
        priority = int(self._slot_priority[slot])
        prompt = self._slot_prompt[slot]
        outputs = self._outputs.pop(rid)
        remaining = int(self._budget[slot])
        # only the tokens emitted SINCE this slot's admission extend
        # the prompt — a resumed request's prompt already folds in its
        # pre-preemption output (_slot_prior), and re-appending it
        # would corrupt the sequence on a second preemption
        seq = np.concatenate(
            [prompt, np.asarray(outputs[int(self._slot_prior[slot]):],
                                np.int32)])
        parked = 0
        try:
            if fault_site("serving.preempt"):
                raise InjectedFault("injected preempt-park drop")
            # KV through position _pos[slot] is on device: park its
            # full blocks (the pending last token was never processed,
            # so the parked chain covers seq[:-1])
            parked = self._park_slot_blocks(
                slot, seq[:int(self._pos[slot]) + 1])
        except InjectedFault:
            parked = 0     # park failed: blocks free below instead —
            # the resume recomputes the prefix, the request survives
        resume = self._resume.get(rid)
        preempts = 1 + (0 if resume is None else resume["preempts"])
        self._rid[slot] = None
        self._release_blocks(slot)
        self._clear_slot_meta(slot)
        self._admit_t.pop(rid, None)
        if self._chain_memo is not None and self._chain_memo[0] == rid:
            # the resume prompt differs from the one this rid's memo
            # hashed — a stale memo would walk the wrong chain
            self._chain_memo = None
        if self._promo_memo is not None and self._promo_memo[0] == rid:
            self._promo_memo = None
        self._resume[rid] = {"outputs": outputs, "preempts": preempts}
        self._queue.appendleft(QueuedRequest(
            rid, seq, remaining, float(self._temp[slot]),
            int(self._topk[slot]), float(self._topp[slot]), tenant,
            priority, session=self._session.get(rid)))
        self._queued_tokens += int(seq.size)
        self._m_preemptions.inc()
        if self.qos is not None:
            self._m_tenant_preempt.labels(
                tenant=self.qos.label(tenant)).inc()
        self.recorder.record(rid, "preempted", tokens=len(outputs),
                             parked_blocks=parked,
                             remaining_tokens=remaining)
        emit_event("serving.preempted", rid=rid, tenant=tenant,
                   tokens=len(outputs), parked_blocks=parked)

    def _park_slot_blocks(self, slot: int, seq_kv: np.ndarray) -> int:
        """Move the slot's PRIVATE full blocks over ``seq_kv`` (the
        tokens whose KV the slot holds) into the block cache, keyed by
        the sequence's chain — un-referenced, so they park on the LRU
        immediately: resident for the resume's walk, reclaimable under
        pool pressure like any cold prefix. Blocks whose chain key is
        already cached (admission-time hits/inserts) stay where they
        are — :meth:`_release_blocks` parks those via their refcounts.
        Returns how many blocks parked here."""
        if int(self._slot_wv[slot]) != int(self.weights_version):
            # a hot-swap landed mid-decode: this KV was (partly)
            # computed under other weights — parking it under the
            # CURRENT version's chain keys would serve stale state to
            # a post-swap admission. Free instead of park.
            return 0
        if self._slot_lossy[slot]:
            # lossy-tainted slot (admitted over a dequantized promoted
            # block): same parity rule as _insert_full_blocks — free,
            # never park under chain keys
            return 0
        from .models.block_cache import chain_keys

        bs = self._kv_cache_bs
        nfull = seq_kv.size // bs
        if nfull == 0:
            return 0
        keys = chain_keys(seq_kv[:nfull * bs], bs, self.weights_version)
        private = set(self._slot_blocks[slot])
        parked = 0
        for i, key in enumerate(keys):
            if self._kv_cache.get(key) is not None:
                continue
            bid = int(self._tables[slot, i])
            if bid not in private:
                continue           # shared under a different key: leave
            self._kv_cache.insert(key, bid, (i + 1) * bs)
            self._slot_blocks[slot].remove(bid)
            private.discard(bid)
            parked += 1
        return parked

    def _clear_slot_meta(self, slot: int) -> None:
        self._slot_prompt[slot] = None
        self._slot_prior[slot] = 0
        self._slot_tenant[slot] = None
        self._slot_priority[slot] = 0
        self._slot_wv[slot] = 0
        self._slot_seed[slot] = -1
        self._slot_lossy[slot] = False
        self._slot_promos.pop(slot, None)

    def _admit_prefill(self, rid: int, slot: int, prompt: np.ndarray,
                       temp: float, topk: int, topp: float) -> int:
        """The colocated admission body: prefix-aware prefill on THIS
        engine, slot install, first-token sample. Runs under the
        request's restored trace context (the caller's ``use_context``)."""
        if self._kv_cache is not None:
            if self.paged is not None:
                return self._admit_prefill_paged_cached(
                    rid, slot, prompt, temp, topk, topp)
            logits, row, reused, reg_used = self._host_cache_prefill(
                rid, prompt)
            self.cache = self._install_fn(self.cache, row, slot)
            if self.draft_config is not None:
                # the cache served (some of) the TARGET's prefill; the
                # draft's KV is never cached and recomputes in full
                self._install_draft_row(slot, prompt)
            t0 = self._sample_first(logits, temp, topk, topp,
                                    seed=self._seed.get(rid),
                                    fold=int(prompt.size))
            self.recorder.record(
                rid, "prefill", prompt_tokens=int(prompt.size),
                prefix_tokens=max(reused, reg_used),
                duration_s=round(
                    time.monotonic() - self._admit_t[rid], 6))
            return t0
        # exact-length prefill: one compile per distinct prompt
        # length (an online server batches by length bucket
        # upstream if compile churn matters); a registered-
        # prefix hit reuses the prefix's cached k/v and
        # prefills only the suffix
        entry = self._match_prefix(prompt)
        if entry is not None:
            self._m_prefix_hits.inc()
            self._m_prefix_tokens.inc(int(entry[0].size))
        logits, row_cache = self._prefill_with_prefixes(
            prompt, self._extend_fn, self._extend_owned_fn,
            self._prefill_fn, self.params, entry, 2,
            self._fresh_row_fn)
        if self.paged is not None:
            from .models.paged_decode import install_row_paged

            nprefill = -(-prompt.size // self.paged[1])
            self.pool = install_row_paged(
                self.pool, row_cache, self._tables[slot], nprefill)
        else:
            self.cache = self._install_fn(self.cache, row_cache,
                                          slot)
        if self.draft_config is not None:
            self._install_draft_row(slot, prompt, entry=entry)
        t0 = self._sample_first(logits, temp, topk, topp,
                                seed=self._seed.get(rid),
                                fold=int(prompt.size))
        self.recorder.record(
            rid, "prefill", prompt_tokens=int(prompt.size),
            prefix_tokens=(0 if entry is None else int(entry[0].size)),
            duration_s=round(time.monotonic() - self._admit_t[rid], 6))
        return t0

    def _admit_prefill_paged_cached(self, rid: int, slot: int,
                                    prompt: np.ndarray, temp: float,
                                    topk: int, topp: float) -> int:
        """Paged admission with the automatic block cache: the hit
        chain (claimed by ``_admit`` — its blocks are ALREADY the head
        of the slot's table, pure pointer install) is gathered into a
        row head, only the remainder prefills, and the freshly
        computed full blocks register in the cache so the next
        same-head request hits. Zero hits degrades to the classic
        prefix-aware full prefill (plus the cache insert)."""
        from .models.paged_decode import (gather_blocks_to_row,
                                          install_row_paged)

        cache, bs = self._kv_cache, self._kv_cache_bs
        # COUNT of device hits, not the list: _install_promotions
        # appends the promoted entries to _slot_cached[slot] (they are
        # cache-registered, slot-referenced blocks from then on), so
        # the live list grows past the device-hit prefix
        nhits = len(self._slot_cached[slot])
        promos = self._slot_promos.pop(slot, [])
        walk_keys, _ = self._chain_keys_for(rid, prompt)
        nprefill = -(-prompt.size // bs)
        if promos:
            # spilled/session blocks claimed by _admit's tier walk:
            # one host->device copy each into the already-allocated
            # table entries just past the device hits, then the chain
            # continues exactly as if they had been device hits
            self._install_promotions(rid, slot, nhits, promos)
        j = nhits + len(promos)
        if (self._session.get(rid) is not None
                and self._m_session_hits is not None and walk_keys):
            # resume observability: did this session-tagged admission
            # find ANY of its chain (device, spill, or session tier)?
            (self._m_session_hits if j > 0
             else self._m_session_misses).inc()
        if j > 0:
            reused = j * bs
            self._m_kv_hits.inc()
            self._m_prefix_tokens.inc(reused)
            cache.record_walk(j, True)
            self.recorder.record(rid, "kv_cache_hit", blocks=j,
                                 tokens_reused=reused,
                                 promoted=len(promos))
            row = gather_blocks_to_row(
                self.pool,
                [int(b) for b in self._tables[slot, :j]],
                self.max_len)
            logits, row = self._extend_remainder(row, prompt, reused)
        else:
            # classic path, registered row included (longest match
            # wins — _admit skips the chain claim when the pinned row
            # covers more than the cached chain)
            entry = self._match_prefix(prompt)
            reused = 0
            if entry is not None:
                self._m_prefix_hits.inc()
                self._m_prefix_tokens.inc(int(entry[0].size))
                reused = int(entry[0].size)
            elif walk_keys:
                # a registered-row-served admission is the PINNING
                # layer's reuse (counted just above), not a cache miss
                self._m_kv_misses.inc()
                cache.record_walk(0, True)
            logits, row = self._prefill_with_prefixes(
                prompt, self._extend_fn, self._extend_owned_fn,
                self._prefill_fn, self.params, entry, 2,
                self._fresh_row_fn)
        # install ONLY the remainder blocks: positions [j*bs, ...) —
        # the shared head blocks already hold their positions and other
        # slots may be reading them this very step
        self.pool = install_row_paged(self.pool, row,
                                      self._tables[slot], nprefill,
                                      start=j)
        self._insert_full_blocks(slot, prompt, skip=j, rid=rid)
        if self.draft_config is not None:
            # speculative paged admission: the chain hit (or miss) above
            # served the TARGET cache only — the draft recomputes its
            # whole-prompt KV into its contiguous cache
            self._install_draft_row(slot, prompt)
        t0 = self._sample_first(logits, temp, topk, topp,
                                seed=self._seed.get(rid),
                                fold=int(prompt.size))
        self.recorder.record(
            rid, "prefill", prompt_tokens=int(prompt.size),
            # whichever layer served: the chain's blocks or the
            # registered row (the classic path stamps the same field)
            prefix_tokens=int(reused),
            duration_s=round(time.monotonic() - self._admit_t[rid], 6))
        return t0

    # ------------------------------------------- interleaved prefill
    def _interleave_ok(self, slot: int, prompt: np.ndarray) -> bool:
        """Should THIS admission's chunk loop defer between decode
        steps? Only worth it when decodes are actually in flight (an
        empty engine prefills fastest run-to-completion) and more than
        one chunk of compute remains after prefix/cache reuse. The
        contiguous host-cache path stays run-to-completion: its payload
        import and insert steps are woven through the compute. The
        decision never affects output tokens — both paths feed
        identical chunk shapes — only who waits for whom."""
        if (not self.interleave_prefill
                or not any(r is not None for r in self._rid)):
            return False
        if self.paged is None and self._kv_cache is not None:
            return False
        if self.paged is not None and self._kv_cache is not None:
            est = (len(self._slot_cached[slot])
                   + len(self._slot_promos.get(slot, []))
                   ) * self._kv_cache_bs
            if est == 0:
                entry = self._match_prefix(prompt)
                est = 0 if entry is None else int(entry[0].size)
        else:
            entry = self._match_prefix(prompt)
            est = 0 if entry is None else int(entry[0].size)
        return prompt.size - est > self.prefill_chunk

    def _begin_interleaved_prefill(self, rid: int, slot: int, item,
                                   prompt: np.ndarray, resume,
                                   temp: float, topk: int,
                                   topp: float) -> None:
        """The front half of admission, minus the chunk loop: claim
        whatever serves the prompt head (cache-hit chain, tier
        promotions, or a registered prefix row) exactly as the
        run-to-completion paths do, then park the admission as pending
        state for :meth:`_interleave_prefills` to advance. The slot's
        table resets to the scratch sink while pending — inactive
        slots' decode-step garbage writes land on block 0, and this
        slot's REAL blocks (some shared with live decodes via the
        cache) must not take them."""
        reused, j, entry, row, owned = 0, 0, None, None, True
        if self.paged is not None and self._kv_cache is not None:
            from .models.paged_decode import gather_blocks_to_row

            bs = self._kv_cache_bs
            nhits = len(self._slot_cached[slot])
            promos = self._slot_promos.pop(slot, [])
            walk_keys, _ = self._chain_keys_for(rid, prompt)
            if promos:
                self._install_promotions(rid, slot, nhits, promos)
            j = nhits + len(promos)
            if (self._session.get(rid) is not None
                    and self._m_session_hits is not None and walk_keys):
                (self._m_session_hits if j > 0
                 else self._m_session_misses).inc()
            if j > 0:
                reused = j * bs
                self._m_kv_hits.inc()
                self._m_prefix_tokens.inc(reused)
                self._kv_cache.record_walk(j, True)
                self.recorder.record(rid, "kv_cache_hit", blocks=j,
                                     tokens_reused=reused,
                                     promoted=len(promos))
                row = gather_blocks_to_row(
                    self.pool,
                    [int(b) for b in self._tables[slot, :j]],
                    self.max_len)
            else:
                entry = self._match_prefix(prompt)
                if entry is not None:
                    self._m_prefix_hits.inc()
                    self._m_prefix_tokens.inc(int(entry[0].size))
                    reused = int(entry[0].size)
                elif walk_keys:
                    self._m_kv_misses.inc()
                    self._kv_cache.record_walk(0, True)
        else:
            entry = self._match_prefix(prompt)
            if entry is not None:
                self._m_prefix_hits.inc()
                self._m_prefix_tokens.inc(int(entry[0].size))
                reused = int(entry[0].size)
        if row is None:
            row = (self._fresh_row_fn() if entry is None else entry[2])
            owned = entry is None
        self._pending_prefill[slot] = dict(
            rid=rid, item=item, resume=resume, prompt=prompt,
            temp=temp, topk=topk, topp=topp, row=row,
            suffix=prompt[int(reused):], cursor=0, first=True,
            owned=owned, entry=entry, logits=None, reused=int(reused),
            j=j, wv0=int(self.weights_version),
            table=(self._tables[slot].copy()
                   if self.paged is not None else None),
            t0=time.monotonic(), ctx=self._trace_ctx.get(rid))
        if self.paged is not None:
            self._tables[slot, :] = 0

    def _refresh_prefill_budget(self) -> None:
        """Recompute the chunks-per-iteration budget from the
        profiler's decode-phase share of wall time, every
        :data:`PREFILL_BUDGET_EVERY` iterations (``utilization()``
        walks the ring under a lock — reading it per step would spend
        the profiler's <2% overhead budget on the scheduler). Decode
        saturating the loop → 1 chunk/step (in-flight inter-token
        latency wins); decode mostly waiting → up to
        :data:`MAX_INTERLEAVE_CHUNKS` (drain the prompt, TTFT wins).
        Profiler off → the conservative 1."""
        self._budget_age += 1
        if self._budget_age < PREFILL_BUDGET_EVERY:
            return
        self._budget_age = 0
        if self.profiler is None:
            self._prefill_budget = 1
            return
        decode = self.profiler.utilization().get("decode", 0.0)
        self._prefill_budget = max(
            1, int(round((1.0 - decode) * MAX_INTERLEAVE_CHUNKS)))

    def _interleave_prefills(self) -> None:
        """Advance pending interleaved prefills by at most the current
        chunk budget (total, across pending slots — oldest first, so
        the earliest admission reaches its first token soonest), and
        complete any whose last chunk landed."""
        self._refresh_prefill_budget()
        budget = self._prefill_budget
        for slot in list(self._pending_prefill):
            while budget > 0:
                budget -= 1
                if self._feed_prefill_chunk(slot):
                    self._finish_interleaved_prefill(slot)
                    break
            if budget <= 0:
                return

    def _feed_prefill_chunk(self, slot: int) -> bool:
        """Feed ONE ``prefill_chunk``-sized block of the pending
        prompt. Chunk boundaries and fn choice (the first chunk over a
        registered row must not donate it) mirror
        :meth:`_extend_chunked` exactly, so the interleaved admission
        computes the identical program sequence — identical compiles,
        identical logits — as run-to-completion, just spread across
        iterations. Returns True when the suffix is exhausted."""
        st = self._pending_prefill[slot]
        suffix, cur = st["suffix"], st["cursor"]
        blk = suffix[cur:cur + self.prefill_chunk]
        fn = (self._extend_owned_fn if (st["owned"] or not st["first"])
              else self._extend_fn)
        with use_context(st["ctx"]):
            st["logits"], st["row"] = fn(
                self.params, st["row"], jnp.asarray(blk[None]),
                jnp.int32(st["reused"] + cur))
        st["cursor"] = cur + int(blk.size)
        st["first"] = False
        self._m_interleaved.inc()
        return st["cursor"] >= suffix.size

    def _finish_interleaved_prefill(self, slot: int) -> None:
        """The back half of admission, once every chunk has fed:
        install the finished row, register fresh cache blocks, draft
        prefill, first-token sample, and all the slot bookkeeping the
        run-to-completion path does inline."""
        st = self._pending_prefill.pop(slot)
        rid, prompt, item = st["rid"], st["prompt"], st["item"]
        resume = st["resume"]
        with use_context(st["ctx"]):
            if self.paged is not None:
                from .models.paged_decode import install_row_paged

                self._tables[slot] = st["table"]
                nprefill = -(-prompt.size // self.paged[1])
                self.pool = install_row_paged(
                    self.pool, st["row"], self._tables[slot], nprefill,
                    start=st["j"])
                # a weight swap landed mid-pendency: the row mixes KV
                # from two versions — registering it under the NEW
                # version's chain keys would poison the cache
                if (self._kv_cache is not None
                        and int(self.weights_version) == st["wv0"]):
                    self._insert_full_blocks(slot, prompt,
                                             skip=st["j"], rid=rid)
            else:
                self.cache = self._install_fn(self.cache, st["row"],
                                              slot)
            if self.draft_config is not None:
                if self.paged is not None and self._kv_cache is not None:
                    self._install_draft_row(slot, prompt)
                else:
                    self._install_draft_row(slot, prompt,
                                            entry=st["entry"])
            t0 = self._sample_first(st["logits"][0], st["temp"],
                                    st["topk"], st["topp"],
                                    seed=self._seed.get(rid),
                                    fold=int(prompt.size))
            now = time.monotonic()
            if st["ctx"] is not None:
                # the prefill stage span, retroactive: begin-to-finish
                # wall time — the interleaved decode steps inside it
                # are exactly the graceful-TTFT trade the scheduler made
                dur = now - st["t0"]
                add_span("serving.prefill", time.time() - dur, dur,
                         stage="prefill", interleaved=True,
                         ctx=st["ctx"])
            self.recorder.record(
                rid, "prefill", prompt_tokens=int(prompt.size),
                prefix_tokens=st["reused"], interleaved=True,
                duration_s=round(now - self._admit_t[rid], 6))
        self._rid[slot] = rid
        self._outputs[rid] = [] if resume is None else resume["outputs"]
        self._slot_prompt[slot] = prompt
        self._slot_prior[slot] = len(self._outputs[rid])
        self._slot_tenant[slot] = item.tenant
        self._slot_priority[slot] = item.priority
        # the version the row was (mostly) computed under — a
        # mid-pendency swap leaves this != weights_version, which the
        # park/persist guards already treat as "do not cache"
        self._slot_wv[slot] = st["wv0"]
        self._pos[slot] = prompt.size - 1
        self._last[slot] = t0
        self._budget[slot] = item.max_new
        self._temp[slot] = st["temp"]
        self._topk[slot] = st["topk"]
        self._topp[slot] = st["topp"]
        self._slot_seed[slot] = self._seed.get(rid, -1)
        if self.qos is not None:
            self._m_tenant_admitted.labels(
                tenant=self.qos.label(item.tenant)).inc()
        if resume is not None:
            self.recorder.record(
                rid, "resumed", tokens_so_far=len(self._outputs[rid]),
                remaining_tokens=int(item.max_new),
                preemptions=resume["preempts"])
        if self._record(slot, t0):
            self._fresh.setdefault(rid, []).append(t0)

    def _abort_pending_prefill(self, slot: int) -> Dict:
        """Drop a pending interleaved prefill (cancel/deadline): the
        slot's table restores so its private blocks free and its
        claimed hit chain releases, exactly like an active slot's
        teardown. Returns the pending state for the caller's
        request-level bookkeeping."""
        st = self._pending_prefill.pop(slot)
        if self.paged is not None:
            self._tables[slot] = st["table"]
        self._release_blocks(slot)
        self._clear_slot_meta(slot)
        return st

    def _enforce_pending_deadlines(self) -> None:
        """Retire pending interleaved prefills whose deadline passed —
        the mid-prefill mirror of :meth:`_enforce_active_deadlines`
        (``timed_out``: the request WAS admitted; a preempted-resumed
        one keeps its earlier tokens as the partial output)."""
        if not self._deadline or not self._pending_prefill:
            return
        now = self._clock()
        for slot in list(self._pending_prefill):
            rid = self._pending_prefill[slot]["rid"]
            if self._deadline.get(rid, now + 1) > now:
                continue
            st = self._abort_pending_prefill(slot)
            saved = st["resume"]
            self._done[rid] = ([] if saved is None
                               else saved["outputs"])
            self._timed_out.add(rid)
            self._m_timed_out.inc()
            self._deadline.pop(rid, None)
            self._seed.pop(rid, None)
            self._session.pop(rid, None)
            t_sub = self._submit_t.pop(rid, None)
            self._admit_t.pop(rid, None)
            self._fresh.pop(rid, None)
            a_p = self._accept.pop(rid, None)
            ctx = self._trace_ctx.pop(rid, None)
            if ctx is not None:
                default_span_store().finish(
                    ctx.trace_id,
                    latency_s=(None if t_sub is None
                               else time.monotonic() - t_sub),
                    violated=True)
            self._ttft_origin.pop(rid, None)
            self._last_tok_t.pop(rid, None)
            self._ttft_val.pop(rid, None)
            self.recorder.record(
                rid, "timed_out", stage="prefilling",
                tokens=len(self._done[rid]),
                **({} if a_p is None
                   else {"draft_accepted": a_p[0],
                         "draft_proposed": a_p[1]}))

    def _install_promotions(self, rid: int, slot: int, start: int,
                            promos: List) -> None:
        """Install tier-walk promotions into the slot's table entries
        ``start..start+len(promos)`` (private blocks _admit allocated):
        one batched host->device scatter, then per block — LOSSLESS
        payloads re-register under their chain key (device copy is
        exact content again; the next same-chain admission device-hits)
        while LOSSY ones stay private and taint the slot (parity rule:
        nothing computed over dequantized KV may ever enter the cache,
        park, or persist)."""
        from .models.paged_decode import install_pool_blocks

        cache = self._kv_cache
        with start_span("serving.kv_promote", stage="spill_promote",
                        blocks=len(promos)):
            bids = [int(self._tables[slot, start + i])
                    for i in range(len(promos))]
            self.pool = install_pool_blocks(
                self.pool, [blk.payload for blk, _ in promos], bids)
            tiers: Dict[str, int] = {}
            for (blk, src), bid in zip(promos, bids):
                tiers[src] = tiers.get(src, 0) + 1
                if self._m_spill_promote is not None:
                    self._m_spill_promote.labels(tier=src).inc()
                if blk.lossy:
                    self._slot_lossy[slot] = True
                elif cache.get(blk.key) is None:
                    # guard against a duplicate registered between walk
                    # and install (another admission prefilled the same
                    # chain): insert raises on duplicates — keep ours
                    # private then, mirroring _insert_full_blocks
                    entry = cache.insert(blk.key, bid, blk.tokens,
                                         acquire=True)
                    self._slot_blocks[slot].remove(bid)
                    self._slot_cached[slot].append(entry)
                if self._kv_spill is not None:
                    # device is canonical again: drop the host copy
                    # (re-eviction re-demotes); storage copies stay as
                    # the cross-replica durability layer
                    self._kv_spill.consumed(blk.key)
        self._promo_memo = None
        self.recorder.record(rid, "kv_promote", blocks=len(promos),
                             tiers=tiers)
        emit_event("serving.kv_promote", rid=rid, blocks=len(promos),
                   tiers=tiers)

    def _install_draft_row(self, slot: int, prompt: np.ndarray,
                           entry=...) -> None:
        """Prefill the DRAFT model's KV for ``prompt`` and install it
        into the slot's contiguous draft cache — the admission step
        speculative mode adds on every admission path: the classic
        prefill (which passes its already-matched ``entry``) and every
        path where the TARGET's prefill was (partly) served from
        elsewhere: a prefix-cache hit, a shipped disaggregated frame.
        Draft KV is proposer-private state — never cached, shipped, or
        paged — so it is recomputed here under the CURRENT draft
        params, which also means no admission can ever decode over
        draft state from an older draft version. A registered prefix's
        draft row still serves as the head (``entry`` is the
        ``_match_prefix`` result; ``...`` = look it up here)."""
        if entry is ...:
            entry = self._match_prefix(prompt)
        _, d_row = self._prefill_with_prefixes(
            prompt, self._extend_draft_fn, self._extend_draft_owned_fn,
            self._prefill_draft_fn, self.draft_params, entry, 3,
            self._fresh_draft_row_fn)
        self.draft_cache = self._install_draft_fn(self.draft_cache,
                                                  d_row, slot)

    def _sample_first(self, logits, temp: float, topk: int,
                      topp: float, seed: Optional[int] = None,
                      fold: int = 0) -> int:
        """Sample the admission-time first token from final-position
        prefill logits ``(vocab,)`` — the host-side mirror of the step
        fns' ``_sample_tok`` (same filter order: temperature scales,
        then top-k/top-p on the scaled logits). A per-request ``seed``
        derives the key from ``fold_in(PRNGKey(seed), fold)`` where
        ``fold`` is the sampled token's absolute sequence position —
        the same rule the step fns use, so a resumed request's
        admission token re-samples exactly what the original decode
        emitted at that position."""
        if temp > 0:
            if seed is not None:
                sub = jax.random.fold_in(jax.random.PRNGKey(int(seed)),
                                         int(fold))
            else:
                self._key, sub = jax.random.split(self._key)
            filt = _filter_logits_rows(
                logits[None] / temp,
                jnp.asarray([topk], jnp.int32),
                jnp.asarray([topp], jnp.float32))[0]
            return int(jax.random.categorical(sub, filt))
        return int(jnp.argmax(logits))

    def _install_prefilled(self, slot: int, prompt: np.ndarray,
                           pre: Tuple) -> int:
        """Install shipped KV blocks into ``slot`` and return the
        prefill worker's first token. The imported row is cast to the
        cache dtype, so an fp32-wire transfer installs cleanly into a
        bf16 decode cache."""
        from .models.paged_decode import (import_kv_blocks,
                                          install_row_paged)

        blocks, t0 = pre[0], pre[1]   # pre[2] (version stamp) is the
        # caller's/_admit's concern — checked before this install runs
        if isinstance(blocks, dict):
            row_np = blocks        # prebuilt off-loop by the receiver
        else:
            row_np = import_kv_blocks(blocks, int(prompt.size),
                                      self.max_len)
        row = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a, self.config.dtype), row_np)
        if self.paged is not None:
            nprefill = -(-prompt.size // self.paged[1])
            self.pool = install_row_paged(self.pool, row,
                                          self._tables[slot], nprefill)
        else:
            self.cache = self._install_fn(self.cache, row, slot)
        if self.draft_config is not None:
            # disaggregated speculative decode: the shipped frame holds
            # TARGET KV only — prefill the draft locally BEFORE the
            # first draft round (draft KV never crosses the wire)
            self._install_draft_row(slot, prompt)
        return int(t0)

    def _record(self, slot: int, tok: int) -> bool:
        """Book one emitted token for the slot's request; retire the
        request when it hits eos or exhausts its budget. Returns whether
        the token is part of the output (eos is not — and is therefore
        never streamed either, keeping step() ≡ result())."""
        rid = self._rid[slot]
        if self.eos_id is not None and tok == self.eos_id:
            self._finish(slot)
            return False
        self._outputs[rid].append(tok)
        self._m_emitted.inc()
        # latency decomposition, off HOST state only (a flight-recorder
        # eviction must never cost a histogram sample): the request's
        # FIRST token stamps TTFT — against the front-end submit time
        # when one was passed through (disagg) — and every later token
        # stamps the gap since its predecessor. A resumed preempted
        # request keeps its stamp history (rid-keyed), so its
        # preemption gap lands in the inter-token tail, which is
        # exactly what its client experienced.
        now_tok = time.monotonic()
        last_tok = self._last_tok_t.get(rid)
        if last_tok is None:
            origin = self._ttft_origin.get(rid)
            if origin is None:
                origin = self._submit_t.get(rid)
            if origin is not None:
                ctx = self._trace_ctx.get(rid)
                ttft = now_tok - origin
                self._m_ttft.observe(
                    ttft, trace_id=None if ctx is None
                    else ctx.trace_id)
                self._ttft_val[rid] = ttft
        else:
            self._m_inter_token.observe(now_tok - last_tok)
        self._last_tok_t[rid] = now_tok
        n = len(self._outputs[rid])
        if n % self.TRACE_STEP_EVERY == 0:
            # sampled decode progress on the flight recorder: enough to
            # see a request advancing (or stalled) without one event
            # per token
            self.recorder.record(rid, "step", tokens=n,
                                 pos=int(self._pos[slot]))
        self._budget[slot] -= 1
        if self._budget[slot] <= 0:
            self._finish(slot)
        return True

    def _release_blocks(self, slot: int):
        if self.paged is not None and (self._slot_blocks[slot]
                                       or self._slot_cached[slot]):
            self._free_block_ids.extend(self._slot_blocks[slot])
            self._slot_blocks[slot] = []
            # shared cached blocks: drop this slot's reference — the
            # last release PARKS the entry on the LRU reclaim list
            # (its KV stays resident for future hits) instead of
            # freeing the block
            for entry in self._slot_cached[slot]:
                self._kv_cache.release(entry)
            self._slot_cached[slot] = []
            self._tables[slot, :] = 0          # back to the scratch sink

    def _retire_slot(self, slot: int, outcome: str = "finished") -> int:
        """Slot-retirement bookkeeping shared by normal completion and
        deadline enforcement: tokens move to ``_done``, the slot (and
        paged blocks) frees, the deadline drops, latency is recorded,
        and the flight recorder gets the terminal ``outcome`` event with
        the per-stage durations. Callers bump their own outcome
        counter/marker."""
        rid = self._rid[slot]
        self._done[rid] = self._outputs.pop(rid)
        sid = self._session.pop(rid, None)
        if sid is not None:
            # persist the conversation's tail KV BEFORE the blocks
            # free: the next request for this session admits as a
            # chain hit, on this replica (parked blocks) or any other
            # sharing the store (persisted blocks). The save runs
            # under the retiring request's trace context — it is this
            # request's time — as a session_save stage span.
            if self._session_store is not None:
                with use_context(self._trace_ctx.get(rid)), \
                        start_span("serving.session_save",
                                   stage="session_save", session=sid):
                    self._persist_session(slot, rid, sid)
            else:
                self._persist_session(slot, rid, sid)
        self._rid[slot] = None
        self._release_blocks(slot)
        self._clear_slot_meta(slot)
        self._deadline.pop(rid, None)
        self._seed.pop(rid, None)
        now = time.monotonic()
        t_sub = self._submit_t.pop(rid, None)
        t_adm = self._admit_t.pop(rid, now)
        ctx = self._trace_ctx.get(rid)
        if t_sub is not None:
            self._latency_window.append((t_adm - t_sub, now - t_sub,
                                         len(self._done[rid])))
            self._m_queue_wait.observe(t_adm - t_sub)
            # exemplar-enabled: a p99 latency bucket names the trace
            # whose retained tree explains it
            self._m_request_latency.observe(
                now - t_sub,
                trace_id=None if ctx is None else ctx.trace_id)
        self._finish_trace(rid, ctx, outcome, now, t_sub)
        self._trace_ctx.pop(rid, None)
        extra = {}
        a_p = self._accept.pop(rid, None)
        if a_p is not None:
            # per-request speculative acceptance on the terminal event:
            # the counters answer "how is the engine doing", this
            # answers "how did THIS request's draft do"
            extra = {"draft_accepted": a_p[0], "draft_proposed": a_p[1]}
        # the latency decomposition's terminal stamp (+ host-dict
        # cleanup — these are rid-keyed and must not outlive retirement)
        ttft = self._ttft_val.pop(rid, None)
        self._last_tok_t.pop(rid, None)
        self._ttft_origin.pop(rid, None)
        if ttft is not None:
            extra["ttft_s"] = round(ttft, 6)
        self.recorder.record(
            rid, outcome, tokens=len(self._done[rid]),
            queue_wait_s=(None if t_sub is None
                          else round(t_adm - t_sub, 6)),
            total_s=(None if t_sub is None else round(now - t_sub, 6)),
            **extra)
        return rid

    def _finish_trace(self, rid: int, ctx, outcome: str, now: float,
                      t_sub: Optional[float]) -> None:
        """Materialize the request's retroactive spans — the
        ``serving.request`` root (the span id every live span under
        this request already parents to) and the decode stage (first
        token -> last token) — then hand the tree to the span store's
        tail-based retention decision. A request submitted without a
        trace context never touched the store and has nothing to
        finish."""
        if ctx is None:
            return
        origin = self._ttft_origin.get(rid, t_sub)
        if origin is None:
            origin = t_sub
        ttft = self._ttft_val.get(rid)
        if origin is not None:
            total = now - origin
            wall0 = time.time() - total
            root_attrs = {"rid": rid, "outcome": outcome}
            if ttft is not None:
                root_attrs["ttft_s"] = round(ttft, 6)
            add_span("serving.request", wall0, total, ctx=ctx,
                     span_id=ctx.span_id, parent_id=ctx.parent_id,
                     **root_attrs)
            last_tok = self._last_tok_t.get(rid)
            if ttft is not None and last_tok is not None:
                dec = last_tok - (origin + ttft)
                if dec > 0:
                    add_span("serving.decode", wall0 + ttft, dec,
                             stage="decode", ctx=ctx)
        default_span_store().finish(
            ctx.trace_id,
            latency_s=None if origin is None else now - origin,
            ttft_s=ttft,
            # a missed deadline IS the SLO violation the tail keeps
            violated=outcome in ("expired", "timed_out"),
            errored=outcome not in ("finished", "expired", "timed_out",
                                    "cancelled"))

    def _persist_session(self, slot: int, rid: int, sid: str) -> None:
        """Write the retiring slot's full KV blocks into the session
        store, keyed by the FINAL sequence's chain (prompt + emitted
        tokens, current ``weights_version``) — only keys the store
        doesn't already hold are exported off the pool. The blocks
        also park locally, so a same-replica follow-up resumes
        straight off the device cache without touching the store.
        Paged engines only (blocks export straight off the pool);
        best-effort — a failed persist costs the next turn a
        re-prefill, never this request."""
        store = self._session_store
        if (store is None or self.paged is None
                or self._kv_cache is None or self._slot_lossy[slot]
                or int(self._slot_wv[slot]) != int(self.weights_version)):
            return
        prompt = self._slot_prompt[slot]
        if prompt is None:
            return
        from .models.block_cache import chain_keys
        from .models.paged_decode import export_pool_blocks

        bs = self._kv_cache_bs
        # the sequence whose KV the slot holds: prompt + tokens emitted
        # since admission (a resumed request's prompt already folds in
        # its earlier output — the _preempt_slot convention), truncated
        # to the last PROCESSED position (the pending token's KV was
        # never written)
        seq = np.concatenate(
            [prompt,
             np.asarray(self._done[rid][int(self._slot_prior[slot]):],
                        np.int32)])
        seq_kv = seq[:int(self._pos[slot]) + 1]
        nfull = seq_kv.size // bs
        if nfull == 0:
            return
        keys = chain_keys(seq_kv[:nfull * bs], bs, self.weights_version)
        missing = [i for i, k in enumerate(keys) if not store.has(k)]
        if missing:
            payloads = export_pool_blocks(
                self.pool, [int(self._tables[slot, i]) for i in missing])
            nbytes = 0
            for i, payload in zip(missing, payloads):
                nbytes += store.put_block(keys[i], payload,
                                          (i + 1) * bs)
            if self._m_spill_bytes is not None and nbytes:
                self._m_spill_bytes.labels(tier="session").inc(nbytes)
        store.note_session(sid, nfull)
        self.recorder.record(rid, "session_saved", session=sid,
                             blocks=nfull, new_blocks=len(missing))
        # park the slot's private full blocks under the same chain:
        # free same-replica resume, reclaimable under pool pressure
        # (where eviction now demotes instead of discarding)
        self._park_slot_blocks(slot, seq_kv)

    def _finish(self, slot: int):
        self._retire_slot(slot, "finished")
        self._m_finished.inc()

    @property
    def stats(self) -> Dict[str, float]:
        """Serving counters since construction: ``steps`` (device round
        trips), ``tokens_emitted``, ``requests_finished``,
        ``tokens_per_step`` (the continuous-batching + speculation
        payoff), and in speculative mode ``draft_acceptance`` (accepted
        / proposed over active slots). Every counter is a read of this
        engine's :attr:`registry` minus this engine's construction-time
        baseline — zero for the default fresh registry, so stats and
        ``GET /metrics`` agree exactly; with a shared injected registry
        the scrape keeps process-lifetime totals while stats stays
        per-engine."""
        steps = int(self._since_init(self._m_steps))
        emitted = int(self._since_init(self._m_emitted))
        out = {"steps": steps,
               "tokens_emitted": emitted,
               "requests_finished": int(self._since_init(self._m_finished)),
               "tokens_per_step": (emitted / steps if steps else 0.0),
               # overload-safety counters: admission rejections (429),
               # queued-deadline sheds (504), mid-decode timeouts, and
               # the live backlog the admission bounds act on
               "requests_shed": int(self._since_init(self._m_shed)),
               "requests_expired": int(self._since_init(self._m_expired)),
               "requests_timed_out": int(
                   self._since_init(self._m_timed_out)),
               "queue_depth": len(self._queue),
               "queued_tokens": self._queued_tokens,
               # live weight plane: what the engine serves NOW and how
               # many hot-swaps it has applied (gauge + counter on
               # /metrics; same numbers here so the surfaces agree)
               "weights_version": int(self.weights_version),
               "weight_swaps": int(self._since_init(
                   self._m_weight_swaps))}
        if self._prefixes or self._kv_cache is not None:
            out["prefix_hits"] = int(self._since_init(self._m_prefix_hits))
            out["prefix_tokens_reused"] = int(
                self._since_init(self._m_prefix_tokens))
        if self.paged is not None:
            out["blocks_total"] = self.paged[0] - 1
            # "free" = ALLOCATABLE: the raw free list plus parked cache
            # blocks (zero-ref, unpinned) admission pressure may
            # reclaim — the number the admission math actually acts on
            free = len(self._free_block_ids)
            if self._kv_cache is not None:
                free += self._kv_cache.reclaimable_count()
            out["blocks_free"] = free
        if self._kv_cache is not None:
            ks = self._kv_cache.stats()
            ks["block_size"] = self._kv_cache_bs
            out["kv_cache"] = ks
        if self._kv_spill is not None or self._session_store is not None:
            tiers: Dict[str, Dict] = {}
            if self._kv_spill is not None:
                tiers.update(self._kv_spill.stats())
            if self._session_store is not None:
                ss = self._session_store.stats()
                ss["hits"] = int(since_baseline(
                    self._spill_stat_base, self._m_session_hits))
                ss["misses"] = int(since_baseline(
                    self._spill_stat_base, self._m_session_misses))
                tiers["session"] = ss
            if self._m_spill_promote is not None:
                promotions = {
                    labels[0]: int(child.value)
                    for labels, child in
                    self._m_spill_promote.series().items()}
                if promotions:
                    tiers["promotions"] = promotions
            out["kv_tiers"] = tiers
        if self.qos is not None:
            out["preemptions"] = int(
                self._since_init(self._m_preemptions))
            # per-tenant story on one read: live queue numbers plus
            # the labeled counters (the metric IS the store)
            tenants: Dict[str, Dict] = {}
            for t in self._queue.live_tenants():
                label = self.qos.label(t)
                entry = tenants.setdefault(
                    label, {"queue_depth": 0, "queued_tokens": 0})
                entry["queue_depth"] += self._queue.tenant_depth(t)
                entry["queued_tokens"] += (
                    self._queue.tenant_queued_tokens(t))
            for metric, key in ((self._m_tenant_admitted, "admitted"),
                                (self._m_tenant_preempt, "preempted")):
                for labels, child in metric.series().items():
                    entry = tenants.setdefault(
                        labels[0], {"queue_depth": 0,
                                    "queued_tokens": 0})
                    entry[key] = int(child.value)
            for labels, child in self._m_tenant_shed.series().items():
                entry = tenants.setdefault(
                    labels[0], {"queue_depth": 0, "queued_tokens": 0})
                entry.setdefault("sheds", {})[labels[1]] = int(
                    child.value)
            out["tenants"] = tenants
        out["tier"] = self.tier
        # latency decomposition + loop profile: the same numbers the
        # scraped serving_ttft_seconds / serving_inter_token_seconds /
        # serving_loop_utilization series carry, on the JSON surface
        ttft_p50 = self._m_ttft.quantile(0.5)
        if ttft_p50 is not None:
            out["ttft_p50_s"] = round(ttft_p50, 6)
            out["ttft_p95_s"] = round(self._m_ttft.quantile(0.95), 6)
        itl_p50 = self._m_inter_token.quantile(0.5)
        if itl_p50 is not None:
            out["inter_token_p50_s"] = round(itl_p50, 6)
            out["inter_token_p99_s"] = round(
                self._m_inter_token.quantile(0.99), 6)
        if self.profiler is not None:
            out["loop"] = self.profiler.snapshot()
        if self._latency_window:
            totals = [t for _, t, _ in self._latency_window]
            waits = [w for w, _, _ in self._latency_window]
            # per-request decode rate: tokens delivered per second of a
            # request's wall time — with the acceptance rate, THE pair
            # of numbers that says what speculation is buying (surfaced
            # per replica on the fleet router's /stats)
            rates = [n / t for _, t, n in self._latency_window
                     if t > 0 and n > 0]
            if rates:
                out["request_tokens_per_s_p50"] = round(
                    float(np.quantile(rates, 0.5)), 3)
            out["latency_p50_s"] = round(float(np.quantile(totals, 0.5)),
                                         4)
            out["latency_p99_s"] = round(float(np.quantile(totals, 0.99)),
                                         4)
            out["queue_wait_mean_s"] = round(sum(waits) / len(waits), 4)
            # the tier-labeled headline, readable off /stats too: this
            # engine's queue-wait distribution tail (tier="decode" on a
            # disaggregated decode worker excludes prefill blocking;
            # the prefill tier's wait rides DisaggEngine's stats)
            out["queue_wait_p50_s"] = round(
                float(np.quantile(waits, 0.5)), 6)
            out["queue_wait_p99_s"] = round(
                float(np.quantile(waits, 0.99)), 6)
        if self.draft_config is not None:
            proposed = self._since_init(self._m_proposed)
            # None (not 0.0) before any proposal: an idle or freshly
            # scaled-up replica must not read as a zero-acceptance
            # (stale-draft) signal — the fleet prober's
            # draft_acceptance_min skips None
            out["draft_acceptance"] = (
                self._since_init(self._m_accepted) / proposed
                if proposed else None)
            out["speculative_rounds"] = int(
                self._since_init(self._m_spec_rounds))
            out["draft_weights_version"] = int(self.draft_weights_version)
            # operating depth vs ceiling: equal unless adaptive_gamma
            # has steered down (the gap IS the staleness signal)
            out["gamma"] = int(self._gamma_now)
            out["gamma_ceiling"] = int(self.gamma)
        # resolved attention kernel ("pallas" only when it will really
        # run compiled; a fallback shows requested != kernel here)
        out["kernel"] = self.kernel
        if self.kernel != self.kernel_requested:
            out["kernel_requested"] = self.kernel_requested
        if self.interleave_prefill:
            out["prefill_chunks_interleaved"] = int(
                self._since_init(self._m_interleaved))
            out["pending_prefills"] = len(self._pending_prefill)
        return out

    def _since_init(self, metric) -> float:
        """This engine's share of a counter: current value minus the
        construction-time baseline (see ``_stat_base``)."""
        return since_baseline(self._stat_base, metric)

    # ------------------------------------------------------------- step
    @property
    def pending(self) -> int:
        """Work remaining: requests queued or in flight, plus emitted
        tokens not yet surfaced by step() — so the canonical
        ``while eng.pending: eng.step()`` loop always delivers a
        request's tokens even when it retires at admission time
        (``max_new_tokens=1``). A staged weight swap counts too: an
        idle server's engine loop must still pick it up within one
        idle-sleep, not wait for the next request."""
        with self._staged_lock:
            staged = (self._staged_params is not None
                      or self._staged_draft is not None)
        return (len(self._queue)
                + sum(r is not None for r in self._rid)
                + len(self._pending_prefill)
                + len(self._fresh)
                + (1 if staged else 0))

    def _psec(self, phase: str):
        """The profiler section for ``phase`` (a shared no-op context
        when profiling is off — the hot path pays one attribute read)."""
        prof = self.profiler
        return _NULL_SECTION if prof is None else prof.section(phase)

    def _steer_gamma(self, accepted: int, proposed: int) -> None:
        """One control-loop tick of the adaptive speculative depth.

        Feeds this round's pooled acceptance into an EWMA and, at most
        every :data:`GAMMA_ADJUST_EVERY` rounds, moves ``_gamma_now``
        ONE step toward the depth that acceptance currently pays for:
        with per-token acceptance rate ``a``, proposing beyond
        ``~a * ceiling`` drafts tokens the verifier will mostly throw
        away, while proposing fewer leaves accepted tokens on the
        table. The one-step/hysteresis pairing keeps the loop from
        chattering between adjacent depths on acceptance noise, yet an
        acceptance collapse (stale draft) still walks gamma from the
        ceiling to the floor in ``GAMMA_ADJUST_EVERY * (ceiling -
        floor)`` rounds — minutes before a draft_acceptance_min alert
        would fire. Token streams are unaffected by ANY depth schedule:
        greedy verification emits the exact argmax prefix at every
        depth, so steering changes only how much verify work each
        emitted token costs.
        """
        if not proposed:
            return
        acc = accepted / proposed
        self._accept_ewma = (acc if self._accept_ewma is None else
                             GAMMA_EWMA_ALPHA * acc
                             + (1.0 - GAMMA_EWMA_ALPHA)
                             * self._accept_ewma)
        self._rounds_since_adjust += 1
        if self._rounds_since_adjust < GAMMA_ADJUST_EVERY:
            return
        self._rounds_since_adjust = 0
        target = max(self.gamma_min,
                     min(self.gamma,
                         1 + int(self._accept_ewma * self.gamma + 0.5)))
        if target > self._gamma_now:
            self._gamma_now += 1
        elif target < self._gamma_now:
            self._gamma_now -= 1

    def step(self) -> Dict[int, List[int]]:
        """Advance every active slot — by one token (plain mode) or by
        ``1 + accepted`` tokens (speculative mode, up to ``gamma+1``);
        returns ``{request_id: [tokens]}`` emitted since the last call
        (admission-time first tokens ride along too). Finished requests
        retire and queued ones join automatically; expired queued
        requests are shed before prefill and over-deadline active slots
        are freed (their partial output finishes as a ``timeout``)."""
        if self.profiler is not None:
            # iteration boundary: wall time since the LAST tick —
            # including the server loop's idle sleep — closes into the
            # rolling window, so utilization reads as a share of real
            # wall time, not of busy time
            self.profiler.tick()
        # slow steps (a prefill-compile-heavy one) also land on the
        # slow-span ring by name
        with span_if_counted("serving.step", self._m_steps,
                             histogram=self._m_step_latency):
            return self._step_impl()

    def _step_impl(self) -> Dict[int, List[int]]:
        # chaos site: 'error' = engine crash mid-serve (the HTTP loop
        # records it and /health turns red), 'delay' = a slow step
        fault_site("serving.step")
        self._admit()
        if self._pending_prefill:
            # feed this iteration's chunk budget BEFORE reading _fresh:
            # an admission completing here surfaces its first token in
            # this very step, matching run-to-completion semantics
            with self._psec("prefill"):
                self._interleave_prefills()
        emitted = {rid: list(toks) for rid, toks in self._fresh.items()}
        self._fresh = {}
        active = np.asarray([r is not None for r in self._rid])
        if not active.any():
            return emitted
        # inactive slots decode garbage at position 0 (static batch
        # shape); their writes are overwritten by the next admission's
        # prefill and masked until then
        pos = np.where(active, self._pos + 1, 0).astype(np.int32)
        self._m_steps.inc()
        if self.draft_config is not None:
            # speculative round: every active slot advances by its own
            # 1 + accepted tokens in one dispatch. The round runs at the
            # adaptive operating depth (== self.gamma for fixed-gamma
            # engines); verify slack was budgeted at the ceiling, so any
            # depth <= it writes safely
            g_now = self._gamma_now
            with self._psec("decode"):
                if self.paged is not None:
                    (emit, acc, nxt, self.pool, self.draft_cache,
                     self._key) = self._spec_step_paged_for(g_now)(
                        self.params, self.draft_params, self.pool,
                        self.draft_cache, jnp.asarray(self._tables),
                        jnp.asarray(self._last), jnp.asarray(pos),
                        self._key)
                else:
                    (emit, acc, nxt, self.cache, self.draft_cache,
                     self._key) = self._spec_step_for(g_now)(
                        self.params, self.draft_params, self.cache,
                        self.draft_cache, jnp.asarray(self._last),
                        jnp.asarray(pos), self._key)
                emit, acc, nxt = (np.asarray(emit), np.asarray(acc),
                                  np.asarray(nxt))
            n_active = int(active.sum())
            n_accepted = int(acc[active].sum())
            self._m_accepted.inc(n_accepted)
            self._m_proposed.inc(g_now * n_active)
            self._m_spec_rounds.inc(n_active)
            if self.adaptive_gamma:
                self._steer_gamma(n_accepted, g_now * n_active)
            with self._psec("emit"):
                for slot in np.nonzero(active)[0]:
                    rid = self._rid[slot]
                    # per-request acceptance for the flight recorder's
                    # terminal event (engine counters above are pooled)
                    a_p = self._accept.setdefault(rid, [0, 0])
                    a_p[0] += int(acc[slot])
                    a_p[1] += g_now
                    self._pos[slot] += 1 + acc[slot]
                    self._last[slot] = nxt[slot]
                    for tok in emit[slot, :acc[slot] + 1]:
                        if self._rid[slot] is None:
                            break   # retired mid-chunk (eos or budget)
                        if self._record(slot, int(tok)):
                            emitted.setdefault(rid, []).append(int(tok))
            self._admit()
            return emitted
        if self.steps_per_sync > 1:
            with self._psec("decode"):
                if self.paged is not None:
                    toks, self.pool, self._key = \
                        self._multi_step_paged_fn(
                            self.params, self.pool,
                            jnp.asarray(self._tables),
                            jnp.asarray(self._last), jnp.asarray(pos),
                            jnp.asarray(self._temp),
                            jnp.asarray(self._topk),
                            jnp.asarray(self._topp),
                            jnp.asarray(self._slot_seed), self._key)
                else:
                    toks, self.cache, self._key = self._multi_step_fn(
                        self.params, self.cache, jnp.asarray(self._last),
                        jnp.asarray(pos), jnp.asarray(self._temp),
                        jnp.asarray(self._topk), jnp.asarray(self._topp),
                        jnp.asarray(self._slot_seed), self._key)
                toks = np.asarray(toks)                   # (B, K)
            with self._psec("emit"):
                for slot in np.nonzero(active)[0]:
                    rid = self._rid[slot]
                    for tok in toks[slot]:
                        if self._rid[slot] is None:
                            break   # retired mid-chunk — surplus dropped
                        self._pos[slot] += 1
                        self._last[slot] = tok
                        if self._record(slot, int(tok)):
                            emitted.setdefault(rid, []).append(int(tok))
            self._admit()
            return emitted
        with self._psec("decode"):
            if self.paged is not None:
                toks, self.pool, self._key = self._step_paged_fn(
                    self.params, self.pool, jnp.asarray(self._tables),
                    jnp.asarray(self._last), jnp.asarray(pos),
                    jnp.asarray(self._temp), jnp.asarray(self._topk),
                    jnp.asarray(self._topp),
                    jnp.asarray(self._slot_seed), self._key)
            else:
                toks, self.cache, self._key = self._step_fn(
                    self.params, self.cache, jnp.asarray(self._last),
                    jnp.asarray(pos), jnp.asarray(self._temp),
                    jnp.asarray(self._topk), jnp.asarray(self._topp),
                    jnp.asarray(self._slot_seed), self._key)
            toks = np.asarray(toks)
        with self._psec("emit"):
            for slot in np.nonzero(active)[0]:
                rid = self._rid[slot]
                self._pos[slot] += 1
                self._last[slot] = toks[slot]
                if self._record(slot, int(toks[slot])):
                    emitted.setdefault(rid, []).append(int(toks[slot]))
        self._admit()
        return emitted

    def run(self, requests: Sequence[Sequence[int]],
            max_new_tokens: int) -> List[List[int]]:
        """Convenience batch driver: submit every request, step until
        drained, return outputs in request order."""
        rids = [self.submit(p, max_new_tokens) for p in requests]
        while self.pending:
            self.step()
        return [self.result(r) for r in rids]

    def result(self, rid: int) -> Optional[List[int]]:
        """Finished output for ``rid`` (None while still in flight).
        Pops the entry: a long-running server does not accumulate every
        finished request's tokens; call once per request."""
        info = self.result_info(rid)
        return None if info is None else info["tokens"]

    def result_info(self, rid: int) -> Optional[Dict]:
        """Like :meth:`result` but returns the full outcome:
        ``{"tokens": [...], "timeout": bool, "expired": bool}``.
        ``expired`` — the deadline passed while queued (no token was
        ever decoded; the request never reached prefill); ``timeout`` —
        the deadline cut the request short (set for BOTH cases; for a
        mid-decode cut ``tokens`` holds the partial output). One-shot,
        like :meth:`result`."""
        if rid not in self._done:
            return None
        tokens = self._done.pop(rid)
        expired = rid in self._expired
        timed_out = expired or rid in self._timed_out
        self._expired.discard(rid)
        self._timed_out.discard(rid)
        return {"tokens": tokens, "timeout": timed_out,
                "expired": expired}

    # ---------------------------------------------------------- tracing
    def request_trace(self, rid: int) -> Optional[Dict]:
        """The request's flight-recorder timeline ``{"id", "trace_id",
        "events": [...]}`` — every event stamped with the trace id
        captured at submit. Unlike :meth:`result` this is NOT one-shot
        (it answers "what happened", possibly long after the result was
        fetched), but it IS a bounded ring: old requests eventually
        evict. None for unknown/evicted ids."""
        return self.recorder.trace(rid)

    def recent_traces(self, limit: int = 32) -> List[Dict]:
        """The newest ``limit`` request timelines, oldest first (the
        ``GET /debug/trace/recent`` payload)."""
        return self.recorder.recent(limit)
