"""Elastic worker supervision for asynchronous training.

The reference (and this repo's previous ``_fit_async``) ran one
fire-and-forget thread per shard and aborted the whole fit on the first
worker exception, silently discarding every surviving worker's progress.
:class:`WorkerSupervisor` replaces that with the elastic-training shape
popularized by Horovod Elastic / TorchElastic, scaled to the
single-controller threading model:

- shards are *work items* on a queue, executed by a fixed set of worker
  slots (each slot maps round-robin onto a local device, exactly like
  the thread-per-shard dispatch it replaces);
- a failed item is handled by policy: ``reassign`` (default) re-queues
  the shard onto a surviving slot, bounded by ``max_worker_restarts``
  per shard; ``fail`` preserves the pre-supervisor semantics exactly
  (every dispatched shard still runs to completion — drains — and then
  the first error is raised); ``continue`` drops the shard and degrades
  gracefully as long as at least a ``min_workers`` fraction of shards
  completes (quorum), else :class:`QuorumLostError`;
- an optional parameter-server monitor probes PS health between
  failures and on a background cadence; a dead PS is restarted through
  the caller's ``ps_restart`` hook (snapshot-based, same port) and the
  failed shard is re-queued *without* consuming its restart budget —
  a PS outage is not the worker's fault;
- every decision is recorded in a :class:`SupervisorReport`
  (``restarts``/``reassigned_shards``/``lost_shards``/``ps_restarts``)
  so degradation is observable, never silent.
"""
import logging
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..obs.context import current_context, current_trace_id, set_context
from ..obs.metrics import default_registry

_LOG = logging.getLogger(__name__)

POLICIES = ("reassign", "fail", "continue")


class QuorumLostError(RuntimeError):
    """Raised by ``on_worker_failure='continue'`` when fewer than the
    ``min_workers`` fraction of shards completed successfully."""


class SupervisorReport:
    """What the supervisor did, for ``training_histories``.

    :ivar restarts: shard re-executions after a worker failure
    :ivar reassigned_shards: shard indices re-queued (one entry per
        re-queue, so a twice-restarted shard appears twice)
    :ivar lost_shards: shard indices dropped under ``continue``
    :ivar completed_shards: shard indices that finished successfully
    :ivar ps_restarts: parameter-server restarts performed
    :ivar failures: ``(shard, attempt, repr(error))`` per observed failure
    :ivar trace_id: the trace id active when :meth:`WorkerSupervisor.run`
        started (None outside any context) — joins this fit's decisions
        to the fleet's event log / flight-recorder artifacts
    """

    def __init__(self):
        self.restarts = 0
        self.reassigned_shards: List[int] = []
        self.lost_shards: List[int] = []
        self.completed_shards: List[int] = []
        self.ps_restarts = 0
        self.failures: List[tuple] = []
        self.trace_id: Optional[str] = None

    def as_dict(self) -> Dict[str, Any]:
        return {"restarts": self.restarts,
                "reassigned_shards": list(self.reassigned_shards),
                "lost_shards": sorted(self.lost_shards),
                "completed_shards": sorted(self.completed_shards),
                "ps_restarts": self.ps_restarts,
                "failures": [(s, a, e) for s, a, e in self.failures],
                "trace_id": self.trace_id}


class WorkerSupervisor:
    """Dispatch shards to worker slots; survive failures by policy.

    :param run_shard: ``run_shard(slot, shard_idx, shard, attempt)``
        trains one shard. ``slot`` is the stable slot index (use it for
        round-robin device assignment); ``attempt`` is 0 for the first
        dispatch and grows with each re-queue.
    :param on_worker_failure: ``'reassign'`` | ``'fail'`` | ``'continue'``
    :param max_worker_restarts: per-shard re-queue budget under
        ``reassign``; exhausting it re-raises the shard's last error
    :param min_workers: quorum fraction (0..1] of shards that must
        complete under ``continue``
    :param num_slots: concurrent worker slots (default: one per shard)
    :param ps_probe: optional zero-arg health probe returning True when
        the parameter server is alive (call sites usually also snapshot
        server state inside a healthy probe)
    :param ps_restart: optional zero-arg hook restarting the parameter
        server (from the caller's latest snapshot, on the same port)
    :param ps_probe_interval: background probe cadence, seconds
    :param max_ps_restarts: bound on PS restarts per fit — a flapping
        server (dies again right after every restart) must eventually
        surface as worker failures handled by the policy, not restart
        forever
    :param on_item_failure: ``(shard_idx, attempt, error)`` observer
        fired for every worker failure the *policy* must act on (a
        PS-restart free retry resumes the worker's role and is not
        reported) — the fit driver uses it to remove the dead
        participant from the epoch aggregator so callbacks never stall
    """

    def __init__(self, run_shard: Callable[[int, int, Any, int], Any],
                 on_worker_failure: str = "reassign",
                 max_worker_restarts: int = 2, min_workers: float = 0.5,
                 num_slots: Optional[int] = None,
                 ps_probe: Optional[Callable[[], bool]] = None,
                 ps_restart: Optional[Callable[[], None]] = None,
                 ps_probe_interval: float = 2.0, max_ps_restarts: int = 5,
                 on_item_failure: Optional[Callable[[int, int, BaseException],
                                                    None]] = None):
        if on_worker_failure not in POLICIES:
            raise ValueError(
                f"on_worker_failure must be one of {POLICIES}, "
                f"got {on_worker_failure!r}")
        if not (0.0 < min_workers <= 1.0):
            raise ValueError(
                f"min_workers must be in (0, 1], got {min_workers}")
        if ps_probe_interval <= 0:
            # Event.wait(0) would turn the monitor into a busy loop
            raise ValueError(
                f"ps_probe_interval must be > 0, got {ps_probe_interval}")
        self.run_shard = run_shard
        self.policy = on_worker_failure
        self.max_worker_restarts = max(0, int(max_worker_restarts))
        self.min_workers = float(min_workers)
        self.num_slots = num_slots
        self.ps_probe = ps_probe
        self.ps_restart = ps_restart
        self.ps_probe_interval = float(ps_probe_interval)
        self.max_ps_restarts = max(0, int(max_ps_restarts))
        self.on_item_failure = on_item_failure
        self.report = SupervisorReport()
        self._queue: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        # PS supervision gets its own lock: a health probe (network
        # timeout) or snapshot (full weight copy) must serialize restarts
        # without stalling item bookkeeping under self._lock
        self._ps_lock = threading.Lock()
        # restart generation + timestamp: workers co-felled by ONE
        # outage all deserve the free retry, but only the first one's
        # probe still sees a dead server — the rest match on a recent
        # restart instead (once per shard per generation)
        self._ps_generation = 0
        self._ps_restart_time: Optional[float] = None
        self._shard_ps_gen: Dict[int, int] = {}
        self._trace_ctx = None             # captured by run()
        self._done = threading.Event()
        self._stop_monitor = threading.Event()
        self._outstanding = 0
        self._fatal: Optional[BaseException] = None
        # process-wide counters mirroring the per-fit report: the report
        # answers "what did THIS fit survive", the registry answers "how
        # often does this fleet member restart things" across fits
        reg = default_registry()
        self._m_failures = reg.counter(
            "supervisor_worker_failures_total",
            "worker failures observed by the supervisor")
        self._m_restarts = reg.counter(
            "supervisor_restarts_total",
            "shard re-executions after a worker failure")
        self._m_reassigned = reg.counter(
            "supervisor_shards_reassigned_total",
            "shard re-queues onto surviving slots")
        self._m_lost = reg.counter(
            "supervisor_shards_lost_total",
            "shards dropped under the 'continue' policy")
        self._m_completed = reg.counter(
            "supervisor_shards_completed_total",
            "shards that finished successfully")
        self._m_ps_restarts = reg.counter(
            "supervisor_ps_restarts_total",
            "parameter-server snapshot restarts performed")

    # ------------------------------------------------------------------ run
    def run(self, shards: Sequence) -> SupervisorReport:
        """Execute every shard; return the report. Raises the first
        fatal error (policy ``fail``, an exhausted restart budget, or a
        lost quorum) after running work has drained."""
        shards = list(shards)
        # the fit's trace context: stamped on the report and restored in
        # every slot/monitor thread (contextvars do not cross threads),
        # so fault events and PS RPCs fired by workers carry the
        # caller's trace id
        self._trace_ctx = current_context()
        self.report.trace_id = current_trace_id()
        if not shards:
            return self.report
        self._outstanding = len(shards)
        for idx, shard in enumerate(shards):
            self._queue.put((idx, shard, 0))
        n_slots = min(len(shards), self.num_slots or len(shards))
        slots = [threading.Thread(target=self._slot_loop, args=(s,),
                                  daemon=True,
                                  name=f"elephas-tpu-supervisor-{s}")
                 for s in range(n_slots)]
        monitor = None
        if self.ps_probe is not None and self.ps_restart is not None:
            monitor = threading.Thread(target=self._monitor_loop,
                                       daemon=True,
                                       name="elephas-tpu-ps-monitor")
            monitor.start()
        for t in slots:
            t.start()
        try:
            self._done.wait()
        finally:
            self._stop_monitor.set()
            for t in slots:
                t.join()
            if monitor is not None:
                monitor.join()
        if self._fatal is not None:
            raise self._fatal
        if self.policy == "continue":
            total = len(shards)
            ok = len(self.report.completed_shards)
            if ok < self.min_workers * total:
                raise QuorumLostError(
                    f"only {ok}/{total} shards completed — below the "
                    f"min_workers quorum of {self.min_workers:.0%}; lost "
                    f"shards: {sorted(self.report.lost_shards)}")
        return self.report

    # ---------------------------------------------------------- slot loop
    def _slot_loop(self, slot: int):
        set_context(self._trace_ctx)       # inherit the fit's context
        while not self._done.is_set():
            try:
                item = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            idx, shard, attempt = item
            try:
                self.run_shard(slot, idx, shard, attempt)
            except BaseException as err:  # noqa: BLE001 — policy decides
                self._on_failure(idx, shard, attempt, err)
            else:
                with self._lock:
                    self.report.completed_shards.append(idx)
                self._m_completed.inc()
                self._finish_item()

    def _finish_item(self):
        with self._lock:
            self._outstanding -= 1
            if self._outstanding <= 0:
                self._done.set()

    # ------------------------------------------------------------ failures
    def _on_failure(self, idx: int, shard, attempt: int,
                    err: BaseException):
        _LOG.warning("shard %d failed on attempt %d: %r", idx, attempt, err)
        self._m_failures.inc()
        with self._lock:
            self.report.failures.append((idx, attempt, repr(err)))

        # a dead parameter server is not the worker's fault: restart it
        # (caller-provided, snapshot-based) and re-run the shard without
        # consuming its restart budget — and without notifying
        # on_item_failure, so the retry keeps the worker's aggregator
        # seat (re-reported epochs are idempotent per member)
        if self._ps_recovered(err, idx):
            with self._lock:
                self.report.restarts += 1
                self.report.reassigned_shards.append(idx)
            self._m_restarts.inc()
            self._m_reassigned.inc()
            self._queue.put((idx, shard, attempt))
            return

        if self.on_item_failure is not None:
            try:
                self.on_item_failure(idx, attempt, err)
            except Exception:  # an observer must never mask the policy
                _LOG.exception("on_item_failure observer raised")

        if self.policy == "fail":
            # pre-supervisor semantics: the remaining dispatched shards
            # still run (drain), then the first error aborts the fit
            self._trip_fatal(err)
        elif self.policy == "reassign":
            if attempt < self.max_worker_restarts:
                with self._lock:
                    self.report.restarts += 1
                    self.report.reassigned_shards.append(idx)
                self._m_restarts.inc()
                self._m_reassigned.inc()
                self._queue.put((idx, shard, attempt + 1))
            else:
                _LOG.error("shard %d exhausted its %d restart(s)",
                           idx, self.max_worker_restarts)
                self._trip_fatal(err)
        else:  # continue: drop the shard, quorum checked at the end
            with self._lock:
                self.report.lost_shards.append(idx)
            self._m_lost.inc()
            self._finish_item()

    def _trip_fatal(self, err: BaseException):
        with self._lock:
            if self._fatal is None:
                self._fatal = err
        self._finish_item()

    # ----------------------------------------------------------- PS watch
    #: window after a restart in which a transport-failed worker is
    #: attributed to the outage that restart healed (client retries
    #: spanning the restart succeed on their own; only deadlines that
    #: expired just before/around it land here)
    _PS_GRACE_S = 10.0

    def _ps_recovered(self, err: BaseException, idx: int) -> bool:
        """If PS supervision is wired and the server is down (or was
        just restarted), give shard ``idx`` a free retry. True iff the
        failure is attributed to a PS outage.

        Only a TRANSPORT failure counts as a death signal — a worker
        that died of its own bug (shape mismatch, OOM) must not combine
        with timed-out probes on a busy-but-live server into a
        destructive snapshot restart. A live restart demands TWO failed
        probes (``confirm=2``); workers co-felled by the SAME outage
        arrive after the first one's restart and match on the recent
        restart generation instead (once per shard per generation, so a
        shard failing for its own reasons can't free-retry forever)."""
        if self.ps_probe is None or self.ps_restart is None:
            return False
        # transport errors only (the clients wrap exhausted retries in
        # ConnectionError): a broad OSError would misattribute local I/O
        # failures — a deleted shard file — to the PS outage
        if not isinstance(err, (ConnectionError, TimeoutError)):
            return False
        import time as _time

        with self._ps_lock:
            if (self._ps_restart_time is not None
                    and _time.monotonic() - self._ps_restart_time
                    < self._PS_GRACE_S
                    and self._shard_ps_gen.get(idx) != self._ps_generation):
                self._shard_ps_gen[idx] = self._ps_generation
                return True
        if self._try_restart("", confirm=2):
            with self._ps_lock:
                self._shard_ps_gen[idx] = self._ps_generation
            return True
        return False

    #: gap between confirmation probes (dead servers refuse instantly, so
    #: this mostly prices the overloaded-but-alive case)
    _CONFIRM_GAP_S = 0.3

    def _try_restart(self, context: str, confirm: int = 1) -> bool:
        """Probe under the PS lock and, if the server looks dead for
        ``confirm`` consecutive probes, restart it and record the
        restart. The one shared probe→restart→record sequence for both
        the worker-failure path and the background monitor."""
        import time as _time

        with self._ps_lock:
            # serialize probe+restart: concurrent failing workers must
            # trigger ONE restart, and the later ones must observe it.
            # The budget check lives INSIDE the lock: checked outside,
            # N concurrently-failing workers could each pass it and
            # overshoot max_ps_restarts by N-1 against a flapping server
            if self._ps_budget_spent():
                return False  # let the worker policy decide
            try:
                for i in range(max(1, confirm)):
                    if self.ps_probe():
                        return False
                    if i + 1 < confirm:
                        _time.sleep(self._CONFIRM_GAP_S)
                self.ps_restart()
                self._ps_generation += 1
                self._ps_restart_time = _time.monotonic()
                with self._lock:
                    self.report.ps_restarts += 1
                self._m_ps_restarts.inc()
                _LOG.warning("parameter server restarted from snapshot%s",
                             context)
                return True
            except Exception:
                _LOG.exception("parameter-server restart failed")
                return False

    def _ps_budget_spent(self) -> bool:
        with self._lock:
            return self.report.ps_restarts >= self.max_ps_restarts

    def _monitor_loop(self):
        """Background PS health cadence: catches a PS death even while
        every worker is busy inside a long RPC retry, so the restart
        lands before client deadlines expire.

        Restarting a live server is destructive (it rolls acked updates
        back to the latest snapshot), so the monitor demands TWO
        consecutive failed probes — plus :meth:`_try_restart`'s own
        under-lock confirmation — before acting; a single timed-out
        probe on a loaded but healthy server must not trigger it."""
        set_context(self._trace_ctx)       # inherit the fit's context
        suspect = 0
        while not self._stop_monitor.wait(self.ps_probe_interval):
            try:
                if self._ps_budget_spent():
                    _LOG.error(
                        "parameter server restarted %d times and keeps "
                        "dying — giving up on PS supervision; worker "
                        "failures now fall to the %r policy",
                        self.max_ps_restarts, self.policy)
                    return
                with self._ps_lock:
                    if self._done.is_set():
                        return
                    healthy = self.ps_probe()
                if healthy:
                    suspect = 0
                    continue
                suspect += 1
                if suspect < 2:
                    continue  # one blip is not evidence of death
                if self._try_restart(" (background probe)"):
                    suspect = 0
            except Exception:
                _LOG.exception("parameter-server monitor probe failed")
