"""GPipe-style pipeline parallelism over a ``pipe`` mesh axis.

The reference cannot do any model parallelism (``README.md:319-321`` calls
it "practically impossible" under Spark); on TPU it is a mesh axis. This
module implements the classic microbatched pipeline schedule as a pure
function under ``shard_map``:

- stage parameters are stacked along a leading axis sharded over ``pipe``
  (device s holds stage s),
- the batch splits into M microbatches; at tick t stage 0 injects
  microbatch t while every stage processes the activation it received
  last tick and ``ppermute``s its output to the next stage,
- after ``M + S - 1`` ticks the last stage has produced every microbatch;
  outputs are gathered with a masked ``psum`` so the result is replicated.

The schedule lives inside one ``lax.scan`` — XLA sees a static loop of
S-way-parallel stage computations with neighbor-only ICI transfers, which
is exactly the hardware-shaped formulation of GPipe. Differentiable end
to end (``shard_map``/``ppermute``/``scan`` all have transpose rules), so
``jax.grad`` of a pipelined loss just works; the backward pass is the
reverse pipeline.
"""
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_pipeline_fn", "stack_stage_params",
           "split_transformer_stages", "merge_transformer_stages",
           "shard_pipelined_params", "make_pipelined_lm_loss",
           "make_pipelined_train_step"]


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees (identical structure)
    along a new leading axis — the axis that shards over ``pipe``."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


def make_pipeline_fn(stage_fn: Callable, mesh: Mesh, axis: str = "pipe",
                     num_microbatches: int = None,
                     batch_axis: Optional[str] = None):
    """Build ``fn(stacked_params, x) -> y`` running ``stage_fn`` as a
    microbatched pipeline over ``mesh[axis]``.

    :param stage_fn: ``(stage_params, x_micro) -> y_micro``, shape
        preserving (the activation flowing between stages must keep one
        shape, as in a stack of transformer blocks).
    :param num_microbatches: number of microbatches M (default: pipeline
        depth). The batch dimension must divide by M.
    :param batch_axis: optional data-parallel mesh axis: each dp row of
        the mesh pipelines its own batch shard through the same stage
        stack (dp x pp composition — stage params are sharded over
        ``axis`` and replicated over ``batch_axis``; the gradient
        all-reduce over ``batch_axis`` is inserted by GSPMD where the
        loss averages over the global batch).
    """
    num_stages = mesh.shape[axis]
    M = num_microbatches or num_stages

    def pipelined(stacked_params, x):
        leading = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        if leading != num_stages:
            raise ValueError(
                f"stacked params hold {leading} stages but mesh axis "
                f"{axis!r} has {num_stages} devices — a mismatched stack "
                "would silently drop stages")
        if x.shape[0] % M:
            raise ValueError(f"batch {x.shape[0]} not divisible by "
                             f"{M} microbatches")
        micro = x.reshape((M, x.shape[0] // M) + x.shape[1:])

        def per_device(params_local, micro_local):
            # params_local leading dim is 1 (this device's stage slice)
            stage_params = jax.tree_util.tree_map(lambda p: p[0],
                                                  params_local)
            idx = jax.lax.axis_index(axis)
            num_ticks = M + num_stages - 1
            state0 = jnp.zeros_like(micro_local[0])

            def tick(state, t):
                # stage 0 injects microbatch t (clamped; injections past
                # M-1 never reach the collected output window)
                inject = jax.lax.dynamic_index_in_dim(
                    micro_local, jnp.minimum(t, M - 1), axis=0,
                    keepdims=False)
                x_in = jnp.where(idx == 0, inject, state)
                y = stage_fn(stage_params, x_in)
                # neighbor-only transfer: stage s -> s+1 over ICI
                state_next = jax.lax.ppermute(
                    y, axis, [(s, s + 1) for s in range(num_stages - 1)])
                return state_next, y

            _, ys = jax.lax.scan(tick, state0, jnp.arange(num_ticks))
            # microbatch m finishes on the LAST stage at tick m + S - 1;
            # mask everyone else and psum to replicate the result
            outs = jax.lax.dynamic_slice_in_dim(ys, num_stages - 1, M,
                                                axis=0)
            outs = jnp.where(idx == num_stages - 1, outs,
                             jnp.zeros_like(outs))
            return jax.lax.psum(outs, axis)

        in_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
        # micro is (M, B, ...): with a dp axis the per-microbatch batch
        # dim shards over it, so each dp row pipelines its own shard
        x_spec = P(None, batch_axis) if batch_axis is not None else P()
        from ..utils.compat import shard_map as _shard_map

        y = _shard_map(per_device, mesh=mesh,
                       in_specs=(in_spec, x_spec), out_specs=x_spec,
                       check=False)(stacked_params, micro)
        return y.reshape(x.shape[0:1] + y.shape[2:])

    return pipelined


# --------------------------------------------------------- pipelined LM
# End-to-end pipeline-parallel training of the flagship transformer:
# embedding and LM head live OUTSIDE the shape-preserving stage stack
# (they change the activation shape, so they cannot be pipeline stages),
# the transformer blocks flow through the GPipe schedule above, and the
# optimizer steps over the stage-stacked parameter pytree. Gradient
# accumulation across microbatches is inherent: the loss averages over
# the full batch, so differentiating through the pipeline's scan sums
# each stage's gradient contributions over all of its microbatches —
# exactly GPipe's accumulate-then-apply semantics, derived by transpose
# instead of hand-scheduled.

def split_transformer_stages(params: Dict, config, num_stages: int) -> Dict:
    """Rearrange a :func:`~elephas_tpu.models.transformer.init_params`
    pytree for pipeline execution:

    ``{"embed", "final_ln", "stages"}`` where ``stages`` stacks the
    ``layer_i`` subtrees as ``(num_stages, layers_per_stage, ...)`` —
    leading axis sharded over ``pipe``, second axis looped inside a stage.
    """
    L = config.num_layers
    if L % num_stages:
        raise ValueError(f"{L} layers do not split into {num_stages} "
                         "equal pipeline stages")
    per_stage = L // num_stages
    stages = [
        jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs),
            *[params[f"layer_{s * per_stage + j}"] for j in range(per_stage)])
        for s in range(num_stages)]
    out = {"embed": params["embed"], "final_ln": params["final_ln"],
           "stages": stack_stage_params(stages)}
    if "head" in params:  # untied LM head rides outside the stage stack
        out["head"] = params["head"]
    return out


def merge_transformer_stages(pipe_params: Dict, config) -> Dict:
    """Inverse of :func:`split_transformer_stages` — back to the flat
    ``layer_i`` layout (checkpoint interop, parity tests)."""
    stages = pipe_params["stages"]
    num_stages = jax.tree_util.tree_leaves(stages)[0].shape[0]
    per_stage = config.num_layers // num_stages
    params = {"embed": pipe_params["embed"],
              "final_ln": pipe_params["final_ln"]}
    if "head" in pipe_params:
        params["head"] = pipe_params["head"]
    for s in range(num_stages):
        for j in range(per_stage):
            params[f"layer_{s * per_stage + j}"] = jax.tree_util.tree_map(
                lambda p: p[s, j], stages)
    return params


def shard_pipelined_params(pipe_params: Dict, mesh: Mesh,
                           axis: str = "pipe") -> Dict:
    """Place the pipelined pytree: stage stack sharded over ``axis``
    (device s holds stage s's layers), embed/head replicated."""
    def put(path_is_stage, p):
        if path_is_stage:
            spec = P(axis, *([None] * (p.ndim - 1)))
        else:
            spec = P()
        return jax.device_put(p, NamedSharding(mesh, spec))

    out = {
        "embed": jax.tree_util.tree_map(lambda p: put(False, p),
                                        pipe_params["embed"]),
        "final_ln": jax.tree_util.tree_map(lambda p: put(False, p),
                                           pipe_params["final_ln"]),
        "stages": jax.tree_util.tree_map(lambda p: put(True, p),
                                         pipe_params["stages"]),
    }
    if "head" in pipe_params:
        out["head"] = jax.tree_util.tree_map(lambda p: put(False, p),
                                             pipe_params["head"])
    return out


def make_pipelined_lm_loss(config, mesh: Mesh, axis: str = "pipe",
                           num_microbatches: Optional[int] = None,
                           batch_axis: Optional[str] = None):
    """Build ``loss(pipe_params, tokens)`` — next-token cross-entropy of
    the transformer LM with its blocks running as a GPipe pipeline.

    Dense configs only: MoE blocks route over the ``model`` axis, which
    composes with tp, not pp-stage stacking. Attention inside a stage is
    always the XLA path (each stage owns the full local sequence; the
    Pallas kernel would need its own shard_map nesting).
    """
    from ..models.transformer import (block_apply, embed_apply, head_logits,
                                      next_token_loss)

    if config.num_experts > 1:
        raise ValueError(
            "pipelined LM training supports dense configs only "
            f"(num_experts={config.num_experts}); shard experts over the "
            "'model' axis with make_train_step instead")
    num_stages = mesh.shape[axis]
    per_stage = config.num_layers // num_stages
    if config.num_layers % num_stages:
        raise ValueError(f"{config.num_layers} layers do not split into "
                         f"{num_stages} equal pipeline stages")

    block = block_apply
    if config.remat:
        # recompute each block in the pipeline's backward sweep: with M
        # microbatches in flight GPipe keeps O(M) activations live per
        # stage, so per-block remat is the difference between activation
        # memory scaling with the *microbatch count* vs the *stage depth*
        block = jax.checkpoint(block_apply, static_argnums=(2,))

    def stage_fn(stage_params, x):
        for j in range(per_stage):
            layer = jax.tree_util.tree_map(lambda p: p[j], stage_params)
            x = block(layer, x, config)
        return x

    pipe_fn = make_pipeline_fn(stage_fn, mesh, axis=axis,
                               num_microbatches=num_microbatches,
                               batch_axis=batch_axis)

    def loss(pipe_params, tokens):
        x = embed_apply(pipe_params["embed"], tokens, config)
        x = pipe_fn(pipe_params["stages"], x)
        logits = head_logits(pipe_params["embed"], pipe_params["final_ln"],
                             x, head=pipe_params.get("head"),
                             norm=config.norm)
        return next_token_loss(logits, tokens)

    return loss


def make_pipelined_train_step(config, tx, mesh: Mesh, axis: str = "pipe",
                              num_microbatches: Optional[int] = None,
                              batch_axis: Optional[str] = None):
    """Jitted ``(pipe_params, opt_state, tokens) -> (pipe_params,
    opt_state, loss)``: forward + backward through the pipeline (gradient
    accumulation over microbatches via the scan transpose) and an optax
    update over the stage-stacked pytree, all in one compiled program.
    With ``batch_axis`` the step runs dp x pp: tokens shard over the
    data axis, each dp row pipelines its shard, and the loss mean makes
    GSPMD all-reduce the gradients across rows."""
    loss_fn = make_pipelined_lm_loss(config, mesh, axis=axis,
                                     num_microbatches=num_microbatches,
                                     batch_axis=batch_axis)

    def step(pipe_params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(pipe_params, tokens)
        updates, opt_state = tx.update(grads, opt_state, pipe_params)
        pipe_params = jax.tree_util.tree_map(lambda p, u: p + u,
                                             pipe_params, updates)
        return pipe_params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))
