"""GPipe-style pipeline parallelism over a ``pipe`` mesh axis.

The reference cannot do any model parallelism (``README.md:319-321`` calls
it "practically impossible" under Spark); on TPU it is a mesh axis. This
module implements the classic microbatched pipeline schedule as a pure
function under ``shard_map``:

- stage parameters are stacked along a leading axis sharded over ``pipe``
  (device s holds stage s),
- the batch splits into M microbatches; at tick t stage 0 injects
  microbatch t while every stage processes the activation it received
  last tick and ``ppermute``s its output to the next stage,
- after ``M + S - 1`` ticks the last stage has produced every microbatch;
  outputs are gathered with a masked ``psum`` so the result is replicated.

The schedule lives inside one ``lax.scan`` — XLA sees a static loop of
S-way-parallel stage computations with neighbor-only ICI transfers, which
is exactly the hardware-shaped formulation of GPipe. Differentiable end
to end (``shard_map``/``ppermute``/``scan`` all have transpose rules), so
``jax.grad`` of a pipelined loss just works; the backward pass is the
reverse pipeline.
"""
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

__all__ = ["make_pipeline_fn", "stack_stage_params"]


def stack_stage_params(per_stage_params):
    """Stack a list of per-stage parameter pytrees (identical structure)
    along a new leading axis — the axis that shards over ``pipe``."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


def make_pipeline_fn(stage_fn: Callable, mesh: Mesh, axis: str = "pipe",
                     num_microbatches: int = None):
    """Build ``fn(stacked_params, x) -> y`` running ``stage_fn`` as a
    microbatched pipeline over ``mesh[axis]``.

    :param stage_fn: ``(stage_params, x_micro) -> y_micro``, shape
        preserving (the activation flowing between stages must keep one
        shape, as in a stack of transformer blocks).
    :param num_microbatches: number of microbatches M (default: pipeline
        depth). The batch dimension must divide by M.
    """
    num_stages = mesh.shape[axis]
    M = num_microbatches or num_stages

    def pipelined(stacked_params, x):
        leading = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        if leading != num_stages:
            raise ValueError(
                f"stacked params hold {leading} stages but mesh axis "
                f"{axis!r} has {num_stages} devices — a mismatched stack "
                "would silently drop stages")
        if x.shape[0] % M:
            raise ValueError(f"batch {x.shape[0]} not divisible by "
                             f"{M} microbatches")
        micro = x.reshape((M, x.shape[0] // M) + x.shape[1:])

        def per_device(params_local, micro_local):
            # params_local leading dim is 1 (this device's stage slice)
            stage_params = jax.tree_util.tree_map(lambda p: p[0],
                                                  params_local)
            idx = jax.lax.axis_index(axis)
            num_ticks = M + num_stages - 1
            state0 = jnp.zeros_like(micro_local[0])

            def tick(state, t):
                # stage 0 injects microbatch t (clamped; injections past
                # M-1 never reach the collected output window)
                inject = jax.lax.dynamic_index_in_dim(
                    micro_local, jnp.minimum(t, M - 1), axis=0,
                    keepdims=False)
                x_in = jnp.where(idx == 0, inject, state)
                y = stage_fn(stage_params, x_in)
                # neighbor-only transfer: stage s -> s+1 over ICI
                state_next = jax.lax.ppermute(
                    y, axis, [(s, s + 1) for s in range(num_stages - 1)])
                return state_next, y

            _, ys = jax.lax.scan(tick, state0, jnp.arange(num_ticks))
            # microbatch m finishes on the LAST stage at tick m + S - 1;
            # mask everyone else and psum to replicate the result
            outs = jax.lax.dynamic_slice_in_dim(ys, num_stages - 1, M,
                                                axis=0)
            outs = jnp.where(idx == num_stages - 1, outs,
                             jnp.zeros_like(outs))
            return jax.lax.psum(outs, axis)

        in_spec = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
        y = jax.shard_map(per_device, mesh=mesh,
                          in_specs=(in_spec, P()), out_specs=P(),
                          check_vma=False)(stacked_params, micro)
        return y.reshape(x.shape[0:1] + y.shape[2:])

    return pipelined
