"""Synchronous data-parallel training over a device mesh.

Two flavors, matching SURVEY.md §2.2:

- :class:`SyncAverageTrainer` — the reference's ``synchronous`` semantics
  (each worker trains a full local model copy for all epochs on its
  partition, the driver averages the weight *deltas*,
  ``elephas/spark_model.py:217-228`` + ``elephas/worker.py:11-49``) —
  re-expressed the TPU way: all worker replicas are stacked on a leading
  ``workers`` axis sharded over the mesh, local training is a
  ``lax.scan`` over epochs×batches vmapped across workers, and the final
  delta average is a mean over the sharded axis (an XLA all-reduce over
  ICI). One jit-compiled program replaces one Spark job; there is no
  driver-side numpy merge loop.

- :class:`SyncStepTrainer` — true per-step synchronous SGD: the global
  batch is sharded over the ``data`` axis, parameters are replicated, and
  XLA inserts the gradient all-reduce (psum) automatically. Strictly
  stronger convergence than epoch-level model averaging and the benchmark
  configuration (SURVEY.md §7 step 4).

Shard-size edge cases (uneven partitions, empty partitions, the
reference's "skip training when partition <= batch_size" rule,
``elephas/worker.py:41``) are handled with static padding + per-sample
masks so XLA sees fixed shapes.
"""
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import NamedSharding, PartitionSpec

from ..models import losses as losses_mod
from ..models import metrics as metrics_mod
from ..models.core import BaseModel
from ..data.sources import ColumnSource, ParquetSource
from .mesh import worker_mesh


def _pad_to(arr: np.ndarray, size: int) -> np.ndarray:
    if arr.shape[0] == size:
        return arr
    pad = np.zeros((size - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def _take_rows(col, idx: np.ndarray) -> np.ndarray:
    """Materialize the given rows of an ndarray or lazy ColumnSource.
    (isinstance, not hasattr: ndarray.take defaults to axis=None, which
    would silently flatten a column of a mixed lazy/in-memory dataset.)"""
    return col.take(idx) if isinstance(col, ColumnSource) else col[idx]


#: inner chunks jointly shuffled per window — DERIVED from the sources'
#: decode LRU so the invariant window <= LRU can't silently break: a
#: window of W inner chunks touches at most W row groups per column,
#: all simultaneously resident, so windowed mixing costs zero extra
#: decodes
_SHUFFLE_WINDOW = ParquetSource._LRU_SIZE


def _epoch_permutation(x, y, n: int, n_pad: int, shuffle: bool,
                       rng) -> np.ndarray:
    """The epoch's sample visit order.

    In-memory data gets a global row permutation. File-backed columns
    get a *chunk* permutation matched to the sources' read granularity
    (Parquet row groups, shard files), hierarchically: the coarsest
    chunked column's groups set the outer visit order, the merged
    boundaries of ALL chunked columns cut each outer group into inner
    chunks, and rows shuffle jointly across a small WINDOW of inner
    chunks (:data:`_SHUFFLE_WINDOW`, sized to the decode LRU). Every
    inner chunk lies inside one group of every column, and a column's
    groups stay adjacent at their own level — so a shuffled streaming
    epoch decodes the coarse column's groups exactly once and a finer
    column's at most once per outer group it overlaps, instead of once
    per batch that touches them. The window is the within-batch mixing
    fix: shuffling rows only within one chunk left every global batch
    drawn from a single row group (highly correlated samples when the
    file is sorted); interleaving across a window mixes each batch over
    several row groups while the LRU keeps the decode-once property.
    (Chunk-level shuffle is the standard out-of-core trade: slightly
    less mixing for O(data) less decode IO.) Padding rows sort to the
    end; they are masked, never read.
    """
    if not shuffle:
        return np.arange(n_pad)
    all_bounds = [col.chunk_bounds() for col in (x, y)
                  if isinstance(col, ColumnSource)]
    all_bounds = [np.unique(np.clip(np.asarray(b, np.int64), 0, n))
                  for b in all_bounds if b is not None]
    if not all_bounds:
        return rng.permutation(n_pad)
    merged = np.unique(np.concatenate(all_bounds))
    coarse = min(all_bounds, key=len)
    parts = []
    for clo, chi in zip(coarse[:-1], coarse[1:]):
        inner = merged[(merged >= clo) & (merged <= chi)]
        chunks = [np.arange(lo, hi)
                  for lo, hi in zip(inner[:-1], inner[1:]) if hi > lo]
        if chunks:
            parts.append(chunks)
    ordered = []
    for ci in rng.permutation(len(parts)):
        chunks = parts[ci]
        for ii in rng.permutation(len(chunks)):
            ordered.append(chunks[ii])
    # windows deliberately straddle outer-group boundaries: with window
    # size == LRU size, a window's rows touch at most 2 consecutive
    # groups of EVERY column (both resident), so each group still
    # decodes once while batches mix across group boundaries
    out = []
    for w in range(0, len(ordered), _SHUFFLE_WINDOW):
        window = np.concatenate(ordered[w:w + _SHUFFLE_WINDOW])
        out.append(rng.permutation(window))
    out.append(np.arange(n, n_pad))
    return np.concatenate(out)


def _gather_lazy_batch(model, x, y, sl: np.ndarray, n: int):
    """Assemble one zero-padded training batch from (possibly
    file-backed) columns: only ``sl``'s in-range rows are read from
    disk; padding slots are zeros with weight 0 — numerically identical
    to the in-memory per-batch path's padded epoch arrays."""
    valid = sl < n
    rows_x = model._prepare_x(np.asarray(_take_rows(x, sl[valid])))
    rows_y = model._prepare_y(np.asarray(_take_rows(y, sl[valid])))
    xb = np.zeros((sl.size,) + rows_x.shape[1:], dtype=rows_x.dtype)
    yb = np.zeros((sl.size,) + rows_y.shape[1:], dtype=rows_y.dtype)
    xb[valid] = rows_x
    yb[valid] = rows_y
    return xb, yb, valid.astype(np.float32)


def stack_shards(shards: Sequence[Tuple[np.ndarray, np.ndarray]],
                 pad_multiple: int = 1):
    """Stack uneven (x, y) shards into masked fixed-shape arrays.

    Returns ``(X, Y, SW, sizes)`` with leading worker axis; ``SW`` is 1.0
    for real samples, 0.0 for padding.
    """
    sizes = np.array([x.shape[0] for x, _ in shards], dtype=np.int64)
    target = int(max(1, sizes.max()))
    if pad_multiple > 1:
        target = int(-(-target // pad_multiple) * pad_multiple)
    xs, ys, ws = [], [], []
    for x, y in shards:
        n = x.shape[0]
        xs.append(_pad_to(np.asarray(x), target))
        ys.append(_pad_to(np.asarray(y), target))
        w = np.zeros(target, dtype=np.float32)
        w[:n] = 1.0
        ws.append(w)
    return np.stack(xs), np.stack(ys), np.stack(ws), sizes


class SyncAverageTrainer:
    """Vectorized 'local training + delta averaging' on a worker mesh."""

    def __init__(self, model: BaseModel, optimizer, loss, metrics=None,
                 custom_objects: Optional[Dict] = None):
        self.model = model
        self.tx = optimizer.to_optax()
        self.loss_fn = losses_mod.get(loss, custom_objects)
        self.metric_fns = list(metrics or [])
        # jitted all-workers programs keyed by the run geometry — repeat
        # fits with the same shapes reuse the compiled program
        self._run_fns: Dict = {}
        # per-batch jitted steps for the conv path, keyed by batch shapes
        self._step_fns: Dict = {}

    def run(self, weights: List[np.ndarray],
            shards: Sequence[Tuple[np.ndarray, np.ndarray]],
            epochs: int, batch_size: int, validation_split: float = 0.0,
            shuffle: bool = True, seed: int = 0):
        """Train all workers in one jitted program.

        Returns ``(new_weights, histories)`` where histories is a list (one
        per worker) of Keras-style dicts.
        """
        model = self.model
        model.set_weights(weights)
        params0 = model.params
        num_workers = len(shards)

        # normalize dtypes/label ranks exactly as single-process fit does
        # (e.g. rank-1 regression labels -> (n, 1) to match the output rank)
        shards = [(model._prepare_x(x), model._prepare_y(y))
                  for x, y in shards]
        X, Y, SW, sizes = stack_shards(shards, pad_multiple=batch_size)
        # training mask: reference semantics — validation split carves off
        # the LAST fraction of each partition; training skipped entirely
        # when the partition is not larger than one batch.
        train_counts = (sizes * (1.0 - validation_split)).astype(np.int64)
        ar = np.arange(X.shape[1])[None, :]
        SW_train = (SW * (ar < train_counts[:, None])).astype(np.float32)
        active = (sizes > batch_size).astype(np.float32)

        n_pad = X.shape[1]
        nb = max(1, n_pad // batch_size)
        mesh = worker_mesh(num_workers)
        tx, loss_fn, metric_fns = self.tx, self.loss_fn, self.metric_fns
        epochs = int(epochs)
        # conv gradients inside scan bodies get pessimized layouts (see
        # SyncStepTrainer); for small batch counts, unroll the batch scan
        # inside the vmapped program (one dispatch, bounded graph); for
        # realistic partitions (nb > 16, where unrolling would blow up
        # compile time) switch to sequential per-worker training with a
        # per-batch jitted step — the same layout freedom the
        # SyncStepTrainer conv path gets, at parity-path dispatch cost
        from ..models.layers import Conv2D
        from .mesh import spans_processes

        try:
            has_conv = any(isinstance(l, Conv2D) for l in model.layers)
        except Exception:
            has_conv = False
        if has_conv and nb > 16 and not spans_processes(mesh):
            return self._run_per_batch(
                params0, X, Y, SW_train, active, epochs, batch_size, nb,
                n_pad, shuffle, seed, num_workers)
        batch_unroll = nb if (has_conv and nb <= 16) else 1

        def local_train(params0, x, y, sw, active_w, key):
            trainable0, state0 = model._split_params(params0)
            opt_state0 = tx.init(trainable0)

            def epoch_body(carry, key_e):
                trainable, state, opt_state = carry
                perm = (jax.random.permutation(key_e, n_pad) if shuffle
                        else jnp.arange(n_pad))
                xs = x[perm].reshape((nb, batch_size) + x.shape[1:])
                ys = y[perm].reshape((nb, batch_size) + y.shape[1:])
                sws = sw[perm].reshape((nb, batch_size))

                def batch_body(carry2, batch):
                    trainable, state, opt_state, i = carry2
                    xb, yb, swb = batch
                    key_b = jax.random.fold_in(key_e, i)

                    def objective(tr):
                        params = model._merge_params(tr, state)
                        preds, updates = model._apply_for_training(
                            params, xb, key_b)
                        per = loss_fn(yb, preds)
                        count = jnp.sum(swb)
                        mean_loss = jnp.sum(per * swb) / jnp.maximum(count, 1.0)
                        return mean_loss, (preds, updates, count)

                    (lval, (preds, updates, count)), grads = jax.value_and_grad(
                        objective, has_aux=True)(trainable)
                    opt_up, opt_state = tx.update(grads, opt_state, trainable)
                    trainable = optax.apply_updates(trainable, opt_up)
                    new_state = {ln: {**state.get(ln, {}), **lu}
                                 for ln, lu in updates.items()}
                    for ln in state:
                        new_state.setdefault(ln, state[ln])
                    stats = [lval * count, count]
                    for fn in metric_fns:
                        per_m = fn(yb, preds)
                        stats.append(jnp.sum(per_m * swb))
                    return (trainable, new_state, opt_state, i + 1), jnp.stack(stats)

                (trainable, state, opt_state, _), stats = jax.lax.scan(
                    batch_body, (trainable, state, opt_state, 0),
                    (xs, ys, sws), unroll=batch_unroll)
                totals = jnp.sum(stats, axis=0)
                count = jnp.maximum(totals[1], 1.0)
                epoch_stats = jnp.concatenate(
                    [totals[0:1] / count, totals[2:] / count])
                return (trainable, state, opt_state), epoch_stats

            keys = jax.random.split(key, epochs)
            (trainable, state, _), history = jax.lax.scan(
                epoch_body, (trainable0, state0, opt_state0), keys)
            params_final = model._merge_params(trainable, state)
            delta = jax.tree_util.tree_map(
                lambda a, b: (a - b) * active_w, params0, params_final)
            return delta, history

        from .mesh import spans_processes

        multihost = spans_processes(mesh)

        def all_workers(params0, X, Y, SW, active, keys):
            deltas, histories = jax.vmap(
                local_train, in_axes=(None, 0, 0, 0, 0, 0))(
                    params0, X, Y, SW, active, keys)
            # delta average over the sharded worker axis -> all-reduce
            mean_delta = jax.tree_util.tree_map(
                lambda d: jnp.mean(d, axis=0), deltas)
            new_params = jax.tree_util.tree_map(
                lambda p, d: p - d, params0, mean_delta)
            if multihost:
                # per-worker histories stay sharded over the worker axis —
                # replicate them so every host can fetch the full set
                histories = jax.lax.with_sharding_constraint(
                    histories, NamedSharding(mesh, PartitionSpec()))
            return new_params, histories

        from .mesh import replicate, shard_leading
        from ..utils.tracing import StepTimer

        self.timer = timer = StepTimer()
        with mesh:
            X_d = shard_leading(mesh, "workers", X)
            Y_d = shard_leading(mesh, "workers", Y)
            SW_d = shard_leading(mesh, "workers", SW_train)
            active_d = shard_leading(mesh, "workers", jnp.asarray(active))
            keys = jax.random.split(jax.random.PRNGKey(seed), num_workers)
            keys_d = shard_leading(mesh, "workers", keys)
            params_d = replicate(mesh, params0)
            run_key = (num_workers, X.shape, Y.shape, batch_size, epochs,
                       bool(shuffle), float(validation_split), multihost)
            run_fn = self._run_fns.get(run_key)
            if run_fn is None:
                run_fn = jax.jit(all_workers)
                self._run_fns[run_key] = run_fn
            timer.start()
            new_params, histories = run_fn(
                params_d, X_d, Y_d, SW_d, active_d, keys_d)

        model.params = jax.device_get(new_params)  # forces completion
        timer.stop()
        new_weights = model.get_weights()

        histories = np.asarray(jax.device_get(histories))  # (W, epochs, 1+M)
        # all workers run inside one compiled program, so the only
        # observable wall time is the whole fit's (compile excluded on
        # warm runs); surfaced per the survey's tracing requirement
        return new_weights, self._history_dicts(histories, active, timer)

    def _history_dicts(self, histories: np.ndarray, active, timer):
        """(W, epochs, 1+M) stat array -> per-worker Keras-style dicts
        (None for partitions the skip-small rule left untrained)."""
        metric_names = ["loss"] + [metrics_mod.serialize(fn) if not isinstance(fn, str)
                                   else fn for fn in self.metric_fns]
        history_dicts = []
        for w in range(histories.shape[0]):
            if active[w] == 0.0:
                history_dicts.append(None)  # parity: untrained partitions yield no history
                continue
            hist = {}
            for j, name in enumerate(metric_names):
                hist[name] = [float(v) for v in histories[w, :, j]]
            hist["fit_time"] = [timer.total]
            history_dicts.append(hist)
        return history_dicts

    def _run_per_batch(self, params0, X, Y, SW, active, epochs: int,
                       batch_size: int, nb: int, n_pad: int, shuffle: bool,
                       seed: int, num_workers: int):
        """Conv-model path for realistic partition sizes: sequential
        per-worker local training with a per-batch jitted step.

        XLA pessimizes conv-gradient layouts inside scan bodies (~25-50x,
        measured); vmapping workers over a scanned epoch cannot dispatch
        per batch, so past the unroll budget this path trades the single
        compiled program for per-batch dispatch with free layouts. RNG
        derivation (worker -> epoch -> batch keys) matches the vmapped
        program, and the delta-averaging semantics are identical
        (``elephas/spark_model.py:217-228`` parity).
        """
        model, tx = self.model, self.tx
        loss_fn, metric_fns = self.loss_fn, self.metric_fns

        shape_key = (X.shape[2:], Y.shape[2:], batch_size)
        step_fn = self._step_fns.get(shape_key)
        if step_fn is None:
            def step(trainable, state, opt_state, xb, yb, swb, key_b):
                def objective(tr):
                    params = model._merge_params(tr, state)
                    preds, updates = model._apply_for_training(
                        params, xb, key_b)
                    per = loss_fn(yb, preds)
                    count = jnp.sum(swb)
                    mean_loss = (jnp.sum(per * swb)
                                 / jnp.maximum(count, 1.0))
                    return mean_loss, (preds, updates, count)

                (lval, (preds, updates, count)), grads = jax.value_and_grad(
                    objective, has_aux=True)(trainable)
                opt_up, opt_state = tx.update(grads, opt_state, trainable)
                trainable = optax.apply_updates(trainable, opt_up)
                new_state = {ln: {**state.get(ln, {}), **lu}
                             for ln, lu in updates.items()}
                for ln in state:
                    new_state.setdefault(ln, state[ln])
                stats = [lval * count, count]
                for fn in metric_fns:
                    stats.append(jnp.sum(fn(yb, preds) * swb))
                return trainable, new_state, opt_state, jnp.stack(stats)

            # no donation: aliasing outputs into input buffers pins the
            # conv layouts (see SyncStepTrainer._build_step_fn)
            step_fn = jax.jit(step)
            self._step_fns[shape_key] = step_fn

        from ..utils.prefetch import prefetch_to_device
        from ..utils.tracing import StepTimer

        self.timer = timer = StepTimer()
        timer.start()
        trainable0, state0 = model._split_params(params0)
        init_fn = self._step_fns.setdefault("opt_init", jax.jit(tx.init))
        worker_keys = jax.random.split(jax.random.PRNGKey(seed), num_workers)
        histories = np.zeros((num_workers, epochs, 1 + len(metric_fns)))
        delta_sum = jax.tree_util.tree_map(
            lambda p: np.zeros_like(np.asarray(p)), params0)
        for w in range(num_workers):
            if active[w] == 0.0:
                continue  # zero delta, no history (skip-small rule)
            trainable, state = trainable0, state0
            opt_state = init_fn(trainable)
            epoch_keys = jax.random.split(worker_keys[w], epochs)
            x, y, sw = X[w], Y[w], SW[w]
            for e in range(epochs):
                key_e = epoch_keys[e]
                perm = (np.asarray(jax.random.permutation(key_e, n_pad))
                        if shuffle else np.arange(n_pad))
                xs, ys, sws = x[perm], y[perm], sw[perm]
                batch_stats = []

                # prefetch: batch i+1's host->device copy overlaps batch
                # i's compute (device_put is async) instead of blocking
                # at the top of every dispatch
                def slices():
                    for i in range(nb):
                        sl = slice(i * batch_size, (i + 1) * batch_size)
                        yield xs[sl], ys[sl], sws[sl]

                for i, (xb, yb, swb) in enumerate(
                        prefetch_to_device(slices(), size=2)):
                    trainable, state, opt_state, st = step_fn(
                        trainable, state, opt_state, xb, yb, swb,
                        jax.random.fold_in(key_e, i))
                    batch_stats.append(st)
                totals = np.sum(np.asarray(jax.device_get(batch_stats)),
                                axis=0)
                count = max(float(totals[1]), 1.0)
                histories[w, e] = np.concatenate(
                    [totals[0:1] / count, totals[2:] / count])
            params_final = model._merge_params(jax.device_get(trainable),
                                               jax.device_get(state))
            delta_sum = jax.tree_util.tree_map(
                lambda acc, a, b: acc + (np.asarray(a) - np.asarray(b)),
                delta_sum, params0, params_final)
        # mean over ALL workers (inactive ones contribute zero), exactly
        # like the vmapped program's mean over the sharded worker axis
        model.params = jax.tree_util.tree_map(
            lambda p, d: np.asarray(p) - d / num_workers, params0,
            delta_sum)
        timer.stop()
        return model.get_weights(), self._history_dicts(histories, active,
                                                        timer)


class SyncStepTrainer:
    """True per-step synchronous data-parallel SGD, one jit dispatch per epoch.

    Global batches are sharded over the ``data`` axis, parameters are
    replicated; XLA inserts the cross-device gradient all-reduce. The whole
    epoch — on-device shuffle + ``lax.scan`` over batches — is a single
    compiled program, so host<->device round-trips (the throughput killer on
    remote-attached TPUs) happen once per epoch, not once per step. This is
    the benchmark configuration (SURVEY.md §7's design stance).
    """

    def __init__(self, model: BaseModel, optimizer, loss, metrics=None,
                 custom_objects: Optional[Dict] = None, mesh=None,
                 donate: bool = True, epoch_mode: str = "auto"):
        self.model = model
        self.optimizer = optimizer
        self.tx = optimizer.to_optax()
        self.loss_fn = losses_mod.get(loss, custom_objects)
        self.metric_fns = list(metrics or [])
        from .mesh import data_mesh

        self.mesh = mesh if mesh is not None else data_mesh()
        # jitted epoch programs keyed by (nb, batch, shuffle): refitting
        # with the same geometry must NOT recompile (on conv nets the
        # XLA compile dwarfs the training itself)
        self._epoch_fns: Dict = {}
        self._donate = donate
        if epoch_mode not in ("auto", "scan", "per_batch"):
            raise ValueError("epoch_mode must be 'auto', 'scan' or "
                             f"'per_batch', got {epoch_mode!r}")
        # XLA pessimizes CONV GRADIENTS inside while-loop (scan) bodies —
        # forced layouts mean per-iteration transposes, measured ~20-50x
        # slower than the same step dispatched per batch. 'auto' keeps the
        # whole-epoch scan (one host round-trip per epoch) for dense
        # models and switches conv models to a per-batch jitted step.
        self._epoch_mode = epoch_mode
        self._step_fns: Dict = {}

    def _resolve_mode(self) -> str:
        if self._epoch_mode != "auto":
            return self._epoch_mode
        from ..models.layers import Conv2D

        try:
            has_conv = any(isinstance(l, Conv2D)
                           for l in self.model.layers)
        except Exception:
            has_conv = False
        return "per_batch" if has_conv else "scan"

    def _build_epoch_fn(self, nb: int, batch_size: int, shuffle: bool):
        model, tx, loss_fn = self.model, self.tx, self.loss_fn
        metric_fns = self.metric_fns
        n_pad = nb * batch_size

        def step(carry, batch):
            trainable, state, opt_state, key = carry
            xb, yb, swb = batch
            key, sub = jax.random.split(key)

            def objective(tr):
                params = model._merge_params(tr, state)
                preds, updates = model._apply_for_training(params, xb, sub)
                per = loss_fn(yb, preds)
                count = jnp.maximum(jnp.sum(swb), 1.0)
                return jnp.sum(per * swb) / count, (preds, updates, count)

            (lval, (preds, updates, count)), grads = jax.value_and_grad(
                objective, has_aux=True)(trainable)
            opt_up, opt_state = tx.update(grads, opt_state, trainable)
            trainable = optax.apply_updates(trainable, opt_up)
            new_state = {ln: {**state.get(ln, {}), **lu}
                         for ln, lu in updates.items()}
            for ln in state:
                new_state.setdefault(ln, state[ln])
            stats = [lval * count, count]
            stats += [jnp.sum(fn(yb, preds) * swb) for fn in metric_fns]
            return (trainable, new_state, opt_state, key), jnp.stack(stats)

        def epoch(trainable, state, opt_state, key, x, y, sw):
            if shuffle:
                perm_key, key = jax.random.split(key)
                perm = jax.random.permutation(perm_key, n_pad)
                x, y, sw = x[perm], y[perm], sw[perm]
            xs = x.reshape((nb, batch_size) + x.shape[1:])
            ys = y.reshape((nb, batch_size) + y.shape[1:])
            sws = sw.reshape((nb, batch_size))
            (trainable, state, opt_state, _), stats = jax.lax.scan(
                step, (trainable, state, opt_state, key), (xs, ys, sws))
            totals = jnp.sum(stats, axis=0)
            count = jnp.maximum(totals[1], 1.0)
            epoch_stats = jnp.concatenate([totals[0:1] / count,
                                           totals[2:] / count])
            return trainable, state, opt_state, epoch_stats

        donate = (0, 1, 2) if self._donate else ()
        return jax.jit(epoch, donate_argnums=donate)

    def _build_step_fn(self):
        """Single-batch jitted step for ``per_batch`` mode — same math as
        one scan tick, dispatched per batch (conv-friendly layouts)."""
        model, tx, loss_fn = self.model, self.tx, self.loss_fn
        metric_fns = self.metric_fns

        def step(trainable, state, opt_state, key, xb, yb, swb):
            key, sub = jax.random.split(key)

            def objective(tr):
                params = model._merge_params(tr, state)
                preds, updates = model._apply_for_training(params, xb, sub)
                per = loss_fn(yb, preds)
                count = jnp.maximum(jnp.sum(swb), 1.0)
                return jnp.sum(per * swb) / count, (preds, updates, count)

            (lval, (preds, updates, count)), grads = jax.value_and_grad(
                objective, has_aux=True)(trainable)
            opt_up, opt_state = tx.update(grads, opt_state, trainable)
            trainable = optax.apply_updates(trainable, opt_up)
            new_state = {ln: {**state.get(ln, {}), **lu}
                         for ln, lu in updates.items()}
            for ln in state:
                new_state.setdefault(ln, state[ln])
            stats = [lval * count, count]
            stats += [jnp.sum(fn(yb, preds) * swb) for fn in metric_fns]
            return trainable, new_state, opt_state, key, jnp.stack(stats)

        # NO donation here, deliberately: aliasing outputs into donated
        # input buffers pins the conv layouts to the inputs' and costs
        # ~3x per step (measured on resnet8) — the whole reason this
        # path exists is layout freedom for conv gradients
        return jax.jit(step)

    def fit(self, weights: List[np.ndarray], x: np.ndarray, y: np.ndarray,
            epochs: int, batch_size: int, validation_split: float = 0.0,
            shuffle: bool = True, seed: int = 0, verbose: int = 0,
            epoch_callback=None, timing: bool = True):
        """Train; returns (new_weights, history dict).

        ``epoch_callback(epoch_idx, logs) -> bool`` fires after each epoch
        with that epoch's metric means; returning True stops training. When
        set, the replica model's params are synced from device before each
        call (so the callback can snapshot/checkpoint weights) — this costs
        a device fetch per epoch, so it is opt-in.

        With ``timing=True`` (default) each epoch's wall time lands in
        ``history['epoch_time']`` — real time, not dispatch time, because
        the per-epoch stats fetch forces the epoch program to complete.
        ``timing=False`` skips that host round-trip (pure-throughput runs
        on remote-attached TPUs) unless verbose/callbacks need it anyway.
        """
        from .mesh import replicate, shard_leading

        model = self.model
        model.set_weights(weights)
        # file-backed columns stream: batches are read from disk as the
        # epoch progresses (per-batch dispatch), so peak host memory is
        # O(batch), never O(dataset) — the out-of-core training path
        lazy = isinstance(x, ColumnSource) or isinstance(y, ColumnSource)
        if not lazy:
            x = model._prepare_x(x)
            y = model._prepare_y(y)
        if validation_split and 0.0 < validation_split < 1.0:
            split_at = int(x.shape[0] * (1.0 - validation_split))
            x, y = x[:split_at], y[:split_at]  # lazy slices stay lazy

        mesh = self.mesh
        ndev = int(np.prod(mesh.devices.shape))
        # round the global batch up to a device multiple; mask the padding
        global_batch = int(-(-batch_size // ndev) * ndev)
        n = x.shape[0]
        nb = max(1, -(-n // global_batch))
        n_pad = nb * global_batch

        mode = "per_batch" if lazy else self._resolve_mode()
        if not lazy:
            sw = np.zeros(n_pad, dtype=np.float32)
            sw[:n] = 1.0
            x_pad, y_pad = _pad_to(x, n_pad), _pad_to(y, n_pad)
        if mode == "scan":
            # transfer the (padded) epoch data and parameters once
            x_d = shard_leading(mesh, "data", x_pad)
            y_d = shard_leading(mesh, "data", y_pad)
            sw_d = shard_leading(mesh, "data", sw)

        trainable, state = model._split_params(model.params)
        trainable = replicate(mesh, trainable)
        state = replicate(mesh, state)
        opt_state = jax.jit(self.tx.init)(trainable)

        if mode == "scan":
            cache_key = (nb, global_batch, bool(shuffle))
            epoch_fn = self._epoch_fns.get(cache_key)
            if epoch_fn is None:
                epoch_fn = self._build_epoch_fn(nb, global_batch, shuffle)
                self._epoch_fns[cache_key] = epoch_fn
        else:
            step_fn = self._step_fns.get("step")
            if step_fn is None:
                step_fn = self._build_step_fn()
                self._step_fns["step"] = step_fn
        base_key = jax.random.PRNGKey(seed)
        metric_names = ["loss"] + [metrics_mod.serialize(fn)
                                   for fn in self.metric_fns]
        from ..utils.tracing import StepTimer

        self.timer = timer = StepTimer()
        epoch_stats = []
        for epoch_idx in range(int(epochs)):
            key = jax.random.fold_in(base_key, epoch_idx)
            timer.start()
            if mode == "scan":
                trainable, state, opt_state, stats = epoch_fn(
                    trainable, state, opt_state, key, x_d, y_d, sw_d)
            else:
                # per-batch dispatch: conv-model path (conv grads inside
                # a scan get pessimized layouts); shuffle on host, one
                # sharded transfer + one jitted step per batch.
                # File-backed columns shuffle at chunk granularity so
                # the epoch decodes each row group once.
                perm = _epoch_permutation(
                    x, y, n, n_pad, shuffle,
                    np.random.default_rng(
                        np.asarray(jax.random.key_data(key))[-1]))
                batch_stats = []
                for b in range(nb):
                    sl = perm[b * global_batch:(b + 1) * global_batch]
                    if lazy:
                        xb_np, yb_np, swb_np = _gather_lazy_batch(
                            model, x, y, sl, n)
                    else:
                        xb_np, yb_np, swb_np = x_pad[sl], y_pad[sl], sw[sl]
                    xb = shard_leading(mesh, "data", xb_np)
                    yb = shard_leading(mesh, "data", yb_np)
                    swb = shard_leading(mesh, "data", swb_np)
                    trainable, state, opt_state, key, st = step_fn(
                        trainable, state, opt_state, key, xb, yb, swb)
                    batch_stats.append(st)
                totals = jnp.sum(jnp.stack(batch_stats), axis=0)
                count = jnp.maximum(totals[1], 1.0)
                stats = jnp.concatenate([totals[0:1] / count,
                                         totals[2:] / count])
            epoch_stats.append(stats)  # stays on device; fetched at the end
            if timing or verbose or epoch_callback is not None:
                # one host fetch serves timing, verbose and callbacks — and
                # fetching the stats forces the dispatched epoch program to
                # complete, which is what makes the recorded time real
                vals = np.asarray(stats)
            timer.stop()
            if verbose:
                print(f"Epoch {epoch_idx + 1}/{epochs} - " + " - ".join(
                    f"{name}: {val:.4f}"
                    for name, val in zip(metric_names, vals)))
            if epoch_callback is not None:
                logs = {name: float(val)
                        for name, val in zip(metric_names, vals)}
                # sync the resumable training state (params AND optimizer
                # moments) so checkpoint callbacks capture all of it
                model.params = model._merge_params(jax.device_get(trainable),
                                                   jax.device_get(state))
                model._opt_state = jax.device_get(opt_state)
                if epoch_callback(epoch_idx, logs):
                    break

        history: Dict[str, List[float]] = {}
        for stats in np.asarray(jax.device_get(epoch_stats)):
            for name, val in zip(metric_names, stats):
                history.setdefault(name, []).append(float(val))
        if timing:
            history["epoch_time"] = list(timer.durations)

        model.params = self.model._merge_params(
            jax.device_get(trainable), jax.device_get(state))
        return model.get_weights(), history


def build_sharded_predict(model: BaseModel, mesh=None):
    """Order-preserving sharded inference.

    The reference preserves order by tagging rows with indices, shuffling
    them through executors and re-sorting (``elephas/spark_model.py:257-266``).
    Contiguous sharding makes that dance unnecessary: rows are padded to a
    device multiple, sharded, predicted, and sliced back — order never
    changes.
    """
    from .mesh import data_mesh, replicate, shard_leading, spans_processes

    mesh = mesh if mesh is not None else data_mesh()
    ndev = int(np.prod(mesh.devices.shape))

    # multi-host meshes: all-gather the predictions onto every host (a
    # host cannot device_get shards living on another host's devices)
    out_sharding = (NamedSharding(mesh, PartitionSpec())
                    if spans_processes(mesh) else None)
    jit_apply = jax.jit(
        lambda params, xb: model.apply(params, xb, training=False),
        out_shardings=out_sharding)

    # the replicated param buffers persist across predict() calls;
    # set_weights swaps the model's params pytree object, so identity is
    # the invalidation key (re-uploading every call made each chunked
    # inference pay a full host->device weight transfer)
    cache: Dict[str, Any] = {"key": None, "value": None}

    def replicated_params():
        if cache["key"] is not model.params:
            cache["value"] = replicate(mesh, model.params)
            cache["key"] = model.params
        return cache["value"]

    def predict(x: np.ndarray, batch_size: int = 1024,
                out: Optional[np.ndarray] = None) -> np.ndarray:
        """``out``: optional preallocated array (e.g. a writable
        ``np.lib.format.open_memmap``) receiving predictions in place —
        with a file-backed ``x`` neither the inputs nor the outputs
        ever fully materialize in process memory."""
        lazy = isinstance(x, ColumnSource)
        if not lazy:
            x = model._prepare_x(x)
        n = x.shape[0]
        if n == 0:
            return (out if out is not None else
                    np.zeros((0,) + tuple(model.output_shape),
                             dtype=np.float32))
        chunk = int(-(-min(batch_size, n) // ndev) * ndev)
        params = replicated_params()
        outs = []
        for start in range(0, n, chunk):
            xc = x[start:start + chunk]
            if lazy:  # chunk-local materialization + dtype prep
                xc = model._prepare_x(np.asarray(xc))
            xb = _pad_to(xc, chunk)
            real = min(chunk, n - start)
            xb = shard_leading(mesh, "data", xb)
            res = np.asarray(jax.device_get(jit_apply(params, xb)))
            if out is not None:
                out[start:start + real] = res[:real]
            else:
                outs.append(res[:real])
        return out if out is not None else np.concatenate(outs, axis=0)

    return predict


def build_sharded_evaluate(model: BaseModel, loss, metrics=None,
                           custom_objects=None, mesh=None):
    """Sharded masked evaluation; exactly equals single-process evaluation
    because every metric is a per-sample mean (sample-count weighting,
    parity with ``elephas/spark_model.py:300-308``)."""
    from .mesh import data_mesh, replicate, shard_leading, spans_processes

    mesh = mesh if mesh is not None else data_mesh()
    ndev = int(np.prod(mesh.devices.shape))
    loss_fn = losses_mod.get(loss, custom_objects)
    metric_fns = list(metrics or [])

    def batch_stats(params, xb, yb, swb):
        preds = model.apply(params, xb, training=False)
        vals = [jnp.sum(loss_fn(yb, preds) * swb)]
        vals += [jnp.sum(fn(yb, preds) * swb) for fn in metric_fns]
        vals.append(jnp.sum(swb))
        return jnp.stack(vals)

    jit_stats = jax.jit(batch_stats, out_shardings=(
        NamedSharding(mesh, PartitionSpec())
        if spans_processes(mesh) else None))

    # replicated-param cache, as in build_sharded_predict
    cache: Dict[str, Any] = {"key": None, "value": None}

    def replicated_params():
        if cache["key"] is not model.params:
            cache["value"] = replicate(mesh, model.params)
            cache["key"] = model.params
        return cache["value"]

    def evaluate(x: np.ndarray, y: np.ndarray, batch_size: int = 1024):
        x_lazy = isinstance(x, ColumnSource)
        y_lazy = isinstance(y, ColumnSource)
        if not x_lazy:
            x = model._prepare_x(x)
        if not y_lazy:
            y = model._prepare_y(y)
        n = x.shape[0]
        chunk = int(-(-min(batch_size, max(n, 1)) // ndev) * ndev)
        params = replicated_params()
        totals = None
        for start in range(0, n, chunk):
            real = min(chunk, n - start)
            swb = np.zeros(chunk, dtype=np.float32)
            swb[:real] = 1.0
            xc = x[start:start + chunk]
            yc = y[start:start + chunk]
            if x_lazy:
                xc = model._prepare_x(np.asarray(xc))
            if y_lazy:
                yc = model._prepare_y(np.asarray(yc))
            vals = np.asarray(jax.device_get(jit_stats(
                params,
                shard_leading(mesh, "data", _pad_to(xc, chunk)),
                shard_leading(mesh, "data", _pad_to(yc, chunk)),
                shard_leading(mesh, "data", swb))))
            totals = vals if totals is None else totals + vals
        count = max(totals[-1], 1.0)
        results = [float(v / count) for v in totals[:-1]]
        return results if len(results) > 1 else results[0]

    return evaluate
