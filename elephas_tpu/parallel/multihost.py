"""Multi-host (DCN) execution helpers.

Scaling past one TPU host follows the single-controller JAX recipe
(SURVEY.md §7 step 7): every host runs the same program,
``jax.distributed.initialize`` wires the processes together over DCN, the
global mesh spans all hosts' devices (collectives ride ICI within a slice
and DCN across), and the parameter server for async modes binds on the
coordinator host (process 0) — workers reach it via
``ELEPHAS_TPU_MASTER_IP``.

Data is host-sharded: each process loads only its slice of the dataset
(:func:`host_local_slice`) and builds global arrays with
``jax.make_array_from_process_local_data``.
"""
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None):
    """Initialize the JAX distributed runtime (idempotent).

    Arguments default to the standard env vars
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID``) and to TPU-pod auto-detection when none are set.
    """
    # NOTE: the guard must not touch the XLA backend — jax.process_count()
    # would initialize it, after which jax.distributed.initialize() fails.
    try:
        from jax._src import distributed as _jax_distributed

        if _jax_distributed.global_state.client is not None:
            return  # already initialized
    except (ImportError, AttributeError):
        pass  # private API moved: fall through and let initialize() decide
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None and num_processes is None:
        try:
            jax.distributed.initialize()  # TPU-pod auto-detection
        except Exception:
            pass  # single-process run
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "1")),
        process_id=process_id if process_id is not None
        else int(os.environ.get("JAX_PROCESS_ID", "0")))


def ensure_multihost() -> bool:
    """Entry-point hook for :meth:`TPUModel.fit`: initialize the JAX
    distributed runtime when the standard env vars say this is a
    multi-process launch, and report whether the run spans processes.

    Deliberately env-gated — a plain single-host run must not trigger
    coordinator auto-detection (which could stall probing for a pod).

    Best-effort by construction: ``jax.distributed.initialize`` must run
    before anything touches the XLA backend, and building/compiling a
    model already does. If the backend beat us to it, warn with the fix
    (call :func:`initialize_multihost` — or ``elephas_tpu`` import-time
    auto-init — before building models) instead of crashing the fit.
    """
    if (os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("JAX_NUM_PROCESSES")):
        try:
            initialize_multihost()
        except RuntimeError as err:
            import warnings

            warnings.warn(
                "JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES are set but the "
                "distributed runtime could not be initialized here "
                f"({err}); jax.distributed.initialize must run before any "
                "JAX backend use. Import elephas_tpu (which auto-"
                "initializes from these env vars) or call "
                "elephas_tpu.parallel.initialize_multihost() before "
                "building models. Continuing single-process.",
                RuntimeWarning, stacklevel=2)
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def maybe_initialize_from_env():
    """Import-time hook: initialize the distributed runtime iff the
    standard env vars are present AND no XLA backend exists yet. Safe to
    call unconditionally; never raises."""
    if not (os.environ.get("JAX_COORDINATOR_ADDRESS")
            or os.environ.get("JAX_NUM_PROCESSES")):
        return
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            return
    except (ImportError, AttributeError):
        pass
    try:
        initialize_multihost()
    except Exception:
        pass  # fit()'s ensure_multihost will surface the warning


#: set to the barrier name after a timeout: further barriers in this
#: process refuse to run (the abandoned rendezvous could pair with them)
_POISONED_BARRIER: Optional[str] = None


def barrier(name: str, timeout_s: Optional[float] = None):
    """Cross-process rendezvous (no-op single-process).

    Bounded: if a peer process died, its side of the rendezvous never
    arrives and an unguarded ``sync_global_devices`` can block far past
    the coordination service's failure detection. The sync runs on a
    watchdog thread; on timeout (``ELEPHAS_TPU_BARRIER_TIMEOUT_S``,
    default 900 s) the caller gets a clear RuntimeError naming the
    barrier instead of a silent hang — the failure-detection contract
    (SURVEY §5) at the DCN level.

    Recovery requires a process restart: the watchdog thread stays
    parked (leaked) in the abandoned rendezvous, and the process's
    cross-process rendezvous state is undefined from then on — every
    later :func:`barrier` call in this process refuses to run
    (poisoned) rather than risk pairing the stale rendezvous with a
    different barrier on the peers.
    """
    global _POISONED_BARRIER
    if jax.process_count() <= 1:
        return
    if _POISONED_BARRIER is not None:
        # a previous timeout abandoned a watchdog thread still parked in
        # its rendezvous; letting a NEW sync start could pair the stale
        # rendezvous with a different barrier on the peers and corrupt
        # the protocol — this process must restart, not retry
        raise RuntimeError(
            f"barrier {_POISONED_BARRIER!r} timed out earlier; the "
            "cross-process rendezvous state of this process is "
            "undefined. Restart the process — training resumes from "
            "the latest checkpoint.")
    import threading

    from jax.experimental import multihost_utils

    if timeout_s is None:
        timeout_s = float(os.environ.get("ELEPHAS_TPU_BARRIER_TIMEOUT_S",
                                         "900"))
    outcome = {}

    def sync():
        try:
            multihost_utils.sync_global_devices(name)
            outcome["ok"] = True
        except Exception as err:  # noqa: BLE001 — re-raised on the caller
            outcome["err"] = err

    t = threading.Thread(target=sync, daemon=True, name=f"barrier-{name}")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        _POISONED_BARRIER = name
        raise RuntimeError(
            f"barrier {name!r} timed out after {timeout_s:.0f}s — a peer "
            "process likely died mid-run (crash or preemption), or is "
            "pathologically slow. The watchdog thread remains parked in "
            "the abandoned rendezvous (leaked) and this process's "
            "rendezvous state is now undefined: restart the process to "
            "recover; training resumes from the latest checkpoint. "
            "ELEPHAS_TPU_BARRIER_TIMEOUT_S tunes this deadline.")
    if "err" in outcome:
        raise outcome["err"]


def is_coordinator() -> bool:
    """True on process 0 — where the parameter server and checkpoint
    writes live."""
    return jax.process_index() == 0


def coordinator_bind_env(port: int = 4000) -> Optional[str]:
    """Share the coordinator's address with every process.

    Process 0 resolves its own IP and broadcasts it to all hosts (env vars
    do not cross host boundaries); every process then sets
    ``ELEPHAS_TPU_MASTER_IP`` locally so ``determine_master`` resolves the
    parameter server to the coordinator. Single-process runs just set the
    local env var.
    """
    import socket as pysocket

    preset = os.environ.get("ELEPHAS_TPU_MASTER_IP")
    if preset is not None and jax.process_count() <= 1:
        return preset

    if is_coordinator():
        # a preset on the coordinator wins and is broadcast to every host;
        # presets on non-coordinator hosts are overwritten so all processes
        # agree AND all enter the collective below (a per-host early return
        # would deadlock the others in broadcast_one_to_all)
        host = preset
        if not host:
            try:
                host = pysocket.gethostbyname(pysocket.gethostname())
            except pysocket.gaierror:
                host = "127.0.0.1"
    else:
        host = ""

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        encoded = np.zeros(64, dtype=np.uint8)
        raw = host.encode("utf8")[:64]
        encoded[:len(raw)] = np.frombuffer(raw, dtype=np.uint8)
        encoded = multihost_utils.broadcast_one_to_all(encoded)
        host = bytes(np.asarray(encoded)).rstrip(b"\x00").decode("utf8")

    os.environ["ELEPHAS_TPU_MASTER_IP"] = host
    return host


def global_data_mesh() -> Mesh:
    """1-D ``data`` mesh over every device of every host."""
    return Mesh(np.array(jax.devices()), ("data",))


def host_local_slice(n: int) -> Tuple[int, int]:
    """Row range [lo, hi) of a length-``n`` dataset this host should load
    (contiguous, balanced across processes)."""
    p = jax.process_count()
    i = jax.process_index()
    base, extra = divmod(n, p)
    lo = i * base + min(i, extra)
    return lo, lo + base + (1 if i < extra else 0)


def global_batch_from_host_data(mesh: Mesh, host_array: np.ndarray,
                                axis: str = "data"):
    """Assemble a globally-sharded array from per-host local rows."""
    spec = PartitionSpec(axis, *([None] * (host_array.ndim - 1)))
    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), host_array)
