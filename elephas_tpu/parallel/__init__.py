from .mesh import (data_mesh, hybrid_mesh, make_mesh, replicate,
                   shard_leading, spans_processes, worker_mesh)
from .multihost import (barrier, coordinator_bind_env, ensure_multihost,
                        global_batch_from_host_data, global_data_mesh,
                        host_local_slice, initialize_multihost,
                        is_coordinator)
from .pipeline import (make_pipeline_fn, make_pipelined_lm_loss,
                       make_pipelined_train_step, merge_transformer_stages,
                       shard_pipelined_params, split_transformer_stages,
                       stack_stage_params)
from .supervisor import QuorumLostError, SupervisorReport, WorkerSupervisor
from .sync_trainer import (SyncAverageTrainer, SyncStepTrainer,
                           build_sharded_evaluate, build_sharded_predict,
                           stack_shards)
