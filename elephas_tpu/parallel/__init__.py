from .mesh import data_mesh, make_mesh, replicate, shard_leading, worker_mesh
from .sync_trainer import (SyncAverageTrainer, SyncStepTrainer,
                           build_sharded_evaluate, build_sharded_predict,
                           stack_shards)
