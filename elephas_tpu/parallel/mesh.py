"""Device-mesh helpers.

The reference's "cluster" is a set of Spark executors; ours is a
``jax.sharding.Mesh`` over TPU chips. Intra-mesh communication rides ICI via
XLA collectives inserted by the partitioner — there is no hand-written
transport on the compute path (the NCCL analog the survey calls for,
SURVEY.md §2.3).
"""
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def worker_mesh(num_workers: int, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the ``workers`` axis.

    Picks the largest device count that evenly divides ``num_workers`` so a
    stacked per-worker computation shards cleanly; falls back to a single
    device when nothing divides (e.g. 3 workers on 8 chips -> 1 device,
    still correct, just unsharded).
    """
    devices = list(devices if devices is not None else jax.devices())
    d = 1
    for candidate in range(min(num_workers, len(devices)), 0, -1):
        if num_workers % candidate == 0:
            d = candidate
            break
    return Mesh(np.array(devices[:d]), ("workers",))


def data_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the ``data`` axis using all visible devices."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), ("data",))


def make_mesh(axis_sizes: Tuple[Tuple[str, int], ...],
              devices: Optional[Sequence] = None) -> Mesh:
    """N-D mesh from ``(axis_name, size)`` pairs (sizes must multiply to the
    device count used)."""
    devices = list(devices if devices is not None else jax.devices())
    names = [name for name, _ in axis_sizes]
    sizes = [size for _, size in axis_sizes]
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh of size {total} exceeds {len(devices)} devices")
    grid = np.array(devices[:total]).reshape(sizes)
    return Mesh(grid, tuple(names))


def shard_leading(mesh: Mesh, axis: str, array):
    """Place an array with its leading dim sharded over ``axis``."""
    spec = PartitionSpec(axis, *([None] * (np.ndim(array) - 1)))
    return jax.device_put(array, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, tree):
    """Replicate a pytree across the mesh."""
    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.tree_util.tree_map(lambda a: jax.device_put(a, sharding), tree)
