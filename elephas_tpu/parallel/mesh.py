"""Device-mesh helpers.

The reference's "cluster" is a set of Spark executors; ours is a
``jax.sharding.Mesh`` over TPU chips. Intra-mesh communication rides ICI via
XLA collectives inserted by the partitioner — there is no hand-written
transport on the compute path (the NCCL analog the survey calls for,
SURVEY.md §2.3).
"""
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def worker_mesh(num_workers: int, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the ``workers`` axis.

    Picks the largest device count that evenly divides ``num_workers`` so a
    stacked per-worker computation shards cleanly; falls back to fewer
    devices when nothing divides (still correct, just less parallel) — and
    says so, because silently running 5 workers on 1 of 8 chips is a perf
    cliff the user should hear about.
    """
    import warnings

    devices = list(devices if devices is not None else jax.devices())
    d = 1
    for candidate in range(min(num_workers, len(devices)), 0, -1):
        if num_workers % candidate == 0:
            d = candidate
            break
    ideal = min(num_workers, len(devices))
    if d < ideal:
        warnings.warn(
            f"num_workers={num_workers} does not divide across "
            f"{len(devices)} devices; the sync-average job will use only "
            f"{d} device(s). Pick a worker count that is a multiple (or "
            f"divisor) of the device count for full utilization.",
            RuntimeWarning, stacklevel=2)
    return Mesh(np.array(devices[:d]), ("workers",))


def data_mesh(devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the ``data`` axis using all visible devices."""
    devices = list(devices if devices is not None else jax.devices())
    return Mesh(np.array(devices), ("data",))


def make_mesh(axis_sizes: Tuple[Tuple[str, int], ...],
              devices: Optional[Sequence] = None) -> Mesh:
    """N-D mesh from ``(axis_name, size)`` pairs (sizes must multiply to the
    device count used)."""
    devices = list(devices if devices is not None else jax.devices())
    names = [name for name, _ in axis_sizes]
    sizes = [size for _, size in axis_sizes]
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh of size {total} exceeds {len(devices)} devices")
    grid = np.array(devices[:total]).reshape(sizes)
    return Mesh(grid, tuple(names))


def hybrid_mesh(axis_sizes: Tuple[Tuple[str, int], ...],
                dcn_axis: str = "data",
                devices: Optional[Sequence] = None) -> Mesh:
    """Multi-host-aware mesh: the ``dcn_axis`` spans processes (hosts /
    pod slices, traffic over DCN), every other axis stays inside a
    process (traffic over ICI) — the standard pod-scale layout where
    gradient all-reduce crosses hosts but tensor/sequence/expert
    collectives ride the fast intra-slice interconnect.

    ``axis_sizes`` gives TOTAL sizes, e.g. ``(("data", 8), ("model", 4))``
    on 4 hosts x 8 chips puts dp=2 per host x 4 hosts over DCN and tp=4
    over ICI. Falls back to a plain :func:`make_mesh` in single-process
    runs (tests, single host), so code can use it unconditionally.
    """
    devices = list(devices if devices is not None else jax.devices())
    n_proc = len({d.process_index for d in devices})
    names = [name for name, _ in axis_sizes]
    sizes = {name: int(size) for name, size in axis_sizes}
    if dcn_axis not in sizes:
        raise ValueError(f"dcn_axis {dcn_axis!r} not in {names}")
    if n_proc == 1:
        return make_mesh(axis_sizes, devices)
    if sizes[dcn_axis] % n_proc:
        raise ValueError(
            f"{dcn_axis}={sizes[dcn_axis]} must divide by the "
            f"{n_proc} processes it spans over DCN")
    from jax.experimental import mesh_utils

    ici_shape = [sizes[n] // n_proc if n == dcn_axis else sizes[n]
                 for n in names]
    dcn_shape = [n_proc if n == dcn_axis else 1 for n in names]
    try:
        # TPU pods: granule = slice (slice_index attr), DCN between slices
        grid = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices)
    except ValueError:
        # no slice_index info (CPU multi-process, single-slice pods):
        # granule by process instead
        grid = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devices, process_is_granule=True)
    return Mesh(grid, tuple(names))


def spans_processes(mesh: Mesh) -> bool:
    """True when the mesh includes devices of other processes (multi-host
    DCN execution) — placement must then go through global-array assembly
    instead of a plain ``device_put``."""
    me = jax.process_index()
    return any(d.process_index != me for d in mesh.devices.flat)


def _place(array, sharding, mesh: Mesh):
    import os

    force = os.environ.get("ELEPHAS_TPU_FORCE_GLOBAL_ASSEMBLY", "")
    if spans_processes(mesh) or force.lower() not in ("", "0", "false"):
        # every process holds the full array (single-controller API
        # contract) and uploads only the shards of its addressable
        # devices; the result is one global jax.Array spanning hosts.
        # The env flag forces this path on single-process meshes so the
        # multi-host assembly code is exercised by dryruns/CI without
        # real multi-process launches.
        array = np.asarray(array)
        return jax.make_array_from_callback(array.shape, sharding,
                                            lambda idx: array[idx])
    return jax.device_put(array, sharding)


def shard_leading(mesh: Mesh, axis: str, array):
    """Place an array with its leading dim sharded over ``axis``."""
    spec = PartitionSpec(axis, *([None] * (np.ndim(array) - 1)))
    return _place(array, NamedSharding(mesh, spec), mesh)


def replicate(mesh: Mesh, tree):
    """Replicate a pytree across the mesh."""
    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.tree_util.tree_map(lambda a: _place(a, sharding, mesh), tree)
