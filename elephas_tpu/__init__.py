"""elephas_tpu — distributed deep learning on TPU with JAX/XLA.

A TPU-native framework with the capability surface of Elephas (distributed
training, inference and evaluation of compiled models in synchronous,
asynchronous and hogwild modes; a parameter-server layer; MLlib-style and
ML-pipeline integration; save/load with embedded distributed config), built
on jax.sharding meshes, jit-compiled steps and XLA collectives instead of
Spark jobs and pickled RPC.
"""
__version__ = "0.1.0"

from . import models, utils
from .data import Dataset
from .tpu_model import TPUMatrixModel, TPUModel, load_tpu_model
