"""elephas_tpu — distributed deep learning on TPU with JAX/XLA.

A TPU-native framework with the capability surface of Elephas (distributed
training, inference and evaluation of compiled models in synchronous,
asynchronous and hogwild modes; a parameter-server layer; MLlib-style and
ML-pipeline integration; save/load with embedded distributed config), built
on jax.sharding meshes, jit-compiled steps and XLA collectives instead of
Spark jobs and pickled RPC.
"""
__version__ = "0.1.0"

# multi-host launches: jax.distributed.initialize must run before anything
# touches the XLA backend, so hook it at import (no-op unless
# JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES are set and no backend is up)
import os as _os

if (_os.environ.get("JAX_COORDINATOR_ADDRESS")
        or _os.environ.get("JAX_NUM_PROCESSES")):
    from .parallel.multihost import maybe_initialize_from_env as _mh_init

    _mh_init()

from . import models, obs, utils
from .data import Dataset
from .disagg import DisaggEngine, DisaggPool, PrefillWorker
from .fleet import FleetRouter, ReplicaPool
from .serving import TextGenerator
from .serving_engine import (DeadlineExceededError, DecodeEngine,
                             QueueFullError)
from .serving_http import ServingServer
from .serving_qos import TenantQoS
from .ssm_engine import SSMEngine
from .tpu_model import TPUMatrixModel, TPUModel, load_tpu_model
from .weightsync import CanaryController, WeightSubscriber
