"""Activation function registry.

String-named activations resolvable at model-deserialization time, with
``custom_objects`` lookup for user functions (the analog of Keras custom
activations exercised by the reference's custom-model tests,
``tests/integration/test_custom_models.py:14-38``).

All functions are pure ``jnp`` ops, so they trace cleanly under ``jit`` and
fuse into surrounding matmuls on the MXU.
"""
from typing import Callable, Dict, Optional, Union

import jax
import jax.numpy as jnp


def linear(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def tanh(x):
    return jnp.tanh(x)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def softplus(x):
    return jax.nn.softplus(x)


def elu(x):
    return jax.nn.elu(x)


def selu(x):
    return jax.nn.selu(x)


def gelu(x):
    return jax.nn.gelu(x)


def swish(x):
    return jax.nn.swish(x)


def leaky_relu(x):
    return jax.nn.leaky_relu(x)


def exponential(x):
    return jnp.exp(x)


def hard_sigmoid(x):
    return jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


_ACTIVATIONS: Dict[str, Callable] = {
    "linear": linear,
    "relu": relu,
    "sigmoid": sigmoid,
    "tanh": tanh,
    "softmax": softmax,
    "softplus": softplus,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "swish": swish,
    "silu": swish,
    "leaky_relu": leaky_relu,
    "exponential": exponential,
    "hard_sigmoid": hard_sigmoid,
}


def get(identifier: Union[str, Callable, None],
        custom_objects: Optional[Dict[str, Callable]] = None) -> Callable:
    """Resolve an activation from a name, callable or None (= linear)."""
    if identifier is None:
        return linear
    if callable(identifier):
        return identifier
    if custom_objects and identifier in custom_objects:
        return custom_objects[identifier]
    if identifier in _ACTIVATIONS:
        return _ACTIVATIONS[identifier]
    raise ValueError(f"Unknown activation: {identifier!r}")


def serialize(fn: Union[str, Callable, None]) -> Optional[str]:
    """Name under which an activation is persisted in model JSON."""
    if fn is None:
        return None
    if isinstance(fn, str):
        return fn
    for name, known in _ACTIVATIONS.items():
        if known is fn:
            return name
    return getattr(fn, "__name__", None)
