"""Weight initializers (Keras-compatible defaults: glorot_uniform kernels,
zeros biases)."""
from typing import Callable, Dict, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np


def _fans(shape: Sequence[int]):
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: (kh, kw, in, out)
    receptive = int(np.prod(shape[:-2]))
    return shape[-2] * receptive, shape[-1] * receptive


def zeros(key, shape, dtype=jnp.float32):
    return jnp.zeros(shape, dtype)


def ones(key, shape, dtype=jnp.float32):
    return jnp.ones(shape, dtype)


def glorot_uniform(key, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
    return jax.random.uniform(key, shape, dtype, -limit, limit)


def glorot_normal(key, shape, dtype=jnp.float32):
    fan_in, fan_out = _fans(shape)
    stddev = float(np.sqrt(2.0 / (fan_in + fan_out)))
    return stddev * jax.random.normal(key, shape, dtype)


def he_uniform(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    limit = float(np.sqrt(6.0 / fan_in))
    return jax.random.uniform(key, shape, dtype, -limit, limit)


def he_normal(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    stddev = float(np.sqrt(2.0 / fan_in))
    return stddev * jax.random.normal(key, shape, dtype)


def lecun_normal(key, shape, dtype=jnp.float32):
    fan_in, _ = _fans(shape)
    stddev = float(np.sqrt(1.0 / fan_in))
    return stddev * jax.random.normal(key, shape, dtype)


def random_uniform(key, shape, dtype=jnp.float32):
    return jax.random.uniform(key, shape, dtype, -0.05, 0.05)


def random_normal(key, shape, dtype=jnp.float32):
    return 0.05 * jax.random.normal(key, shape, dtype)


def truncated_normal(key, shape, dtype=jnp.float32):
    return 0.05 * jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)


def orthogonal(key, shape, dtype=jnp.float32):
    """Orthogonal matrix via QR (recurrent-kernel standard: preserves
    activation norms through the recurrence)."""
    if len(shape) < 2:
        return random_normal(key, shape, dtype)
    rows = shape[0]
    cols = 1
    for d in shape[1:]:
        cols *= int(d)
    n = max(rows, cols)
    a = jax.random.normal(key, (n, n), jnp.float32)
    q, r = jnp.linalg.qr(a)
    # sign correction makes the distribution uniform over O(n)
    q = q * jnp.sign(jnp.diagonal(r))
    return q[:rows, :cols].reshape(shape).astype(dtype)


_INITIALIZERS: Dict[str, Callable] = {
    "zeros": zeros,
    "ones": ones,
    "glorot_uniform": glorot_uniform,
    "glorot_normal": glorot_normal,
    "he_uniform": he_uniform,
    "he_normal": he_normal,
    "lecun_normal": lecun_normal,
    "random_uniform": random_uniform,
    "random_normal": random_normal,
    "truncated_normal": truncated_normal,
    "orthogonal": orthogonal,
}


def get(identifier: Union[str, Callable]) -> Callable:
    if callable(identifier):
        return identifier
    if identifier in _INITIALIZERS:
        return _INITIALIZERS[identifier]
    raise ValueError(f"Unknown initializer: {identifier!r}")
