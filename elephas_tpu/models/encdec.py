"""Encoder-decoder (seq2seq) transformer — translation/summarization
family (Vaswani et al. architecture).

Fourth transformer family next to the causal LM, the BERT encoder, and
ViT, completing the architecture matrix: a bidirectional encoder over
the source (padding-masked), a causal decoder over the target, and
cross-attention from every decoder block into the encoder outputs.
Shares the framework's sublayer helpers and Megatron tensor-parallel
spec shapes; the token embedding is shared between encoder, decoder,
and the (tied) output head.

Decoding runs with a self-attention KV cache plus per-layer
cross-attention K/V computed once from the encoder output — the
standard seq2seq serving split.
"""
import dataclasses
import functools
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import NEG_INF, attention
from .transformer import _dropout, _layer_norm, _mesh_divides

__all__ = ["EncDecConfig", "init_params", "param_specs", "encode",
           "decode_logits", "seq2seq_loss", "make_train_step",
           "greedy_decode", "shard_params"]


@dataclasses.dataclass(frozen=True)
class EncDecConfig:
    vocab_size: int = 32000
    num_encoder_layers: int = 6
    num_decoder_layers: int = 6
    num_heads: int = 8
    d_model: int = 512
    d_ff: int = 2048
    max_seq_len: int = 512
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    pad_token_id: int = 0
    #: decoder-input start token (teacher forcing begins from it)
    bos_token_id: int = 1
    eos_token_id: int = 2
    dropout_rate: float = 0.0
    #: T5-style relative position bias: > 0 adds a learned
    #: (buckets, heads) bias table to the encoder (bidirectional
    #: buckets) and decoder (causal buckets) self-attention — 0 disables.
    #: Shared across layers like T5; cross-attention carries none
    relative_position_buckets: int = 0
    #: distances beyond this share the last log-spaced bucket
    relative_position_max_distance: int = 128

    def __post_init__(self):
        if self.d_model % self.num_heads:
            raise ValueError("num_heads must divide d_model")
        if not 0.0 <= self.dropout_rate < 1.0:
            raise ValueError("dropout_rate must be in [0, 1)")
        if self.relative_position_buckets < 0:
            raise ValueError("relative_position_buckets must be >= 0")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


def _attn_params(keys, c, prefix_dim):
    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, c.param_dtype)
                / math.sqrt(fan_in))

    return {
        "wq": dense(keys[0], (c.d_model, c.num_heads, c.head_dim),
                    c.d_model),
        "wk": dense(keys[1], (prefix_dim, c.num_heads, c.head_dim),
                    prefix_dim),
        "wv": dense(keys[2], (prefix_dim, c.num_heads, c.head_dim),
                    prefix_dim),
        "wo": dense(keys[3], (c.num_heads, c.head_dim, c.d_model),
                    c.d_model),
    }


def _ln(c):
    return {"gamma": jnp.ones((c.d_model,), c.param_dtype),
            "beta": jnp.zeros((c.d_model,), c.param_dtype)}


def _mlp_params(keys, c):
    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, c.param_dtype)
                / math.sqrt(fan_in))

    return {"w1": dense(keys[0], (c.d_model, c.d_ff), c.d_model),
            "b1": jnp.zeros((c.d_ff,), c.param_dtype),
            "w2": dense(keys[1], (c.d_ff, c.d_model), c.d_ff),
            "b2": jnp.zeros((c.d_model,), c.param_dtype)}


def init_params(config: EncDecConfig, key) -> Dict:
    c = config
    n = 2 + c.num_encoder_layers + c.num_decoder_layers
    keys = jax.random.split(key, n)
    params: Dict[str, Any] = {
        "embed": {
            "tokens": 0.02 * jax.random.normal(
                keys[0], (c.vocab_size, c.d_model), c.param_dtype),
            "enc_pos": 0.02 * jax.random.normal(
                keys[1], (c.max_seq_len, c.d_model), c.param_dtype),
            "dec_pos": 0.02 * jax.random.normal(
                jax.random.fold_in(keys[1], 1),
                (c.max_seq_len, c.d_model), c.param_dtype),
        },
        "enc_final_ln": _ln(c),
        "dec_final_ln": _ln(c),
    }
    if c.relative_position_buckets:
        rk = jax.random.fold_in(keys[0], 7)
        params["rel_bias"] = {
            "enc": 0.02 * jax.random.normal(
                rk, (c.relative_position_buckets, c.num_heads),
                c.param_dtype),
            "dec": 0.02 * jax.random.normal(
                jax.random.fold_in(rk, 1),
                (c.relative_position_buckets, c.num_heads),
                c.param_dtype),
        }
    for i in range(c.num_encoder_layers):
        lk = jax.random.split(keys[2 + i], 6)
        params[f"enc_{i}"] = {
            "ln1": _ln(c), "attn": _attn_params(lk[:4], c, c.d_model),
            "ln2": _ln(c), "mlp": _mlp_params(lk[4:6], c),
        }
    off = 2 + c.num_encoder_layers
    for i in range(c.num_decoder_layers):
        lk = jax.random.split(keys[off + i], 10)
        params[f"dec_{i}"] = {
            "ln1": _ln(c), "attn": _attn_params(lk[:4], c, c.d_model),
            "ln_x": _ln(c), "cross": _attn_params(lk[4:8], c, c.d_model),
            "ln2": _ln(c), "mlp": _mlp_params(lk[8:10], c),
        }
    return params


def param_specs(config: EncDecConfig, model_axis: str = "model",
                mesh: Optional[Mesh] = None) -> Dict:
    """Megatron tensor-parallel specs; with ``mesh`` given, the head axis
    replicates when ``num_heads`` does not divide the model axis (same
    fallback rule as the other families)."""
    c = config
    shardable = (mesh is None
                 or _mesh_divides(mesh, model_axis, c.num_heads))
    ax = model_axis if shardable else None
    attn = {"wq": P(None, ax, None), "wk": P(None, ax, None),
            "wv": P(None, ax, None), "wo": P(ax, None, None)}
    ln = {"gamma": P(None), "beta": P(None)}
    mlp_shardable = (mesh is None
                     or _mesh_divides(mesh, model_axis, c.d_ff))
    mx = model_axis if mlp_shardable else None
    mlp = {"w1": P(None, mx), "b1": P(mx),
           "w2": P(mx, None), "b2": P(None)}
    specs: Dict[str, Any] = {
        "embed": {"tokens": P(model_axis, None), "enc_pos": P(None, None),
                  "dec_pos": P(None, None)},
        "enc_final_ln": dict(ln), "dec_final_ln": dict(ln),
    }
    if c.relative_position_buckets:
        h_bias_ax = (model_axis
                     if mesh is None
                     or _mesh_divides(mesh, model_axis, c.num_heads)
                     else None)
        specs["rel_bias"] = {"enc": P(None, h_bias_ax),
                             "dec": P(None, h_bias_ax)}
    for i in range(c.num_encoder_layers):
        specs[f"enc_{i}"] = {"ln1": dict(ln), "attn": dict(attn),
                             "ln2": dict(ln), "mlp": dict(mlp)}
    for i in range(c.num_decoder_layers):
        specs[f"dec_{i}"] = {"ln1": dict(ln), "attn": dict(attn),
                             "ln_x": dict(ln), "cross": dict(attn),
                             "ln2": dict(ln), "mlp": dict(mlp)}
    return specs


def _relative_buckets(rel_pos: jnp.ndarray, num_buckets: int,
                      max_distance: int, bidirectional: bool) -> jnp.ndarray:
    """T5's bucketing (Raffel et al., appendix): half the buckets for
    exact small offsets, half log-spaced out to ``max_distance``; the
    bidirectional variant splits buckets between signs."""
    ret = jnp.zeros_like(rel_pos)
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = ret + (n < 0).astype(jnp.int32) * num_buckets
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    val_large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / math.log(max_distance / max_exact)
        * (num_buckets - max_exact)).astype(jnp.int32)
    val_large = jnp.minimum(val_large, num_buckets - 1)
    return ret + jnp.where(is_small, n, val_large)


def _rel_bias(table: jnp.ndarray, q_len: int, k_len: int,
              config: "EncDecConfig", bidirectional: bool) -> jnp.ndarray:
    """(1, H, Tq, Tk) bias from a (buckets, heads) table."""
    c = config
    rel = (jnp.arange(k_len)[None, :] - jnp.arange(q_len)[:, None])
    buckets = _relative_buckets(rel, c.relative_position_buckets,
                                c.relative_position_max_distance,
                                bidirectional)
    bias = table.astype(jnp.float32)[buckets]        # (Tq, Tk, H)
    return bias.transpose(2, 0, 1)[None]


def _project(h, w, c):
    return jnp.einsum("btd,dhk->bhtk", h, w.astype(c.dtype))


def _attend(layer_attn, q_in, kv_in, mask, c, bias=None):
    """Pre-LN'd inputs -> attention output in model dim."""
    q = _project(q_in, layer_attn["wq"], c)
    k = _project(kv_in, layer_attn["wk"], c)
    v = _project(kv_in, layer_attn["wv"], c)
    o = attention(q, k, v, causal=False, mask=mask, bias=bias)
    return jnp.einsum("bhtk,hkd->btd", o, layer_attn["wo"].astype(c.dtype))


def _mlp(h, mlp, c):
    g = jax.nn.gelu(h @ mlp["w1"].astype(c.dtype)
                    + mlp["b1"].astype(c.dtype))
    return g @ mlp["w2"].astype(c.dtype) + mlp["b2"].astype(c.dtype)


def encode(params: Dict, src: jnp.ndarray, config: EncDecConfig,
           dropout_key=None) -> jnp.ndarray:
    """Source token ids ``(B, S)`` -> encoder states ``(B, S, D)``;
    padding excluded from every attention's key set."""
    c = config
    e = params["embed"]
    x = (e["tokens"][src] + e["enc_pos"][:src.shape[1]]).astype(c.dtype)
    src_mask = (src != c.pad_token_id)[:, None, None, :]
    enc_bias = (_rel_bias(params["rel_bias"]["enc"], src.shape[1],
                          src.shape[1], c, bidirectional=True)
                if c.relative_position_buckets else None)
    for i in range(c.num_encoder_layers):
        layer = params[f"enc_{i}"]
        lkey = (jax.random.fold_in(dropout_key, i)
                if dropout_key is not None else None)
        ak, mk = (jax.random.split(lkey) if lkey is not None
                  else (None, None))
        h = _layer_norm(x, layer["ln1"]["gamma"],
                        layer["ln1"]["beta"]).astype(c.dtype)
        x = x + _dropout(_attend(layer["attn"], h, h, src_mask, c,
                                 bias=enc_bias),
                         c.dropout_rate, ak)
        h = _layer_norm(x, layer["ln2"]["gamma"],
                        layer["ln2"]["beta"]).astype(c.dtype)
        x = x + _dropout(_mlp(h, layer["mlp"], c), c.dropout_rate, mk)
    return _layer_norm(x.astype(jnp.float32),
                       params["enc_final_ln"]["gamma"],
                       params["enc_final_ln"]["beta"]).astype(c.dtype)


def decode_logits(params: Dict, memory: jnp.ndarray, src: jnp.ndarray,
                  tgt_in: jnp.ndarray, config: EncDecConfig,
                  dropout_key=None) -> jnp.ndarray:
    """Teacher-forced decoder: encoder ``memory`` + decoder input ids
    ``(B, T)`` -> next-token logits ``(B, T, V)`` (f32)."""
    c = config
    e = params["embed"]
    x = (e["tokens"][tgt_in] + e["dec_pos"][:tgt_in.shape[1]]).astype(c.dtype)
    t = tgt_in.shape[1]
    causal = (jnp.arange(t)[None, :] <= jnp.arange(t)[:, None])[None, None]
    cross_mask = (src != c.pad_token_id)[:, None, None, :]
    dec_bias = (_rel_bias(params["rel_bias"]["dec"], t, t, c,
                          bidirectional=False)
                if c.relative_position_buckets else None)
    for i in range(c.num_decoder_layers):
        layer = params[f"dec_{i}"]
        lkey = (jax.random.fold_in(dropout_key, 1000 + i)
                if dropout_key is not None else None)
        ak, xk, mk = (jax.random.split(lkey, 3) if lkey is not None
                      else (None, None, None))
        h = _layer_norm(x, layer["ln1"]["gamma"],
                        layer["ln1"]["beta"]).astype(c.dtype)
        x = x + _dropout(_attend(layer["attn"], h, h, causal, c,
                                 bias=dec_bias),
                         c.dropout_rate, ak)
        h = _layer_norm(x, layer["ln_x"]["gamma"],
                        layer["ln_x"]["beta"]).astype(c.dtype)
        x = x + _dropout(_attend(layer["cross"], h, memory, cross_mask, c),
                         c.dropout_rate, xk)
        h = _layer_norm(x, layer["ln2"]["gamma"],
                        layer["ln2"]["beta"]).astype(c.dtype)
        x = x + _dropout(_mlp(h, layer["mlp"], c), c.dropout_rate, mk)
    x = _layer_norm(x.astype(jnp.float32), params["dec_final_ln"]["gamma"],
                    params["dec_final_ln"]["beta"])
    return x @ params["embed"]["tokens"].T.astype(jnp.float32)


def seq2seq_loss(params: Dict, src: jnp.ndarray, tgt: jnp.ndarray,
                 config: EncDecConfig, dropout_key=None) -> jnp.ndarray:
    """Teacher-forced cross-entropy: decoder input is ``[bos, tgt[:-1]]``,
    targets are ``tgt`` with padding positions masked out."""
    c = config
    memory = encode(params, src, c, dropout_key=dropout_key)
    bos = jnp.full((tgt.shape[0], 1), c.bos_token_id, tgt.dtype)
    tgt_in = jnp.concatenate([bos, tgt[:, :-1]], axis=1)
    logits = decode_logits(params, memory, src, tgt_in, c,
                           dropout_key=dropout_key)
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, tgt[..., None].astype(jnp.int32),
                                 axis=-1)[..., 0]
    w = (tgt != c.pad_token_id).astype(jnp.float32)
    return -jnp.sum(picked * w) / jnp.maximum(jnp.sum(w), 1.0)


def shard_params(params: Dict, config: EncDecConfig, mesh: Mesh,
                 model_axis: str = "model") -> Dict:
    specs = param_specs(config, model_axis=model_axis, mesh=mesh)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)


def make_train_step(config: EncDecConfig, tx):
    """Jitted ``(params, opt_state, src, tgt) -> (params, opt_state,
    loss)``; dropout configs take a REQUIRED trailing PRNG key (so a
    forgotten key is a loud TypeError, not silently-disabled dropout)."""
    use_dropout = config.dropout_rate > 0

    def step(params, opt_state, src, tgt, dropout_key):
        loss, grads = jax.value_and_grad(seq2seq_loss)(
            params, src, tgt, config, dropout_key=dropout_key)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    if not use_dropout:
        return jax.jit(lambda p, o, s, t: step(p, o, s, t, None),
                       donate_argnums=(0, 1))

    def with_key(params, opt_state, src, tgt, dropout_key):
        return step(params, opt_state, src, tgt, dropout_key)

    return jax.jit(with_key, donate_argnums=(0, 1))


# ---------------------------------------------------------------- decoding
def _dec_step(params: Dict, caches: Dict, cross_kv: Dict, src_mask,
              tok: jnp.ndarray, pos, config: EncDecConfig
              ) -> Tuple[jnp.ndarray, Dict]:
    """One incremental decoder step with a self-attention KV cache and
    precomputed cross-attention K/V."""
    c = config
    scale = 1.0 / math.sqrt(c.head_dim)
    e = params["embed"]
    x = (e["tokens"][tok] + e["dec_pos"][pos]).astype(c.dtype)   # (B, D)
    length = next(iter(caches.values()))["k"].shape[2]
    self_mask = (jnp.arange(length) <= pos)[None, None, :]
    if c.relative_position_buckets:
        rel = jnp.arange(length) - pos                     # (L,)
        buckets = _relative_buckets(rel, c.relative_position_buckets,
                                    c.relative_position_max_distance,
                                    bidirectional=False)
        dec_bias_row = params["rel_bias"]["dec"].astype(
            jnp.float32)[buckets].T[None]                  # (1, H, L)
    else:
        dec_bias_row = None
    new_caches: Dict = {}
    for i in range(c.num_decoder_layers):
        layer = params[f"dec_{i}"]
        h = _layer_norm(x, layer["ln1"]["gamma"],
                        layer["ln1"]["beta"]).astype(c.dtype)
        q = jnp.einsum("bd,dhk->bhk", h, layer["attn"]["wq"].astype(c.dtype))
        k_new = jnp.einsum("bd,dhk->bhk", h,
                           layer["attn"]["wk"].astype(c.dtype))
        v_new = jnp.einsum("bd,dhk->bhk", h,
                           layer["attn"]["wv"].astype(c.dtype))
        ck = caches[f"dec_{i}"]["k"].at[:, :, pos].set(k_new)
        cv = caches[f"dec_{i}"]["v"].at[:, :, pos].set(v_new)
        new_caches[f"dec_{i}"] = {"k": ck, "v": cv}
        s = jnp.einsum("bhk,bhtk->bht", q, ck) * scale
        if dec_bias_row is not None:
            s = s + dec_bias_row
        s = jnp.where(self_mask, s, NEG_INF)
        o = jnp.einsum("bht,bhtk->bhk", jax.nn.softmax(s, axis=-1), cv)
        x = x + jnp.einsum("bhk,hkd->bd", o,
                           layer["attn"]["wo"].astype(c.dtype))

        h = _layer_norm(x, layer["ln_x"]["gamma"],
                        layer["ln_x"]["beta"]).astype(c.dtype)
        q = jnp.einsum("bd,dhk->bhk", h, layer["cross"]["wq"].astype(c.dtype))
        s = jnp.einsum("bhk,bhtk->bht", q, cross_kv[f"dec_{i}"]["k"]) * scale
        s = jnp.where(src_mask, s, NEG_INF)
        o = jnp.einsum("bht,bhtk->bhk", jax.nn.softmax(s, axis=-1),
                       cross_kv[f"dec_{i}"]["v"])
        x = x + jnp.einsum("bhk,hkd->bd", o,
                           layer["cross"]["wo"].astype(c.dtype))

        h = _layer_norm(x, layer["ln2"]["gamma"],
                        layer["ln2"]["beta"]).astype(c.dtype)
        x = x + _mlp(h, layer["mlp"], c)
    x = _layer_norm(x.astype(jnp.float32), params["dec_final_ln"]["gamma"],
                    params["dec_final_ln"]["beta"])
    return x @ params["embed"]["tokens"].T.astype(jnp.float32), new_caches


def _cross_kv(params, memory, config: EncDecConfig):
    return {f"dec_{i}": {
        "k": _project(memory, params[f"dec_{i}"]["cross"]["wk"], config),
        "v": _project(memory, params[f"dec_{i}"]["cross"]["wv"], config)}
        for i in range(config.num_decoder_layers)}


@functools.partial(jax.jit, static_argnames=("max_len", "config",
                                              "sample"))
def _decode_scan(params, src, max_len: int, config: EncDecConfig,
                 sample: bool = False, temperature=1.0, key=None):
    c = config
    memory = encode(params, src, c)
    cross = _cross_kv(params, memory, c)
    src_mask = (src != c.pad_token_id)[:, None, :]
    batch = src.shape[0]
    caches = {f"dec_{i}": {
        "k": jnp.zeros((batch, c.num_heads, max_len, c.head_dim), c.dtype),
        "v": jnp.zeros((batch, c.num_heads, max_len, c.head_dim), c.dtype)}
        for i in range(c.num_decoder_layers)}
    if key is None:
        key = jax.random.PRNGKey(0)

    def step_fn(carry, pos):
        caches, tok, done, key = carry
        logits, caches = _dec_step(params, caches, cross, src_mask, tok,
                                   pos, c)
        if sample:
            key, sub = jax.random.split(key)
            nxt = jax.random.categorical(
                sub, logits / temperature, axis=-1).astype(src.dtype)
        else:
            nxt = jnp.argmax(logits, axis=-1).astype(src.dtype)
        nxt = jnp.where(done, jnp.asarray(c.eos_token_id, src.dtype), nxt)
        done = done | (nxt == c.eos_token_id)
        return (caches, nxt, done, key), nxt

    bos = jnp.full((batch,), c.bos_token_id, src.dtype)
    (_, _, _, _), out = jax.lax.scan(
        step_fn, (caches, bos, jnp.zeros((batch,), bool), key),
        jnp.arange(max_len))
    return out.T


def greedy_decode(params: Dict, src: jnp.ndarray, max_len: int,
                  config: EncDecConfig, temperature: float = 0.0,
                  key=None) -> jnp.ndarray:
    """Seq2seq decoding: ``(B, S)`` source ids -> ``(B, max_len)``
    target ids, stopping per row at eos (subsequent positions emit eos).
    ``temperature=0`` is greedy argmax; otherwise categorical sampling
    (``key`` required). One module-level jitted scan (compiled once per
    shape/config); cross-attention K/V computed once inside it."""
    c = config
    src = jnp.asarray(src)
    if max_len > c.max_seq_len:
        raise ValueError(f"max_len {max_len} exceeds max_seq_len "
                         f"{c.max_seq_len} (dec_pos table bound)")
    if src.shape[1] > c.max_seq_len:
        raise ValueError(f"source length {src.shape[1]} exceeds "
                         f"max_seq_len {c.max_seq_len}")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    return _decode_scan(params, src, int(max_len), c,
                        sample=temperature > 0,
                        temperature=jnp.float32(temperature or 1.0),
                        key=key)
