"""Draft-model distillation for speculative decoding.

:func:`make_distill_step` trains a SMALL transformer (the draft) to
imitate a frozen large one (the target) by minimizing the KL divergence
between their next-token distributions, optionally mixed with the plain
next-token cross-entropy. Distillation is what turns
:mod:`.speculative` from a primitive into a speedup: speculative
decoding emits ``1 + gamma * acceptance`` tokens per target weight
read, and acceptance is exactly "how often the draft's argmax/top-mass
matches the target's" — the quantity KL training maximizes directly
(unlike ground-truth-only training, which optimizes against the data
rather than against the model being served).

The step is one jitted function; the target runs forward-only under
``lax.stop_gradient`` semantics (its params are an argument but receive
no gradient), so XLA shares nothing with the draft's backward pass and
the target's activations are free to be released after the soft-label
softmax.

``tests/models/test_distill.py`` pins the loop's purpose end to end:
distilling a 1-layer draft against a trained 2-layer target RAISES the
measured speculative acceptance vs an undistilled draft on the same
prompts.
"""
from typing import Dict

import jax
import jax.numpy as jnp
import optax

from .transformer import TransformerConfig, forward

__all__ = ["distill_loss", "make_distill_step"]


def distill_loss(draft_params: Dict, target_params: Dict,
                 tokens: jnp.ndarray, draft_config: TransformerConfig,
                 target_config: TransformerConfig,
                 temperature: float = 1.0,
                 hard_weight: float = 0.0) -> jnp.ndarray:
    """Mean KL(target || draft) over next-token positions at the given
    softening ``temperature``, scaled by ``temperature**2`` (the
    standard correction keeping gradient magnitude comparable across
    temperatures); ``hard_weight`` mixes in ground-truth cross-entropy.
    """
    t_logits = jax.lax.stop_gradient(
        forward(target_params, tokens, target_config))      # (B, T, V)
    d_logits = forward(draft_params, tokens, draft_config)
    t_logp = jax.nn.log_softmax(
        t_logits[:, :-1].astype(jnp.float32) / temperature, axis=-1)
    d_logp = jax.nn.log_softmax(
        d_logits[:, :-1].astype(jnp.float32) / temperature, axis=-1)
    kl = jnp.sum(jnp.exp(t_logp) * (t_logp - d_logp), axis=-1)
    loss = (temperature ** 2) * jnp.mean(kl)
    if hard_weight > 0.0:
        targets = tokens[:, 1:]
        ce = -jnp.take_along_axis(
            jax.nn.log_softmax(d_logits[:, :-1].astype(jnp.float32), -1),
            targets[..., None], axis=-1)[..., 0]
        loss = loss + hard_weight * jnp.mean(ce)
    return loss


def make_distill_step(draft_config: TransformerConfig,
                      target_config: TransformerConfig, tx,
                      temperature: float = 1.0,
                      hard_weight: float = 0.0):
    """Build a jitted ``(draft_params, target_params, opt_state, tokens)
    -> (draft_params, opt_state, loss)`` step. The target is frozen —
    gradients flow only into the draft."""

    @jax.jit
    def step(draft_params, target_params, opt_state, tokens):
        loss, grads = jax.value_and_grad(distill_loss)(
            draft_params, target_params, tokens, draft_config,
            target_config, temperature, hard_weight)
        updates, opt_state = tx.update(grads, opt_state, draft_params)
        draft_params = optax.apply_updates(draft_params, updates)
        return draft_params, opt_state, loss

    return step
