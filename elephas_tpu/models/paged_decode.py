"""Paged KV cache: block-pool decode for memory-oversubscribed serving.

The engine's default cache gives every slot a contiguous
``max_len``-row strip — simple and fastest, but memory is reserved for
the worst case: ``max_slots × max_len`` positions whether requests use
them or not. Paged mode (vLLM's PagedAttention memory model) allocates
cache in fixed ``block_size``-position blocks from one shared pool;
each slot holds a small block table. Capacity then scales with TOKENS
IN FLIGHT, not worst-case sequence length — short requests and early
eos retirements return their blocks immediately, so a pool far smaller
than ``max_slots × max_len`` serves the same traffic (admission simply
queues when the pool is momentarily empty).

The trade: each step gathers the slot's blocks into attention order
(one extra O(cache) HBM pass versus reading a contiguous strip), so
paged mode is a CAPACITY lever, not a speed lever — exactly like the
int8 KV cache (BASELINE.md decode row). Use it when concurrency ×
max_len exceeds HBM, not to make a fitting workload faster.

Math mirrors :func:`~elephas_tpu.models.transformer.decode_block`
(S=1) exactly — same norms, RoPE convention, GQA grouping,
window/ALiBi masks — pinned by parity tests against the contiguous
engine. Safety invariant: block id 0 is a reserved scratch sink that
is never allocated; freed slots' tables are reset to 0, so an inactive
slot's garbage decode (the engine's static-batch idiom) can never
write into a block owned by a live request.

The pool is also the storage layer for AUTOMATIC prefix caching
(:mod:`~elephas_tpu.models.block_cache`): full prompt blocks are
content-addressed and shared across requests by table pointers —
:func:`gather_blocks_to_row` turns a cached chain back into a row head
for remainder prefill, and :func:`install_row_paged`'s ``start``
offset writes only the private remainder around shared blocks.

:func:`decode_block_paged` is the multi-position mirror of
:func:`decode_step_paged` — the target-verify pass of PAGED
speculative decoding: one forward scores ``S`` positions per row,
scattering their k/v into each row's own block table. Shared
prefix-cache blocks stay read-only under it for the same reason they
do under plain decode: every verify write lands at a position at or
past the prompt length, past every shared full block.

Not supported in paged mode (constructor raises): ``kv_cache_quant``
(compose the int8 cache with the contiguous engine instead) and MoE
layers.
"""
import math
from functools import partial
from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .transformer import (NEG_INF, TransformerConfig, _alibi_slope_list,
                          _alibi_slopes,
                          _apply_rope, _mlp_apply, _norm,
                          _sinusoidal_table, head_logits)

__all__ = ["init_paged_pool", "decode_step_paged", "decode_block_paged",
           "install_row_paged", "gather_blocks_to_row",
           "validate_paged_config", "export_kv_blocks",
           "import_kv_blocks", "export_pool_blocks",
           "install_pool_blocks"]


def validate_paged_config(config: TransformerConfig):
    if config.kv_cache_quant:
        raise ValueError("paged KV mode does not compose with "
                         "kv_cache_quant; use the contiguous engine for "
                         "the int8 cache")
    if config.num_experts > 1:
        raise ValueError("paged KV mode does not support MoE layers")


def init_paged_pool(config: TransformerConfig, num_blocks: int,
                    block_size: int) -> Dict:
    """Shared block pool: per layer ``k``/``v`` of shape
    ``(num_blocks, kv_heads, block_size, head_dim)``. Block 0 is the
    reserved scratch sink (allocators must hand out ids >= 1)."""
    validate_paged_config(config)
    c = config
    shape = (num_blocks, c.kv_heads, block_size, c.head_dim)
    return {f"layer_{i}": {"k": jnp.zeros(shape, c.dtype),
                           "v": jnp.zeros(shape, c.dtype)}
            for i in range(c.num_layers)}


def install_row_paged(pool: Dict, row_cache: Dict, block_ids,
                      nblocks: int, start: int = 0) -> Dict:
    """Scatter a contiguous batch-1 prefill row into pool blocks:
    positions ``[start*block_size, nblocks*block_size)`` of
    ``row_cache`` land in ``block_ids[start:nblocks]``. ``start > 0``
    is the prefix-cache-hit install: the first ``start`` table entries
    point at SHARED cached blocks that already hold those positions —
    writing them again would be wasted HBM traffic over blocks other
    slots are reading. One jit specialization per ``(start, nblocks)``
    pair (both bounded by the per-slot table width)."""
    return _install_jit(pool, row_cache, jnp.asarray(block_ids),
                        nblocks, start)


def _install(pool, row_cache, block_ids, nblocks: int, start: int = 0):
    out = {}
    n_write = nblocks - start
    for name, lc in pool.items():
        bs = lc["k"].shape[2]

        def to_blocks(row):                      # (H, L, D) -> blocks
            h, length, d = row.shape
            take = min(nblocks * bs, length)
            chunk = row[:, start * bs:take]
            if take < nblocks * bs:
                # max_len need not divide block_size: the final block's
                # tail holds zero padding that no position ever reads
                # (every valid position is < max_len)
                chunk = jnp.pad(chunk,
                                ((0, 0), (0, nblocks * bs - take),
                                 (0, 0)))
            return chunk.reshape(h, n_write, bs, d)

        chunk_k = to_blocks(row_cache[name]["k"][0])
        chunk_v = to_blocks(row_cache[name]["v"][0])
        ids = block_ids[start:nblocks]
        out[name] = {
            "k": lc["k"].at[ids].set(jnp.swapaxes(chunk_k, 0, 1)),
            "v": lc["v"].at[ids].set(jnp.swapaxes(chunk_v, 0, 1))}
    return out


_install_jit = jax.jit(_install, static_argnums=(3, 4),
                       donate_argnums=(0,))


def gather_blocks_to_row(pool: Dict, block_ids, max_len: int) -> Dict:
    """The inverse of :func:`install_row_paged`: read ``block_ids``'
    pool blocks back into a contiguous batch-1 row cache (``(1,
    kv_heads, max_len, head_dim)`` per layer k/v, zero past
    ``len(block_ids) * block_size``). This is how a prefix-cache HIT
    feeds the remainder prefill: the cached blocks become the row's
    head and :func:`~elephas_tpu.models.transformer.decode_block`
    extends past them — no recompute of the cached positions, one
    O(prefix) device gather instead. One jit specialization per block
    count (bounded by the per-slot table width)."""
    return _gather_jit(pool, jnp.asarray(block_ids), int(max_len))


@partial(jax.jit, static_argnums=(2,))
def _gather_jit(pool, block_ids, max_len: int):
    out = {}
    n = block_ids.shape[0]
    for name, lc in pool.items():
        bs = lc["k"].shape[2]

        def to_row(p):                          # blocks -> (1, H, L, D)
            sel = p[block_ids]                  # (n, H, bs, D)
            h, d = sel.shape[1], sel.shape[3]
            flat = jnp.swapaxes(sel, 0, 1).reshape(h, n * bs, d)
            return jnp.pad(flat, ((0, 0), (0, max_len - n * bs),
                                  (0, 0)))[None]

        out[name] = {"k": to_row(lc["k"]), "v": to_row(lc["v"])}
    return out


# --------------------------------------------------------------------------
# Off-engine block transfer — disaggregated prefill/decode.
#
# A prefill worker computes a contiguous batch-1 row cache and ships it
# to a decode worker in fixed ``block_size``-position blocks: the paged
# pool's native currency, and a bounded shape family (at most
# ``ceil(max_len / block_size)`` distinct block counts) so the decode
# side's install jit cannot churn one compile per prompt length. The
# exports are HOST numpy arrays — they exist to cross a socket
# (:mod:`elephas_tpu.disagg.wire`), not to stay on device.
# --------------------------------------------------------------------------

def _layer_names(row_cache: Dict) -> List[str]:
    """``layer_0..layer_{n-1}`` in index order — the canonical wire
    order, independent of dict insertion order."""
    return sorted(row_cache, key=lambda n: int(n.split("_", 1)[1]))


def export_kv_blocks(row_cache: Dict, length: int,
                     block_size: int) -> List[np.ndarray]:
    """Extract a batch-1 row cache's first ``length`` positions as
    block-unit host arrays: a flat ``[k_0, v_0, k_1, v_1, ...]`` list
    (layer index order) of shape ``(nblocks, kv_heads, block_size,
    head_dim)`` each, ``nblocks = ceil(length / block_size)``. The final
    block's tail is zero padding (no position past ``length`` is ever
    read after install — the same contract as
    :func:`install_row_paged`'s padding)."""
    length = int(length)
    bs = int(block_size)
    if length < 1 or bs < 1:
        raise ValueError("length and block_size must be >= 1")
    nb = -(-length // bs)
    out: List[np.ndarray] = []
    for name in _layer_names(row_cache):
        lc = row_cache[name]
        for part in ("k", "v"):
            row = np.asarray(lc[part])[0]          # (H, L, D)
            h, cached, d = row.shape
            if cached < length:
                raise ValueError(f"row cache holds {cached} positions, "
                                 f"cannot export {length}")
            chunk = np.zeros((h, nb * bs, d), row.dtype)
            chunk[:, :length] = row[:, :length]
            out.append(np.ascontiguousarray(
                chunk.reshape(h, nb, bs, d).swapaxes(0, 1)))
    return out


def import_kv_blocks(arrays: Sequence[np.ndarray], length: int,
                     max_len: int) -> Dict:
    """Reassemble :func:`export_kv_blocks` output into a contiguous
    batch-1 row cache dict (``{"layer_i": {"k", "v"}}``, each ``(1,
    kv_heads, max_len, head_dim)``) padded with zeros past ``length`` —
    ready for the decode engine's slot install (contiguous
    ``_install_fn`` or :func:`install_row_paged`)."""
    if not arrays or len(arrays) % 2:
        raise ValueError("KV block export must hold (k, v) pairs per "
                         f"layer, got {len(arrays)} arrays")
    length, max_len = int(length), int(max_len)
    if length > max_len:
        raise ValueError(f"length {length} exceeds max_len {max_len}")
    row: Dict = {}
    for i, (k_blocks, v_blocks) in enumerate(zip(arrays[0::2],
                                                 arrays[1::2])):
        parts = {}
        for part, blocks in (("k", k_blocks), ("v", v_blocks)):
            blocks = np.asarray(blocks)
            if blocks.ndim != 4:
                raise ValueError("KV block tensors must be (nblocks, "
                                 f"heads, block_size, head_dim), got "
                                 f"shape {blocks.shape}")
            nb, h, bs, d = blocks.shape
            if nb * bs < length:
                raise ValueError(f"{nb} blocks of {bs} positions cannot "
                                 f"cover length {length}")
            flat = blocks.swapaxes(0, 1).reshape(h, nb * bs, d)
            full = np.zeros((1, h, max_len, d), blocks.dtype)
            full[0, :, :length] = flat[:, :length]
            parts[part] = full
        row[f"layer_{i}"] = parts
    return row


def export_pool_blocks(pool: Dict, block_ids: Sequence[int]) -> List[Dict]:
    """Read pool blocks out to host payload dicts: one ``{layer: (k,
    v)}`` dict per id (each array ``(kv_heads, block_size, head_dim)``
    — the block cache's host payload format). One device->host gather
    per layer tensor regardless of block count. The KV spill tier's
    demotion read (:mod:`elephas_tpu.kvtier`) and the session store's
    persistence read."""
    ids = [int(b) for b in block_ids]
    if not ids:
        return []
    idx = jnp.asarray(ids)
    per_layer = {name: (np.asarray(lc["k"][idx]), np.asarray(lc["v"][idx]))
                 for name, lc in pool.items()}
    out: List[Dict] = []
    for i in range(len(ids)):
        out.append({name: (np.ascontiguousarray(ks[i]),
                           np.ascontiguousarray(vs[i]))
                    for name, (ks, vs) in per_layer.items()})
    return out


def install_pool_blocks(pool: Dict, payloads: Sequence[Dict],
                        block_ids: Sequence[int]) -> Dict:
    """Inverse of :func:`export_pool_blocks`: scatter host payload
    dicts into ``block_ids``' pool blocks (the spill tier's PROMOTION
    write — the same one host->device copy per block the host-mode
    cache trades on every hit). Payloads are cast to the pool dtype.
    One jit specialization per block count."""
    if len(payloads) != len(block_ids):
        raise ValueError(f"{len(payloads)} payloads for "
                         f"{len(block_ids)} block ids")
    if not payloads:
        return pool
    stacked = {}
    for name, lc in pool.items():
        dt = lc["k"].dtype
        stacked[name] = {
            "k": jnp.asarray(np.stack([np.asarray(p[name][0], np.float32)
                                       for p in payloads]), dt),
            "v": jnp.asarray(np.stack([np.asarray(p[name][1], np.float32)
                                       for p in payloads]), dt)}
    return _install_blocks_jit(pool, stacked,
                               jnp.asarray([int(b) for b in block_ids]))


@partial(jax.jit, donate_argnums=(0,))
def _install_blocks_jit(pool, blocks, block_ids):
    return {name: {"k": lc["k"].at[block_ids].set(blocks[name]["k"]),
                   "v": lc["v"].at[block_ids].set(blocks[name]["v"])}
            for name, lc in pool.items()}


def decode_step_paged(params: Dict, pool: Dict, tables: jnp.ndarray,
                      tokens: jnp.ndarray, pos,
                      config: TransformerConfig,
                      kernel: str = "gather",
                      interpret=None) -> Tuple[jnp.ndarray, Dict]:
    """One autoregressive step over the block pool: token ids ``(B,)``
    at per-row positions ``pos`` ``(B,)``; ``tables`` is ``(B,
    max_blocks)`` of block ids. Returns (logits ``(B, vocab)``, updated
    pool). The paged mirror of
    :func:`~elephas_tpu.models.transformer.decode_step`.

    ``kernel`` selects the attention inner loop: ``"gather"`` (default)
    materializes each row's blocks into attention order and runs a
    full-row masked softmax; ``"pallas"`` runs
    :func:`~elephas_tpu.ops.paged_attention.paged_decode_attention`,
    which fuses the block gather into a flash-style online-softmax
    kernel (no gathered copy — the decode hot-path saving). The two
    agree to float rounding (the online softmax associates the
    reduction differently), pinned by the variant-matrix parity tests.
    ``interpret`` is threaded to the Pallas kernel (tests force the
    interpreter off-TPU; production callers leave it ``None``)."""
    if kernel not in ("gather", "pallas"):
        raise ValueError(f"unknown paged decode kernel {kernel!r}; "
                         "expected 'gather' or 'pallas'")
    c = config
    b = tokens.shape[0]
    first = next(iter(pool.values()))["k"]
    bs = first.shape[2]
    mb = tables.shape[1]
    length = mb * bs                               # gathered view length
    pos = jnp.asarray(pos)
    blk = jnp.take_along_axis(tables, (pos // bs)[:, None],
                              axis=1)[:, 0]        # (B,) owning block
    off = pos % bs

    x = params["embed"]["tokens"][tokens]          # (B, D)
    if c.positional == "learned":
        x = x + params["embed"]["pos"][pos]
    elif c.positional == "sinusoidal":
        x = x + _sinusoidal_table(pos, c.d_model)
    x = x.astype(c.dtype)[:, None]                 # (B, 1, D)

    kpos = jnp.arange(length)
    mask = kpos[None, :] <= pos[:, None]           # (B, L)
    if c.attention_window is not None:
        mask = mask & (kpos[None, :] > (pos[:, None]
                                        - c.attention_window))
    scale = 1.0 / math.sqrt(c.head_dim)
    rp = pos[:, None, None]                        # (B, 1, 1) rope angles
    groups = c.num_heads // c.kv_heads
    hidx = jnp.arange(c.kv_heads)
    new_pool: Dict = {}
    for i in range(c.num_layers):
        layer = params[f"layer_{i}"]
        h = _norm(x, layer["ln1"], c).astype(c.dtype)
        q = jnp.einsum("bsd,dhk->bhsk", h,
                       layer["attn"]["wq"].astype(c.dtype))
        k_new = jnp.einsum("bsd,dhk->bhsk", h,
                           layer["attn"]["wk"].astype(c.dtype))
        v_new = jnp.einsum("bsd,dhk->bhsk", h,
                           layer["attn"]["wv"].astype(c.dtype))
        if c.positional == "rope":
            q = _apply_rope(q, rp, c)
            k_new = _apply_rope(k_new, rp, c)

        lc = pool[f"layer_{i}"]
        # scatter this position's k/v into each row's owning block:
        # target (block, head, offset) per (b, h)
        widx = (blk[:, None], hidx[None, :], off[:, None])
        pk = lc["k"].at[widx].set(k_new[:, :, 0])
        pv = lc["v"].at[widx].set(v_new[:, :, 0])
        new_pool[f"layer_{i}"] = {"k": pk, "v": pv}

        if kernel == "pallas":
            # fused path: the kernel's index maps stream each table
            # block straight from the pool — no gathered copy
            from ..ops.paged_attention import paged_decode_attention
            o = paged_decode_attention(
                q[:, :, 0], pk, pv, tables, pos,
                window=c.attention_window,
                alibi_slopes=(_alibi_slope_list(c.num_heads)
                              if c.positional == "alibi" else None),
                interpret=interpret)[:, :, None, :]
        else:
            # gather each row's blocks into attention order: (B, MB, H,
            # bs, D) -> (B, H, MB*bs, D). The one extra O(cache) pass
            # paged mode pays; positions beyond the row's allocation
            # land on stale/scratch data and are masked
            ck = jnp.swapaxes(pk[tables], 1, 2).reshape(
                b, c.kv_heads, length, c.head_dim)
            cv = jnp.swapaxes(pv[tables], 1, 2).reshape(
                b, c.kv_heads, length, c.head_dim)

            qg = q.reshape(b, c.kv_heads, groups, 1, c.head_dim)
            scores = jnp.einsum("bngsk,bntk->bngst", qg, ck) * scale
            if c.positional == "alibi":
                dist = (pos[:, None] - kpos[None, :]).astype(jnp.float32)
                ab = (-_alibi_slopes(c.num_heads)[None, :, None, None]
                      * dist[:, None, None]).reshape(b, c.kv_heads,
                                                     groups, 1, length)
                scores = scores + ab
            scores = jnp.where(mask[:, None, None, None, :], scores,
                               NEG_INF)
            weights = jax.nn.softmax(scores, axis=-1)
            o = jnp.einsum("bngst,bntk->bngsk", weights, cv)
            o = o.reshape(b, c.num_heads, 1, c.head_dim)
        x = x + jnp.einsum("bhsk,hkd->bsd", o,
                           layer["attn"]["wo"].astype(c.dtype))
        x = _mlp_apply(layer, x, c)
    logits = head_logits(params["embed"], params["final_ln"], x[:, 0],
                         head=params.get("head"), norm=c.norm)
    return logits, new_pool


def decode_block_paged(params: Dict, pool: Dict, tables: jnp.ndarray,
                       tokens: jnp.ndarray, pos0,
                       config: TransformerConfig) -> Tuple[jnp.ndarray,
                                                           Dict]:
    """Multi-token cached decode over the block pool: process ``(B, S)``
    tokens sitting at per-row positions ``pos0 .. pos0+S-1``, scattering
    each position's k/v into the owning block of that row's table, and
    return (logits ``(B, S, vocab)``, updated pool).

    The paged mirror of
    :func:`~elephas_tpu.models.transformer.decode_block` (vector-``pos0``
    form) and the ``S > 1`` generalization of :func:`decode_step_paged` —
    the verify pass of paged speculative decoding. Math matches
    ``decode_block`` exactly (norms, RoPE convention, GQA grouping,
    window/ALiBi masks); within the block each query attends causally to
    cache positions ``<= pos0 + j`` (all S positions' k/v are written
    before attention, so intra-block attention sees the new keys).
    Writes are confined to the row's own table — a row's rejected
    (stale) tail positions are masked until later rounds overwrite them
    and can never corrupt another row's blocks."""
    c = config
    b, s = tokens.shape
    first = next(iter(pool.values()))["k"]
    bs = first.shape[2]
    mb = tables.shape[1]
    length = mb * bs                               # gathered view length
    pos0 = jnp.asarray(pos0)
    blockpos = pos0[:, None] + jnp.arange(s)[None, :]        # (B, S)
    blk = jnp.take_along_axis(tables, blockpos // bs, axis=1)  # (B, S)
    off = blockpos % bs

    x = params["embed"]["tokens"][tokens]          # (B, S, D)
    if c.positional == "learned":
        x = x + params["embed"]["pos"][blockpos]
    elif c.positional == "sinusoidal":
        x = x + _sinusoidal_table(blockpos, c.d_model)
    x = x.astype(c.dtype)

    kpos = jnp.arange(length)
    mask = kpos[None, None, :] <= blockpos[:, :, None]       # (B, S, L)
    if c.attention_window is not None:
        mask = mask & (kpos[None, None, :]
                       > blockpos[:, :, None] - c.attention_window)
    scale = 1.0 / math.sqrt(c.head_dim)
    rp = blockpos[:, None, :]                      # (B, 1, S) rope angles
    groups = c.num_heads // c.kv_heads
    hidx = jnp.arange(c.kv_heads)
    # scatter target per (b, s): (block, head, offset) — broadcast to
    # (B, S, H). Distinct rows own disjoint tables; within a row the S
    # positions are distinct (block, offset) pairs; only inactive rows
    # (tables all zero) collide, and they collide on the scratch sink
    widx = (blk[:, :, None], hidx[None, None, :], off[:, :, None])
    new_pool: Dict = {}
    for i in range(c.num_layers):
        layer = params[f"layer_{i}"]
        h = _norm(x, layer["ln1"], c).astype(c.dtype)
        q = jnp.einsum("bsd,dhk->bhsk", h,
                       layer["attn"]["wq"].astype(c.dtype))
        k_new = jnp.einsum("bsd,dhk->bhsk", h,
                           layer["attn"]["wk"].astype(c.dtype))
        v_new = jnp.einsum("bsd,dhk->bhsk", h,
                           layer["attn"]["wv"].astype(c.dtype))
        if c.positional == "rope":
            q = _apply_rope(q, rp, c)
            k_new = _apply_rope(k_new, rp, c)

        lc = pool[f"layer_{i}"]
        # (B, H, S, D) -> (B, S, H, D) to line up with the (B, S, H)
        # scatter index
        pk = lc["k"].at[widx].set(jnp.swapaxes(k_new, 1, 2))
        pv = lc["v"].at[widx].set(jnp.swapaxes(v_new, 1, 2))
        new_pool[f"layer_{i}"] = {"k": pk, "v": pv}

        ck = jnp.swapaxes(pk[tables], 1, 2).reshape(
            b, c.kv_heads, length, c.head_dim)
        cv = jnp.swapaxes(pv[tables], 1, 2).reshape(
            b, c.kv_heads, length, c.head_dim)

        qg = q.reshape(b, c.kv_heads, groups, s, c.head_dim)
        scores = jnp.einsum("bngsk,bntk->bngst", qg, ck) * scale
        if c.positional == "alibi":
            dist = (blockpos[:, :, None] - kpos[None, None, :]).astype(
                jnp.float32)                       # (B, S, L)
            ab = (-_alibi_slopes(c.num_heads)[None, :, None, None]
                  * dist[:, None]).reshape(b, c.kv_heads, groups, s,
                                           length)
            scores = scores + ab
        scores = jnp.where(mask[:, None, None, :, :], scores, NEG_INF)
        weights = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("bngst,bntk->bngsk", weights, cv)
        o = o.reshape(b, c.num_heads, s, c.head_dim)
        x = x + jnp.einsum("bhsk,hkd->bsd", o,
                           layer["attn"]["wo"].astype(c.dtype))
        x = _mlp_apply(layer, x, c)
    logits = head_logits(params["embed"], params["final_ln"], x,
                         head=params.get("head"), norm=c.norm)
    return logits, new_pool
