"""Speculative decoding (draft-and-verify) for the transformer LM.

A small draft model proposes ``gamma`` tokens autoregressively; the
target model scores all of them in ONE cached block forward
(:func:`.transformer.decode_block`) and keeps the longest accepted
prefix plus one token of its own. Greedy verification reproduces the
target model's greedy decoding EXACTLY (the parity oracle in
``tests/models/test_speculative.py``); temperature sampling uses the
rejection rule of speculative sampling (accept draft token ``x`` with
probability ``min(1, p_target(x)/p_draft(x))``, on rejection resample
from ``norm(max(p_target - p_draft, 0))``), whose output distribution
provably equals sampling from the target alone.

TPU-first shape: the whole decode is one jitted ``lax.while_loop`` —
no host round trip per round, which matters doubly here because decode
is weight-bandwidth-bound: each round reads the target's weights ONCE
for ``gamma+1`` positions instead of once per token, so the target's
HBM traffic drops by up to ``(gamma+1)x`` at high acceptance. Rows
accept different numbers of tokens per round, so per-row cache
positions ride the vector-``pos`` support in ``decode_step`` /
``decode_block`` — a batch needs no acceptance synchronization and no
cache rollback (stale entries beyond a row's position are masked by
the causal length mask and overwritten before they can be attended).

:func:`speculative_round_paged` is the same round over a PAGED target
cache (:mod:`.paged_decode`): the verify pass scatters its ``gamma+1``
positions into the slot's own block table, so the "rollback" story is
identical — rejected positions land in blocks the table already owns,
masked until overwritten, and can never touch another slot's blocks
(tables are disjoint by construction; the serving engine budgets the
``gamma`` positions of verify slack into each slot's allocation). The
draft model's cache stays contiguous in both variants: draft KV is
small, private to the proposer, and never cached, shipped, or paged.

The reference has no serving path at all (inference is Spark
``mapPartitions`` batch prediction, ``elephas/spark_model.py:235-272``);
speculative decoding is a beyond-parity serving feature.
"""
from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp

from .paged_decode import decode_block_paged
from .transformer import (TransformerConfig, decode_block, decode_step,
                          prefill_cache)

__all__ = ["speculative_generate", "speculative_round",
           "speculative_round_paged"]


def _pick(logits, key, temperature, greedy: bool):
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), key
    key, sub = jax.random.split(key)
    return jax.random.categorical(sub, logits / temperature,
                                  axis=-1).astype(jnp.int32), key


def _draft_propose(draft_params, d_cache, last, p, gamma: int,
                   draft_config: TransformerConfig, temperature, key,
                   greedy: bool):
    """The draft half of a round: propose ``gamma`` tokens
    autoregressively on the draft's own rolling (contiguous) cache.
    Returns ``(d (B, gamma), d_logits list, d_cache, key)``."""
    dc = draft_config
    tok, d_toks, d_logits = last, [], []
    for j in range(gamma):
        lg, d_cache = decode_step(draft_params, d_cache, tok, p + j, dc)
        tok, key = _pick(lg, key, temperature, greedy)
        d_toks.append(tok)
        d_logits.append(lg)
    # cache-advance: process the last proposal too, so a fully accepted
    # round leaves no k/v hole at the next round's start (rejected
    # rounds leave stale tail entries, which the causal mask hides
    # until the next rounds overwrite them)
    _, d_cache = decode_step(draft_params, d_cache, tok, p + gamma, dc)
    return jnp.stack(d_toks, axis=1), d_logits, d_cache, key


def speculative_round(params, draft_params, t_cache, d_cache, last, p,
                      gamma: int, config: TransformerConfig,
                      draft_config: TransformerConfig, temperature, key,
                      greedy: bool):
    """One draft-propose / target-verify round at per-row positions.

    ``last`` ``(batch,)`` is each row's last emitted token, sitting at
    position ``p`` ``(batch,)`` of its sequence (neither cache has
    processed it yet; both are valid below ``p``). Returns
    ``(emit, a, nxt, t_cache, d_cache, key)``: row ``b`` emits
    ``emit[b, :a[b] + 1]`` — its accepted draft prefix with the
    target's own token at slot ``a[b]`` — and continues from
    ``nxt == emit[b, a[b]]`` at position ``p + a + 1``. Rejected tail
    slots of ``emit`` are meaningless.

    Shared by :func:`speculative_generate`'s fused while_loop and the
    continuous-batching engine's per-step speculative mode (where the
    host admits/retires requests between rounds); the accept/resample
    math is shared with :func:`speculative_round_paged` so the two
    cache layouts cannot drift.
    """
    c = config
    d, d_logits, d_cache, key = _draft_propose(
        draft_params, d_cache, last, p, gamma, draft_config, temperature,
        key, greedy)
    # ---- target verifies the whole block in one forward
    block = jnp.concatenate([last[:, None], d], axis=1)
    t_logits, t_cache = decode_block(params, t_cache, block, p, c)
    emit, a, nxt, key = _verify_emit(t_logits, d, d_logits, gamma,
                                     temperature, key, greedy)
    return emit, a, nxt, t_cache, d_cache, key


def speculative_round_paged(params, draft_params, pool, tables, d_cache,
                            last, p, gamma: int,
                            config: TransformerConfig,
                            draft_config: TransformerConfig, temperature,
                            key, greedy: bool):
    """:func:`speculative_round` over a PAGED target cache: the verify
    pass runs :func:`~elephas_tpu.models.paged_decode.decode_block_paged`
    against each row's block table, writing the round's ``gamma + 1``
    positions into the row's OWN blocks (the verify slack the serving
    engine budgets per slot). Returns ``(emit, a, nxt, pool, d_cache,
    key)`` — the exact contract of the contiguous round with the pool
    in the target cache's place. Rejected positions need no rollback:
    they sit past the row's accepted position, are masked by the causal
    length mask, and are overwritten by later rounds — and they can
    never land in another slot's blocks (or a shared prefix-cache
    block, which only ever covers positions below the prompt's
    full-block head) because the scatter targets only the row's table."""
    c = config
    d, d_logits, d_cache, key = _draft_propose(
        draft_params, d_cache, last, p, gamma, draft_config, temperature,
        key, greedy)
    block = jnp.concatenate([last[:, None], d], axis=1)
    t_logits, pool = decode_block_paged(params, pool, tables, block, p, c)
    emit, a, nxt, key = _verify_emit(t_logits, d, d_logits, gamma,
                                     temperature, key, greedy)
    return emit, a, nxt, pool, d_cache, key


def _verify_emit(t_logits, d, d_logits, gamma: int, temperature, key,
                 greedy: bool):
    """The accept/resample rule on the target's verify logits —
    layout-independent, shared by the contiguous and paged rounds."""
    b = d.shape[0]
    if greedy:
        tgt = jnp.argmax(t_logits, axis=-1).astype(jnp.int32)
        match = (tgt[:, :gamma] == d).astype(jnp.int32)
        accepted = jnp.cumprod(match, axis=1)        # agreeing prefix
        a = accepted.sum(axis=1)                     # (B,) in [0, g]
        nxt = jnp.take_along_axis(tgt, a[:, None], axis=1)[:, 0]
    else:
        dl = jnp.stack(d_logits, axis=1)             # (B, gamma, V)
        pt = jax.nn.softmax(t_logits / temperature, axis=-1)
        pd = jax.nn.softmax(dl / temperature, axis=-1)
        pt_d = jnp.take_along_axis(pt[:, :gamma], d[..., None],
                                   axis=-1)[..., 0]
        pd_d = jnp.take_along_axis(pd, d[..., None], axis=-1)[..., 0]
        key, sub = jax.random.split(key)
        u = jax.random.uniform(sub, (b, gamma))
        # accept iff u < pt/pd, written multiplication-safe
        accepted = jnp.cumprod((u * pd_d < pt_d).astype(jnp.int32),
                               axis=1)
        a = accepted.sum(axis=1)
        # resample slot: norm(max(pt - pd, 0)); past the last draft
        # slot (a == gamma) pd is zero and this is just pt's bonus
        pd_pad = jnp.concatenate(
            [pd, jnp.zeros_like(pt[:, :1])], axis=1)
        pt_a = jnp.take_along_axis(pt, a[:, None, None],
                                   axis=1)[:, 0]     # (B, V)
        pd_a = jnp.take_along_axis(pd_pad, a[:, None, None],
                                   axis=1)[:, 0]
        res = jnp.maximum(pt_a - pd_a, 0.0)
        res = res / jnp.maximum(res.sum(-1, keepdims=True), 1e-20)
        key, sub = jax.random.split(key)
        nxt = jax.random.categorical(
            sub, jnp.log(res + 1e-30), axis=-1).astype(jnp.int32)
    # ---- emit = accepted prefix with the target's token at slot a
    slots = jnp.arange(gamma + 1)[None, :]
    d_pad = jnp.concatenate([d, jnp.zeros_like(nxt[:, None])], axis=1)
    emit = jnp.where(slots == a[:, None], nxt[:, None], d_pad)
    return emit, a, nxt, key


@partial(jax.jit, static_argnames=("prompt_len", "max_new_tokens", "gamma",
                                   "config", "draft_config", "greedy"))
def _spec_loop(params, draft_params, prompt, temperature, key,
               prompt_len: int, max_new_tokens: int, gamma: int,
               config: TransformerConfig, draft_config: TransformerConfig,
               greedy: bool):
    c, dc = config, draft_config
    b, _ = prompt.shape
    # worst-case write position: a row clamped at count=max_new keeps
    # verifying blocks at p..p+gamma with p = prompt_len-1+max_new
    cache_len = prompt_len + max_new_tokens + gamma
    t_logits0, t_cache = prefill_cache(params, prompt, c, cache_len)
    _, d_cache = prefill_cache(draft_params, prompt, dc, cache_len)

    n0, key = _pick(t_logits0, key, temperature, greedy)
    out = jnp.zeros((b, max_new_tokens + gamma + 1), jnp.int32)
    out = out.at[:, 0].set(n0)
    count = jnp.ones((b,), jnp.int32)

    def cond(carry):
        return jnp.min(carry[3]) < max_new_tokens

    def body(carry):
        t_cache, d_cache, out, count, last, key, rounds, acc, props = carry
        # rows already at max_new idle while slower rows catch up; their
        # proposals are meaningless and stay out of the acceptance stat
        active = count < max_new_tokens                  # (B,)
        p = prompt_len - 1 + count                       # (B,) positions
        emit, a, nxt, t_cache, d_cache, key = speculative_round(
            params, draft_params, t_cache, d_cache, last, p, gamma, c, dc,
            temperature, key, greedy)
        slots = jnp.arange(gamma + 1)[None, :]
        idx = count[:, None] + slots
        idx = jnp.where(slots <= a[:, None], idx, out.shape[1])  # drop
        out = out.at[jnp.arange(b)[:, None], idx].set(emit, mode="drop")
        # clamp: finished rows idle in place (their writes land beyond
        # max_new and are sliced off) instead of running the cache past
        # its bound while slower rows catch up
        count = jnp.minimum(count + a + 1, max_new_tokens)
        return (t_cache, d_cache, out, count, nxt, key, rounds + 1,
                acc + jnp.where(active, a, 0).sum(),
                props + gamma * active.sum())

    carry = (t_cache, d_cache, out, count, n0, key,
             jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
             jnp.zeros((), jnp.int32))
    *_, out, count, _, _, rounds, acc, props = jax.lax.while_loop(
        cond, body, carry)
    return out[:, :max_new_tokens], rounds, acc, props


def speculative_generate(params: Dict, draft_params: Dict,
                         prompt: jnp.ndarray, max_new_tokens: int,
                         config: TransformerConfig,
                         draft_config: TransformerConfig,
                         gamma: int = 4, temperature: float = 0.0,
                         key=None, return_stats: bool = False):
    """Decode ``(batch, prompt_len)`` prompts with a draft model
    proposing ``gamma`` tokens per round and the target verifying them
    in one block forward; returns ``(batch, max_new_tokens)`` token ids
    (plus ``{"rounds", "draft_acceptance"}`` with ``return_stats``).

    ``temperature=0`` is greedy and reproduces the target model's own
    greedy decode token-for-token (exactly in f32; under bf16 compute
    the verify block and ``generate``'s scan round differently by
    ~5e-4, so an argmax near-tie can resolve differently — a property
    of compilation granularity, not of the algorithm);
    ``temperature>0`` is speculative sampling, distributionally
    identical to sampling the target alone (``key`` required). ``draft_acceptance`` is the fraction of draft
    proposals accepted — the dial that decides the speedup: emitted
    tokens per target-weight-read is ``1 + gamma * acceptance``.

    Uniform-length prompts only (the ragged path stays on
    :func:`generate`'s scan); both models must share a vocabulary.
    """
    c, dc = config, draft_config
    prompt = jnp.asarray(prompt)
    _, prompt_len = prompt.shape
    if dc.vocab_size != c.vocab_size:
        raise ValueError(
            f"draft vocab {dc.vocab_size} != target vocab {c.vocab_size}")
    if gamma < 1:
        raise ValueError("gamma must be >= 1")
    total = prompt_len + max_new_tokens + gamma
    for name, cfg in (("target", c), ("draft", dc)):
        if total > cfg.max_seq_len:
            raise ValueError(
                f"prompt_len + max_new_tokens + gamma = {total} exceeds "
                f"{name} max_seq_len = {cfg.max_seq_len}")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    if key is None:
        key = jax.random.PRNGKey(0)
    tokens, rounds, acc, props = _spec_loop(
        params, draft_params, prompt, jnp.float32(temperature), key,
        prompt_len, int(max_new_tokens), int(gamma), c, dc,
        not temperature > 0)  # <= 0 is greedy, matching generate()
    if not return_stats:
        return tokens
    return tokens, {"rounds": int(rounds),
                    "draft_acceptance": float(acc) / max(int(props), 1)}
