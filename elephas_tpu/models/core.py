"""Model core: Sequential and functional-graph models with jitted training.

A model is a *pure function* over a parameter pytree plus a serializable
architecture config. Nothing here holds device state implicitly: ``fit``,
``evaluate`` and ``predict`` are convenience loops over jit-compiled steps,
and the same ``apply`` is what the distributed layer shards over a device
mesh.

Design notes (TPU-first):
- All steps are ``jax.jit``-compiled once per batch shape; static shapes and
  Python-free inner loops keep XLA's MXU tiling and fusion intact.
- Parameters live in ``{layer_name: {param_name: array}}`` pytrees; weight
  exchange with the distributed layer is via ordered flat lists (the
  reference's ``get_weights``/``set_weights`` currency,
  ``elephas/spark_model.py:63``, ``elephas/worker.py:34``).
- BatchNorm moving statistics are a separate non-trainable collection
  threaded through the train step, keeping ``apply`` pure.

Capability parity: Keras ``Sequential``/functional ``Model`` usage in the
reference (``/root/reference/tests/conftest.py``, ``examples/*.py``),
``model.to_json``/``model_from_json`` with custom objects
(``elephas/worker.py:31``), compile/fit/evaluate/predict/train_on_batch.
"""
import json
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import losses as losses_mod
from . import metrics as metrics_mod
from . import optimizers as optimizers_mod
from .layers import (InputLayer, KTensor, Layer, deserialize_layer,
                     serialize_layer)

_MODEL_UID = [0]


def _cast_floats(tree, dtype):
    """Cast every floating leaf of a pytree (ints/bools untouched);
    non-array leaves (Python floats) become arrays of the target dtype."""
    def cast(a):
        a = jnp.asarray(a)
        return a.astype(dtype) if jnp.issubdtype(a.dtype, jnp.floating) else a
    return jax.tree_util.tree_map(cast, tree)


def _auto_name(prefix: str) -> str:
    _MODEL_UID[0] += 1
    return f"{prefix}_{_MODEL_UID[0]}"


class History:
    """Training history: dict of per-epoch metric lists (Keras-compatible)."""

    def __init__(self):
        self.history: Dict[str, List[float]] = {}

    def append(self, name: str, value: float):
        self.history.setdefault(name, []).append(float(value))


class BaseModel:
    """Shared machinery for Sequential and functional models."""

    def __init__(self, name: Optional[str] = None):
        self.name = name or _auto_name(type(self).__name__.lower())
        self.params: Optional[Dict] = None
        self.built = False
        self.optimizer: Optional[optimizers_mod.Optimizer] = None
        self.loss = None
        self.metrics: List = []
        self.metrics_names: List[str] = ["loss"]
        self.custom_objects: Dict[str, Any] = {}
        self._loss_fn: Optional[Callable] = None
        self._metric_fns: List[Callable] = []
        self._opt_state = None
        self._tx = None
        self._rng_seed: Optional[int] = None
        self._step_counter = 0
        self._jit_cache: Dict[str, Any] = {}
        #: mixed precision: compute dtype for forward/backward (params and
        #: optimizer state stay f32); set via compile(compute_dtype=...)
        self._compute_dtype = None
        #: callbacks set this mid-fit to end training after the epoch
        self.stop_training = False

    # ------------------------------------------------------------------ graph
    @property
    def layers(self) -> List[Layer]:
        raise NotImplementedError

    def _ordered_nodes(self) -> List[Tuple[Layer, List[int], int]]:
        """Topo-ordered (layer, input slot indices, output slot) triples."""
        raise NotImplementedError

    def _input_shapes(self) -> List[Tuple]:
        raise NotImplementedError

    @property
    def output_shape(self) -> Tuple:
        raise NotImplementedError

    # ------------------------------------------------------------------ build
    def build(self, input_shape: Optional[Tuple] = None, seed: Optional[int] = None):
        raise NotImplementedError

    def _ensure_built(self, x: Optional[np.ndarray] = None):
        if not self.built:
            shape = tuple(np.asarray(x).shape[1:]) if x is not None else None
            self.build(input_shape=shape)

    # ------------------------------------------------------------- params api
    def _weight_entries(self) -> List[Tuple[str, str]]:
        """Ordered (layer_name, param_name) pairs defining weight order."""
        entries = []
        for layer in self.layers:
            if not self.params or layer.name not in self.params:
                continue
            layer_params = self.params[layer.name]
            order = [k for k in layer.weight_order if k in layer_params]
            order += [k for k in sorted(layer_params) if k not in order]
            for key in order:
                entries.append((layer.name, key))
        return entries

    def get_weights(self) -> List[np.ndarray]:
        """Model weights as an ordered flat list of numpy arrays."""
        if self.params is None:
            raise ValueError("Model must be built before get_weights()")
        return [np.asarray(self.params[ln][pn]) for ln, pn in self._weight_entries()]

    def set_weights(self, weights: Sequence[np.ndarray]):
        """Load weights from an ordered flat list of arrays."""
        if self.params is None:
            raise ValueError("Model must be built before set_weights()")
        entries = self._weight_entries()
        if len(entries) != len(weights):
            raise ValueError(
                f"Expected {len(entries)} weight arrays, got {len(weights)}")
        new_params = {ln: dict(lp) for ln, lp in self.params.items()}
        for (ln, pn), w in zip(entries, weights):
            current = new_params[ln][pn]
            w = jnp.asarray(w, dtype=current.dtype)
            if w.shape != current.shape:
                raise ValueError(
                    f"Shape mismatch for {ln}/{pn}: {w.shape} vs {current.shape}")
            new_params[ln][pn] = w
        self.params = new_params
        # deliberately NOT invalidating the jit cache: the jitted steps
        # take params as traced arguments, and set_weights preserves every
        # shape/dtype, so the cached executables stay valid. Invalidating
        # here forced a full retrace per pull in the async batch loop and
        # per predict/evaluate call after a weight sync — recompiles that
        # dwarf the actual compute on a real TPU.

    # -------------------------------------------------- checkpoint state api
    def training_state(self) -> Dict:
        """Full resumable training state as a dict-of-arrays pytree:
        model params plus the optimizer state's leaves (dict-keyed, so
        both the orbax and npz checkpoint backends can store it) — the
        one shared encoding (``saving.pack_training_state``)."""
        from .saving import pack_training_state

        if self.params is None:
            raise ValueError("Model must be built before training_state()")
        return pack_training_state(self.params, self._opt_state)

    def restore_training_state(self, directory: str,
                               step: Optional[int] = None) -> Optional[int]:
        """Restore params + optimizer state saved by
        :class:`~elephas_tpu.models.callbacks.ModelCheckpoint`; returns the
        restored step.

        The model must be built and compiled with the same architecture.
        Auto-generated layer names differ between model instances (the uid
        counter keeps running), so param-bearing layers are renamed to the
        checkpoint's names positionally (order taken from the manifest's
        model json) before the state is adopted — this also makes the
        optimizer-state leaf order match the saved flatten order.
        """
        import json as _json

        from ..utils.checkpoint import CheckpointManager

        if not self.built:
            raise RuntimeError("build()/compile() the model (same "
                               "architecture) before restore_training_state")
        manager = CheckpointManager(directory)
        state = manager.restore(step)
        saved_params = state["params"]
        manifest = manager.manifest()
        if "model" in manifest:
            specs = _json.loads(manifest["model"])["config"]["layers"]
            names = [s.get("name") or s["config"]["name"] for s in specs]
            saved_order = [n for n in names if n in saved_params]
        else:  # no manifest: fall back to the stored key order
            saved_order = list(saved_params)
        current = [layer for layer in self.layers
                   if self.params and layer.name in self.params]
        if len(current) != len(saved_order):
            raise ValueError(
                f"checkpoint has {len(saved_order)} parameterized layers, "
                f"model has {len(current)} — architectures differ")
        for layer, saved_name in zip(current, saved_order):
            layer.name = saved_name
        self.params = {ln: {pn: jnp.asarray(v) for pn, v in lp.items()}
                       for ln, lp in saved_params.items()}
        leaves_dict = state.get("opt_state_leaves") or {}
        if leaves_dict:
            if self._tx is None:
                raise RuntimeError(
                    "checkpoint contains optimizer state but the model is "
                    "not compiled — compile() first (compiling after the "
                    "restore would silently reset the optimizer moments)")
            trainable, _ = self._split_params(self.params)
            ref = self._tx.init(trainable)
            treedef = jax.tree_util.tree_structure(ref)
            leaves = [jnp.asarray(leaves_dict[f"leaf_{i}"])
                      for i in range(len(leaves_dict))]
            self._opt_state = jax.tree_util.tree_unflatten(treedef, leaves)
        self._invalidate_jit()
        return step if step is not None else manager.latest_step()

    def _split_params(self, params: Dict) -> Tuple[Dict, Dict]:
        """Split into (trainable, non-trainable) collections."""
        trainable, state = {}, {}
        for layer in self.layers:
            if layer.name not in params:
                continue
            non_trainable = set(getattr(layer, "non_trainable", ()))
            t = {k: v for k, v in params[layer.name].items() if k not in non_trainable}
            s = {k: v for k, v in params[layer.name].items() if k in non_trainable}
            if t:
                trainable[layer.name] = t
            if s:
                state[layer.name] = s
        return trainable, state

    @staticmethod
    def _merge_params(trainable: Dict, state: Dict) -> Dict:
        merged = {ln: dict(lp) for ln, lp in trainable.items()}
        for ln, lp in state.items():
            merged.setdefault(ln, {}).update(lp)
        return merged

    # ------------------------------------------------------------------ apply
    def apply(self, params: Dict, inputs, training: bool = False, rng=None):
        """Pure forward pass. Safe to jit/vmap/shard_map. Under mixed
        precision (``compile(compute_dtype='bfloat16')``) params/inputs
        cast down for the compute and predictions cast back to f32."""
        if self._compute_dtype is not None:
            params = _cast_floats(params, self._compute_dtype)
            inputs = _cast_floats(inputs, self._compute_dtype)
        y, _ = self._apply_internal(params, inputs, training, rng,
                                    collect_updates=False)
        if self._compute_dtype is not None:
            y = _cast_floats(y, jnp.float32)
        return y

    def _apply_internal(self, params, inputs, training, rng, collect_updates):
        raise NotImplementedError

    def _apply_for_training(self, params, inputs, rng):
        """Training forward with the compile-level mixed-precision casts
        applied: compute runs in ``_compute_dtype`` (when set), while the
        returned predictions and state updates are f32 for the loss,
        metrics and state merge. The single entry point for every
        training objective (the model's own jitted step and the sharded
        trainers), so mixed precision holds on all paths."""
        if self._compute_dtype is not None:
            params = _cast_floats(params, self._compute_dtype)
            inputs = _cast_floats(inputs, self._compute_dtype)
        preds, updates = self._apply_internal(params, inputs, True, rng,
                                              collect_updates=True)
        if self._compute_dtype is not None:
            preds = _cast_floats(preds, jnp.float32)
            updates = _cast_floats(updates, jnp.float32)
        return preds, updates

    # ---------------------------------------------------------------- compile
    def compile(self, optimizer="rmsprop", loss=None, metrics=None,
                custom_objects: Optional[Dict] = None, seed: Optional[int] = None,
                compute_dtype: Optional[str] = None):
        """Attach optimizer, loss and metrics; builds params if shapes known.

        :param compute_dtype: ``'bfloat16'`` enables mixed precision —
            forward/backward run in bf16 (MXU-native, half the HBM
            traffic) while parameters, optimizer state, loss and metrics
            stay f32. bf16's f32-sized exponent needs no loss scaling.
        """
        custom_objects = {**self.custom_objects, **(custom_objects or {})}
        self.custom_objects = custom_objects
        if compute_dtype is not None:
            canonical = {"bf16": "bfloat16", "bfloat16": "bfloat16",
                         "float32": None, "fp32": None}
            if compute_dtype in ("float16", "fp16"):
                # fp16's 5-bit exponent underflows small gradients without
                # loss scaling, which this stack does not implement —
                # reject rather than silently fail to converge
                raise ValueError(
                    "compute_dtype='float16' needs loss scaling, which is "
                    "not implemented; use 'bfloat16' (f32-sized exponent, "
                    "no scaling needed)")
            if compute_dtype not in canonical:
                raise ValueError(
                    f"unsupported compute_dtype {compute_dtype!r}")
            name = canonical[compute_dtype]
            self._compute_dtype = jnp.dtype(name) if name else None
        else:
            self._compute_dtype = None
        self.optimizer = optimizers_mod.get(optimizer)
        if loss is None:
            raise ValueError("compile() requires a loss")
        self.loss = loss
        self._loss_fn = losses_mod.get(loss, custom_objects)
        self.metrics = list(metrics or [])
        names, fns = metrics_mod.resolve_metrics(self.metrics, loss=loss,
                                                 custom_objects=custom_objects)
        self.metrics_names = ["loss"] + names
        self._metric_fns = fns
        self._tx = self.optimizer.to_optax()
        self._opt_state = None
        if seed is not None:
            self._rng_seed = seed
        if not self.built:
            try:
                self.build()
            except (ValueError, TypeError):
                pass  # input shape unknown; built lazily at first fit
        self._invalidate_jit()
        return self

    @property
    def compiled(self) -> bool:
        return self._loss_fn is not None

    def _invalidate_jit(self):
        self._jit_cache = {}

    # ------------------------------------------------------------- rng helper
    def _next_key(self):
        if self._rng_seed is None:
            self._rng_seed = int(np.random.SeedSequence().generate_state(1)[0])
        self._step_counter += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._rng_seed),
                                  self._step_counter)

    # ------------------------------------------------------------ data prep
    def _prepare_y(self, y: np.ndarray) -> np.ndarray:
        y = np.asarray(y)
        loss_name = losses_mod.serialize(self.loss) if self.loss is not None else ""
        if loss_name == "sparse_categorical_crossentropy":
            return y.astype(np.int32)
        y = y.astype(np.float32)
        out_rank = len(self.output_shape) + 1  # + batch dim
        if y.ndim == out_rank - 1:
            y = y[..., None]
        return y

    @staticmethod
    def _prepare_x(x: np.ndarray) -> np.ndarray:
        x = np.asarray(x)
        if np.issubdtype(x.dtype, np.integer):
            return x
        return x.astype(np.float32)

    # ------------------------------------------------------------- train step
    def _build_train_step(self):
        tx = self._tx
        loss_fn = self._loss_fn
        metric_fns = list(self._metric_fns)

        def step(trainable, state, opt_state, key, xb, yb):
            def objective(tr):
                params = self._merge_params(tr, state)
                # mixed precision (when compiled so): compute in bf16,
                # master params and the loss/metric reductions stay f32
                # (grad of the cast casts back, so gradients land f32)
                preds, updates = self._apply_for_training(params, xb, key)
                per_sample = loss_fn(yb, preds)
                return jnp.mean(per_sample), (preds, updates)

            (loss_val, (preds, updates)), grads = jax.value_and_grad(
                objective, has_aux=True)(trainable)
            opt_updates, opt_state = tx.update(grads, opt_state, trainable)
            trainable = optax.apply_updates(trainable, opt_updates)
            new_state = {ln: {**state.get(ln, {}), **lu} for ln, lu in updates.items()}
            for ln in state:
                new_state.setdefault(ln, state[ln])
            metric_vals = [jnp.mean(fn(yb, preds)) for fn in metric_fns]
            return trainable, new_state, opt_state, loss_val, metric_vals

        return jax.jit(step)

    def _build_eval_step(self):
        loss_fn = self._loss_fn
        metric_fns = list(self._metric_fns)

        def step(params, xb, yb):
            preds = self.apply(params, xb, training=False)
            vals = [jnp.mean(loss_fn(yb, preds))]
            vals += [jnp.mean(fn(yb, preds)) for fn in metric_fns]
            return vals

        return jax.jit(step)

    def _build_predict_step(self):
        def step(params, xb):
            return self.apply(params, xb, training=False)

        return jax.jit(step)

    def _get_jitted(self, kind: str):
        if kind not in self._jit_cache:
            if kind == "train":
                self._jit_cache[kind] = self._build_train_step()
            elif kind == "eval":
                self._jit_cache[kind] = self._build_eval_step()
            elif kind == "predict":
                self._jit_cache[kind] = self._build_predict_step()
        return self._jit_cache[kind]

    # -------------------------------------------------------------------- fit
    def fit(self, x, y, epochs: int = 1, batch_size: int = 32, verbose: int = 0,
            validation_split: float = 0.0, validation_data=None,
            shuffle: bool = True, callbacks=None, **kwargs) -> History:
        """Train with mini-batch SGD. Returns a Keras-style History.

        ``callbacks`` is a list of
        :class:`~elephas_tpu.models.callbacks.Callback` objects; a callback
        may set ``model.stop_training = True`` (e.g. EarlyStopping) to end
        training after the current epoch.
        """
        if not self.compiled:
            raise RuntimeError("compile() the model before fit()")
        self._ensure_built(x)
        x = self._prepare_x(x)
        y = self._prepare_y(y)

        if validation_data is None and validation_split and 0.0 < validation_split < 1.0:
            split_at = int(x.shape[0] * (1.0 - validation_split))
            x, x_val = x[:split_at], x[split_at:]
            y, y_val = y[:split_at], y[split_at:]
            validation_data = (x_val, y_val)

        n = x.shape[0]
        trainable, state = self._split_params(self.params)
        if self._opt_state is None:
            self._opt_state = self._tx.init(trainable)
        opt_state = self._opt_state
        step = self._get_jitted("train")
        history = History()
        shuffle_rng = np.random.default_rng(self._rng_seed)

        from .callbacks import CallbackList

        self.stop_training = False
        cbs = CallbackList(callbacks, self)
        cbs.train_begin()

        # the epoch loop runs under try/finally so train_end fires even on
        # an interrupt/callback error — async ModelCheckpoint flushes its
        # background writes there (a skipped flush = torn manifest)
        try:
            self._run_epochs(cbs, step, trainable, state, opt_state, x, y, n,
                             epochs, batch_size, shuffle, shuffle_rng,
                             validation_data, verbose, history)
        finally:
            cbs.train_end()
        return history

    def _run_epochs(self, cbs, step, trainable, state, opt_state, x, y, n,
                    epochs, batch_size, shuffle, shuffle_rng,
                    validation_data, verbose, history):
        from ..utils.native import batch_iterator

        for epoch in range(int(epochs)):
            cbs.epoch_begin(epoch)
            order = shuffle_rng.permutation(n) if shuffle else np.arange(n)
            losses_sum, counts, metric_sums = 0.0, 0, None
            # shuffled gather + prefetch runs in the native loader's
            # background thread when built; numpy fallback otherwise.
            # INVARIANT: copy=False hands out views of the loader's ring
            # buffer, and a slot is only safe to recycle because the
            # float(loss_val) below blocks on the step — which has fully
            # consumed xb/yb — before the next batch is requested. If that
            # per-batch host fetch is ever deferred (e.g. for throughput),
            # switch to copy=True or block_until_ready the step outputs,
            # or the loader will overwrite buffers still in use.
            for batch_idx, (xb, yb) in enumerate(
                    batch_iterator((x, y), order, batch_size, copy=False)):
                key = self._next_key()
                trainable, state, opt_state, loss_val, metric_vals = step(
                    trainable, state, opt_state, key, xb, yb)
                bsz = xb.shape[0]
                batch_loss = float(loss_val)
                losses_sum += batch_loss * bsz
                counts += bsz
                vals = [float(v) for v in metric_vals]
                metric_sums = ([s + v * bsz for s, v in zip(metric_sums, vals)]
                               if metric_sums else [v * bsz for v in vals])
                if cbs:
                    cbs.batch_end(batch_idx, {"loss": batch_loss,
                                              "size": bsz})
            if counts:
                history.append("loss", losses_sum / counts)
                for name, total in zip(self.metrics_names[1:], metric_sums or []):
                    history.append(name, total / counts)
            # sync model state each epoch so callbacks (checkpointing,
            # weight snapshots) observe the current weights
            self.params = self._merge_params(trainable, state)
            self._opt_state = opt_state
            if validation_data is not None:
                val_results = self.evaluate(validation_data[0], validation_data[1],
                                            batch_size=batch_size, verbose=0)
                val_results = (val_results if isinstance(val_results, list)
                               else [val_results])
                for name, value in zip(self.metrics_names, val_results):
                    history.append("val_" + name, value)
            if verbose:
                msg = " - ".join(f"{k}: {v[-1]:.4f}" for k, v in history.history.items())
                print(f"Epoch {epoch + 1}/{epochs} - {msg}")
            cbs.epoch_end(epoch, {k: v[-1] for k, v in history.history.items()
                                  if v})
            if cbs:
                # a callback may have mutated the model (set_weights,
                # restore) — re-adopt its state so the next epoch trains
                # from what the callback left behind
                trainable, state = self._split_params(self.params)
                if self._opt_state is not None:
                    opt_state = self._opt_state
            if self.stop_training:
                break

        self.params = self._merge_params(trainable, state)
        self._opt_state = opt_state

    def train_on_batch(self, x, y):
        """Single optimization step on one batch; returns [loss, *metrics]."""
        if not self.compiled:
            raise RuntimeError("compile() the model before train_on_batch()")
        self._ensure_built(x)
        x = self._prepare_x(x)
        y = self._prepare_y(y)
        trainable, state = self._split_params(self.params)
        if self._opt_state is None:
            self._opt_state = self._tx.init(trainable)
        step = self._get_jitted("train")
        trainable, state, self._opt_state, loss_val, metric_vals = step(
            trainable, state, self._opt_state, self._next_key(), x, y)
        self.params = self._merge_params(trainable, state)
        if metric_vals:
            return [float(loss_val)] + [float(v) for v in metric_vals]
        return float(loss_val)

    # --------------------------------------------------------------- evaluate
    def evaluate(self, x, y, batch_size: int = 32, verbose: int = 0,
                 **kwargs) -> Union[List[float], float]:
        """Sample-weighted mean of loss and metrics over the dataset."""
        if not self.compiled:
            raise RuntimeError("compile() the model before evaluate()")
        self._ensure_built(x)
        x = self._prepare_x(x)
        y = self._prepare_y(y)
        step = self._get_jitted("eval")
        n = x.shape[0]
        sums = None
        for start in range(0, n, batch_size):
            xb, yb = x[start:start + batch_size], y[start:start + batch_size]
            vals = [float(v) * xb.shape[0] for v in step(self.params, xb, yb)]
            sums = [s + v for s, v in zip(sums, vals)] if sums else vals
        results = [s / n for s in sums] if sums else [0.0]
        return results if len(results) > 1 else results[0]

    # ---------------------------------------------------------------- predict
    def predict(self, x, batch_size: int = 32, verbose: int = 0,
                **kwargs) -> np.ndarray:
        """Forward inference in fixed-size batches (last batch padded so a
        single compiled executable serves the whole pass)."""
        self._ensure_built(x)
        x = self._prepare_x(x)
        step = self._get_jitted("predict")
        n = x.shape[0]
        outputs = []
        for start in range(0, n, batch_size):
            xb = x[start:start + batch_size]
            real = xb.shape[0]
            if real < batch_size and n > batch_size:
                pad = np.zeros((batch_size - real,) + xb.shape[1:], dtype=xb.dtype)
                xb = np.concatenate([xb, pad], axis=0)
            out = np.asarray(step(self.params, xb))
            outputs.append(out[:real])
        if not outputs:
            return np.zeros((0,) + tuple(self.output_shape), dtype=np.float32)
        return np.concatenate(outputs, axis=0)

    # ------------------------------------------------------------------- json
    def get_config(self) -> Dict:
        raise NotImplementedError

    def to_json(self, **kwargs) -> str:
        return json.dumps({"class_name": type(self).__name__,
                           "config": self.get_config()}, **kwargs)

    def save(self, filepath: str, overwrite: bool = True,
             include_optimizer: bool = True):
        from .saving import save_model

        save_model(self, filepath, overwrite=overwrite,
                   include_optimizer=include_optimizer)

    def summary(self) -> str:
        lines = [f'Model: "{self.name}"', "-" * 60]
        total = 0
        for layer in self.layers:
            count = 0
            if self.params and layer.name in self.params:
                count = sum(int(np.prod(v.shape)) for v in self.params[layer.name].values())
            total += count
            lines.append(f"{layer.name:<30}{type(layer).__name__:<20}{count:>10,}")
        lines.append("-" * 60)
        lines.append(f"Total params: {total:,}")
        text = "\n".join(lines)
        print(text)
        return text


class Sequential(BaseModel):
    """Linear stack of layers (Keras Sequential analog)."""

    def __init__(self, layers: Optional[Sequence[Layer]] = None,
                 name: Optional[str] = None):
        super().__init__(name=name)
        self._layers: List[Layer] = []
        for layer in layers or []:
            self.add(layer)

    @property
    def layers(self) -> List[Layer]:
        return self._layers

    def add(self, layer: Layer):
        if not isinstance(layer, Layer):
            raise TypeError(f"Sequential.add expects a Layer, got {type(layer)}")
        self._layers.append(layer)
        self.built = False
        return self

    def _declared_input_shape(self) -> Optional[Tuple]:
        for layer in self._layers:
            if isinstance(layer, InputLayer):
                return layer.shape
            if layer.input_spec is not None:
                return tuple(layer.input_spec)
            break
        return None

    def build(self, input_shape: Optional[Tuple] = None, seed: Optional[int] = None):
        if input_shape is None:
            input_shape = self._declared_input_shape()
        if input_shape is None:
            raise ValueError(
                "Cannot build Sequential model: supply input_shape/input_dim "
                "on the first layer or call build(input_shape=...)")
        if seed is not None:
            self._rng_seed = seed
        if self._rng_seed is None:
            self._rng_seed = int(np.random.SeedSequence().generate_state(1)[0])
        key = jax.random.PRNGKey(self._rng_seed)
        params = {}
        shape = tuple(input_shape)
        self._built_input_shape = shape
        for i, layer in enumerate(self._layers):
            layer_key = jax.random.fold_in(key, i)
            layer_params = layer.build(layer_key, shape)
            if layer_params:
                params[layer.name] = layer_params
            shape = layer.compute_output_shape(shape)
        self._output_shape = shape
        self.params = params
        self.built = True
        self._opt_state = None
        self._invalidate_jit()
        return self

    @property
    def output_shape(self) -> Tuple:
        if not self.built:
            raise ValueError("Model not built")
        return self._output_shape

    def _apply_internal(self, params, inputs, training, rng, collect_updates):
        updates: Dict[str, Dict] = {}
        x = inputs
        for i, layer in enumerate(self._layers):
            layer_rng = jax.random.fold_in(rng, i) if rng is not None else None
            layer_params = params.get(layer.name, {})
            if collect_updates and hasattr(layer, "batch_stats") and training:
                mean, var = layer.batch_stats(layer_params, x)
                m = layer.momentum
                updates[layer.name] = {
                    "moving_mean": m * layer_params["moving_mean"] + (1 - m) * mean,
                    "moving_variance": m * layer_params["moving_variance"] + (1 - m) * var,
                }
            x = layer.call(layer_params, x, training, layer_rng)
        return x, updates

    def get_config(self) -> Dict:
        return {"name": self.name,
                "layers": [serialize_layer(layer) for layer in self._layers]}

    @classmethod
    def from_config(cls, config: Dict, custom_objects: Optional[Dict] = None):
        model = cls(name=config.get("name"))
        for spec in config["layers"]:
            model.add(deserialize_layer(spec, custom_objects))
        model.custom_objects = custom_objects or {}
        for layer in model._layers:
            layer._custom_objects = model.custom_objects
        try:
            model.build()
        except ValueError:
            pass
        return model


class Model(BaseModel):
    """Functional-API model over a DAG of layer calls."""

    def __init__(self, inputs=None, outputs=None, name: Optional[str] = None):
        super().__init__(name=name)
        if inputs is None or outputs is None:
            raise ValueError("Model requires inputs= and outputs=")
        self.inputs: List[KTensor] = list(inputs) if isinstance(
            inputs, (list, tuple)) else [inputs]
        self.outputs: List[KTensor] = list(outputs) if isinstance(
            outputs, (list, tuple)) else [outputs]
        self._nodes = self._topo_sort()
        self.build()

    # each node: (ktensor, layer, input ktensors)
    def _topo_sort(self):
        order, seen = [], set()

        def visit(t: KTensor):
            if id(t) in seen:
                return
            seen.add(id(t))
            if t.history is None:
                raise ValueError("Disconnected tensor in graph")
            layer, parents = t.history
            for p in parents:
                visit(p)
            order.append((t, layer, parents))

        for out in self.outputs:
            visit(out)
        names = [layer.name for _, layer, _ in order]
        if len(names) != len(set(names)):
            raise ValueError("Layer reuse (shared layers) is not supported yet")
        return order

    @property
    def layers(self) -> List[Layer]:
        return [layer for _, layer, _ in self._nodes]

    def build(self, input_shape=None, seed: Optional[int] = None):
        if seed is not None:
            self._rng_seed = seed
        if self._rng_seed is None:
            self._rng_seed = int(np.random.SeedSequence().generate_state(1)[0])
        key = jax.random.PRNGKey(self._rng_seed)
        params = {}
        shapes: Dict[int, Tuple] = {}
        for i, (t, layer, parents) in enumerate(self._nodes):
            if isinstance(layer, InputLayer):
                shapes[id(t)] = layer.shape
                continue
            in_shapes = [shapes[id(p)] for p in parents]
            arg = in_shapes if len(in_shapes) > 1 else in_shapes[0]
            layer_params = layer.build(jax.random.fold_in(key, i), arg)
            if layer_params:
                params[layer.name] = layer_params
            shapes[id(t)] = layer.compute_output_shape(arg)
        self._output_shape = shapes[id(self.outputs[0])]
        self.params = params
        self.built = True
        self._opt_state = None
        self._invalidate_jit()
        return self

    @property
    def output_shape(self) -> Tuple:
        return self._output_shape

    def _apply_internal(self, params, inputs, training, rng, collect_updates):
        updates: Dict[str, Dict] = {}
        values: Dict[int, Any] = {}
        input_list = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if len(input_list) != len(self.inputs):
            raise ValueError(f"Model expects {len(self.inputs)} inputs, "
                             f"got {len(input_list)}")
        # bind by the user-declared inputs= order, not graph-traversal order
        for placeholder, array in zip(self.inputs, input_list):
            values[id(placeholder)] = array
        for i, (t, layer, parents) in enumerate(self._nodes):
            if isinstance(layer, InputLayer):
                if id(t) not in values:
                    raise ValueError(
                        f"Input tensor for layer {layer.name!r} missing from inputs=")
                continue
            args = [values[id(p)] for p in parents]
            arg = args if len(args) > 1 else args[0]
            layer_rng = jax.random.fold_in(rng, i) if rng is not None else None
            layer_params = params.get(layer.name, {})
            if collect_updates and hasattr(layer, "batch_stats") and training:
                mean, var = layer.batch_stats(layer_params, arg)
                m = layer.momentum
                updates[layer.name] = {
                    "moving_mean": m * layer_params["moving_mean"] + (1 - m) * mean,
                    "moving_variance": m * layer_params["moving_variance"] + (1 - m) * var,
                }
            values[id(t)] = layer.call(layer_params, arg, training, layer_rng)
        outs = [values[id(o)] for o in self.outputs]
        return (outs if len(outs) > 1 else outs[0]), updates

    def get_config(self) -> Dict:
        tensor_names: Dict[int, str] = {}
        layer_specs = []
        for t, layer, parents in self._nodes:
            tensor_names[id(t)] = layer.name
            spec = serialize_layer(layer)
            spec["name"] = layer.name
            spec["inbound"] = [tensor_names[id(p)] for p in parents]
            layer_specs.append(spec)
        return {
            "name": self.name,
            "layers": layer_specs,
            "input_layers": [t.history[0].name for t in self.inputs],
            "output_layers": [tensor_names[id(t)] for t in self.outputs],
        }

    @classmethod
    def from_config(cls, config: Dict, custom_objects: Optional[Dict] = None):
        produced: Dict[str, KTensor] = {}
        for spec in config["layers"]:
            layer = deserialize_layer(spec, custom_objects)
            if isinstance(layer, InputLayer):
                produced[layer.name] = layer._output
                continue
            inbound = [produced[name] for name in spec["inbound"]]
            produced[layer.name] = layer(inbound if len(inbound) > 1 else inbound[0])
        inputs = [produced[name] for name in config["input_layers"]]
        outputs = [produced[name] for name in config["output_layers"]]
        model = cls(inputs=inputs, outputs=outputs, name=config.get("name"))
        model.custom_objects = custom_objects or {}
        for layer in model.layers:
            layer._custom_objects = model.custom_objects
        return model


def model_from_json(json_string: str,
                    custom_objects: Optional[Dict] = None) -> BaseModel:
    """Rebuild a model from its JSON architecture config.

    (Parity: Keras ``model_from_json`` as used at ``elephas/worker.py:31``.)
    """
    spec = json.loads(json_string)
    class_name = spec.get("class_name")
    config = spec.get("config", {})
    if class_name == "Sequential":
        return Sequential.from_config(config, custom_objects)
    if class_name in ("Model", "Functional"):
        return Model.from_config(config, custom_objects)
    if class_name == "TransformerModel":
        from .transformer_model import TransformerModel
        return TransformerModel.from_config(config, custom_objects)
    if class_name == "SSMModel":
        from .ssm_model import SSMModel
        return SSMModel.from_config(config, custom_objects)
    raise ValueError(f"Unknown model class: {class_name!r}")
