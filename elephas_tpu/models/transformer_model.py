"""TransformerModel: the flagship transformer behind the TPUModel API.

Round-1 left two worlds disjoint: the functional transformer stack
(:mod:`~elephas_tpu.models.transformer` — ``init_params`` /
``make_train_step`` pytrees over a mesh) and the framework's distributed
driver (:class:`~elephas_tpu.tpu_model.TPUModel` with callbacks,
checkpointing and histories, the capability mirror of the reference's
``SparkModel``, ``elephas/spark_model.py:28-308``). This adapter unifies
them: it exposes the BaseModel surface TPUModel and the callback suite
expect (``compile``/``get_weights``/``training_state``/``to_json``/...)
while training runs through the jitted, mesh-sharded
``make_train_step`` — so the flagship LM trains via ``TPUModel.fit`` with
``EarlyStopping``/``ModelCheckpoint`` and resumes bit-exact.
"""
import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from .optimizers import Optimizer
from .optimizers import get as get_optimizer
from .transformer import (TransformerConfig, forward, init_params, lm_loss,
                          make_train_step, select_moe_dispatch, shard_params)
from .transformer import generate as _generate

__all__ = ["TransformerModel"]

#: dataclass fields that hold dtypes (serialized by numpy name)
_DTYPE_FIELDS = ("dtype", "param_dtype")


def _config_to_dict(config: TransformerConfig) -> Dict:
    out = dataclasses.asdict(config)
    for f in _DTYPE_FIELDS:
        out[f] = np.dtype(out[f]).name
    return out


def _config_from_dict(d: Dict) -> TransformerConfig:
    d = dict(d)
    for f in _DTYPE_FIELDS:
        if isinstance(d.get(f), str):
            d[f] = getattr(jnp, d[f])
    return TransformerConfig(**d)


class TransformerModel:
    """Decoder-only transformer LM with the framework's model surface.

    Data convention: "x" is a ``(rows, seq_len)`` int array of token ids;
    there is no separate label column (next-token targets are the shifted
    input, ``transformer.next_token_loss``).

    :param config: :class:`~elephas_tpu.models.transformer.TransformerConfig`
    :param tensor_parallel: Megatron-style model-axis size the training
        mesh uses (1 = pure data parallelism over all visible devices)
    :param zero_optimizer: shard the optimizer state over the data axis
        (ZeRO-1: optimizer memory scales down with the data-parallel
        degree instead of being replicated)
    :param fsdp: fully shard parameters, gradients, AND optimizer state
        over the data axis (ZeRO-3 via
        :func:`~elephas_tpu.models.transformer.fsdp_param_specs`);
        composes with ``tensor_parallel``, supersedes ``zero_optimizer``
    :param sequence_parallel: mesh size of the ``seq`` axis — long-
        context training via ring attention (k/v shards stream around
        the seq ring); sequence length must divide by it
    :param ema_decay: keep an exponential moving average of the
        parameters (updated on-device each optimizer step) — the
        standard serving-quality trick; ``apply_ema()`` swaps it in
    :param mesh: explicit training mesh (e.g. a
        :func:`~elephas_tpu.parallel.hybrid_mesh` spanning hosts) —
        must carry a ``data`` axis and, for tp/sp, ``model``/``seq``
        axes; overrides the tensor_parallel/sequence_parallel-derived
        mesh
    :param grad_accum: accumulate gradients over this many microbatches
        per optimizer step (each fit batch splits into ``grad_accum``
        microbatches; identical numerics, 1/``grad_accum`` the activation
        memory)
    """

    def __init__(self, config: TransformerConfig,
                 tensor_parallel: int = 1, name: Optional[str] = None,
                 zero_optimizer: bool = False, grad_accum: int = 1,
                 fsdp: bool = False, sequence_parallel: int = 1,
                 ema_decay: Optional[float] = None,
                 mesh: Optional[Mesh] = None):
        if fsdp and zero_optimizer:
            raise ValueError("fsdp supersedes zero_optimizer — pick one")
        if ema_decay is not None and not 0.0 < ema_decay < 1.0:
            raise ValueError("ema_decay must be in (0, 1)")
        self.config = config
        self.tensor_parallel = int(tensor_parallel)
        self.sequence_parallel = int(sequence_parallel)
        self.ema_decay = ema_decay
        self.ema_params: Optional[Dict] = None
        if mesh is not None and "data" not in mesh.axis_names:
            raise ValueError("an explicit mesh must carry a 'data' axis")
        self._explicit_mesh = mesh
        self.fsdp = bool(fsdp)
        self.zero_optimizer = bool(zero_optimizer)
        self.grad_accum = max(1, int(grad_accum))
        self.name = name or "transformer_model"
        self.params: Optional[Dict] = None
        self.built = False
        self.stop_training = False
        self.optimizer: Optional[Optimizer] = None
        self.loss: Optional[str] = None
        self.metrics: List = []
        self._tx = None
        self._opt_state = None
        self._seed = 0
        # jitted forward/loss, built once per model (config is static; a
        # fresh jax.jit(lambda) per call would retrace every invocation)
        self._jit_forward = None
        self._jit_loss = None

    # ------------------------------------------------------------ lifecycle
    def build(self, input_shape=None, seed: Optional[int] = None):
        if seed is not None:
            self._seed = seed
        self.params = init_params(self.config,
                                  jax.random.PRNGKey(self._seed))
        self.built = True
        self._opt_state = None
        return self

    def compile(self, optimizer="adam", loss: Optional[str] = None,
                metrics: Optional[Sequence] = None,
                seed: Optional[int] = None, **kwargs):
        """``loss``/``metrics`` exist for API parity; the training loss is
        always next-token cross-entropy (+ the MoE aux term)."""
        self.optimizer = get_optimizer(optimizer)
        self.loss = loss or "lm_cross_entropy"
        self.metrics = list(metrics or [])
        self._tx = self.optimizer.to_optax()
        if self.config.num_experts > 1 and self.config.moe_dispatch == "auto":
            # pin 'auto' to one concrete dispatch now, resolved against
            # the TRAINING mesh: otherwise a tp-sharded fit would train
            # dense (exact) while unsharded predict/evaluate routed
            # (capacity drops) — silent train/serve numeric skew
            mesh = self._training_mesh()
            self.config = dataclasses.replace(
                self.config,
                moe_dispatch=select_moe_dispatch(
                    self.config, mesh, "model" if mesh is not None else None))
        self._jit_forward = None  # config may have changed: rebuild lazily
        self._jit_loss = None
        if not self.built:
            self.build(seed=seed)
        elif seed is not None and seed != self._seed:
            self.build(seed=seed)
        self._opt_state = None
        return self

    @property
    def compiled(self) -> bool:
        return self._tx is not None

    # -------------------------------------------------------------- weights
    def get_weights(self) -> List[np.ndarray]:
        """Flat leaf list in jax pytree order (sorted dict keys — stable
        across instances of the same config)."""
        if self.params is None:
            raise ValueError("Model must be built before get_weights()")
        return [np.asarray(leaf)
                for leaf in jax.tree_util.tree_leaves(self.params)]

    def set_weights(self, weights: Sequence[np.ndarray]):
        if self.params is None:
            raise ValueError("Model must be built before set_weights()")
        leaves, treedef = jax.tree_util.tree_flatten(self.params)
        if len(leaves) != len(weights):
            raise ValueError(
                f"Expected {len(leaves)} weight arrays, got {len(weights)}")
        new_leaves = []
        for ref, w in zip(leaves, weights):
            w = jnp.asarray(w, dtype=ref.dtype)
            if w.shape != ref.shape:
                raise ValueError(
                    f"Shape mismatch: {w.shape} vs {ref.shape}")
            new_leaves.append(w)
        self.params = jax.tree_util.tree_unflatten(treedef, new_leaves)

    # ------------------------------------------------------- checkpoint api
    def training_state(self) -> Dict:
        """Same contract as ``BaseModel.training_state`` so
        :class:`~elephas_tpu.models.callbacks.ModelCheckpoint` drives this
        model unchanged."""
        from .saving import pack_training_state

        if self.params is None:
            raise ValueError("Model must be built before training_state()")
        return pack_training_state(self.params, self._opt_state)

    def restore_training_state(self, directory: str,
                               step: Optional[int] = None) -> Optional[int]:
        """Restore params + optimizer moments saved by ModelCheckpoint;
        bit-exact resume (no layer renaming needed — the param pytree keys
        are positional and stable)."""
        from ..utils.checkpoint import CheckpointManager
        from .saving import unpack_training_state

        if not self.built:
            raise RuntimeError("build()/compile() before "
                               "restore_training_state")
        manager = CheckpointManager(directory)
        params, opt_state = unpack_training_state(manager.restore(step),
                                                  self._tx, self.params)
        self.params = params
        if opt_state is not None:
            self._opt_state = opt_state
        return step if step is not None else manager.latest_step()

    # -------------------------------------------------------- serialization
    def get_config(self) -> Dict:
        return {"name": self.name,
                "tensor_parallel": self.tensor_parallel,
                "sequence_parallel": self.sequence_parallel,
                "zero_optimizer": self.zero_optimizer,
                "grad_accum": self.grad_accum,
                "fsdp": self.fsdp,
                "ema_decay": self.ema_decay,
                "transformer_config": _config_to_dict(self.config)}

    def to_json(self, **kwargs) -> str:
        return json.dumps({"class_name": "TransformerModel",
                           "config": self.get_config()}, **kwargs)

    @classmethod
    def from_config(cls, config: Dict,
                    custom_objects: Optional[Dict] = None
                    ) -> "TransformerModel":
        return cls(_config_from_dict(config["transformer_config"]),
                   tensor_parallel=config.get("tensor_parallel", 1),
                   name=config.get("name"),
                   zero_optimizer=config.get("zero_optimizer", False),
                   grad_accum=config.get("grad_accum", 1),
                   fsdp=config.get("fsdp", False),
                   sequence_parallel=config.get("sequence_parallel", 1),
                   ema_decay=config.get("ema_decay"))

    # ------------------------------------------------------------- training
    def _training_mesh(self) -> Optional[Mesh]:
        """dp×tp(×sp) mesh over the visible devices (None on one chip)."""
        if self._explicit_mesh is not None:
            return self._explicit_mesh
        devices = jax.devices()
        tp, sp = self.tensor_parallel, self.sequence_parallel
        if len(devices) == 1 and tp == 1 and sp == 1:
            return None
        if len(devices) % (tp * sp):
            raise ValueError(
                f"tensor_parallel={tp} x sequence_parallel={sp} does not "
                f"divide the {len(devices)}-device mesh")
        dp = len(devices) // (tp * sp)
        if sp > 1:
            return Mesh(np.array(devices).reshape(dp, tp, sp),
                        ("data", "model", "seq"))
        return Mesh(np.array(devices).reshape(dp, tp), ("data", "model"))

    def fit_tokens(self, tokens: np.ndarray, epochs: int = 1,
                   batch_size: int = 32, validation_split: float = 0.0,
                   seed: int = 0, verbose: int = 0,
                   epoch_callback: Optional[Callable] = None) -> Dict:
        """Mesh-sharded LM training; the engine behind ``TPUModel.fit``.

        ``epoch_callback(epoch_idx, logs) -> stop?`` fires after each
        epoch with ``{'loss': ..., 'val_loss': ...}`` logs (val only with
        a validation split), mirroring ``SyncStepTrainer.fit`` so
        TPUModel's callback plumbing drives both trainers identically.
        Returns a Keras-style history dict.
        """
        if not self.compiled:
            raise RuntimeError("compile() the model before fit")
        tokens = np.asarray(tokens)
        if tokens.ndim != 2:
            raise ValueError(f"tokens must be (rows, seq), got {tokens.shape}")

        mesh = self._training_mesh()
        dp = (dict(zip(mesh.axis_names, mesh.devices.shape))["data"]
              if mesh is not None else 1)
        if batch_size % dp:
            raise ValueError(
                f"batch_size={batch_size} must divide over the data-"
                f"parallel axis ({dp} devices)")
        n_val = int(round(tokens.shape[0] * validation_split))
        # the val batch shards over the data axis too: trim to a dp
        # multiple (a sub-dp remainder can't be laid out on the mesh)
        n_val -= n_val % dp
        if n_val:
            tokens, val_tokens = tokens[:-n_val], tokens[-n_val:]

        from ..parallel.mesh import shard_leading

        params = self.params
        if mesh is not None:
            params = shard_params(
                params, self.config, mesh,
                fsdp_axis="data" if self.fsdp else None)
        if batch_size % self.grad_accum:
            raise ValueError(
                f"batch_size={batch_size} does not split into "
                f"{self.grad_accum} gradient-accumulation microbatches")
        sp = self.sequence_parallel
        if mesh is not None and "seq" in mesh.axis_names:
            sp = max(sp, dict(zip(mesh.axis_names,
                                  mesh.devices.shape))["seq"])
        step = make_train_step(self.config, self._tx, mesh=mesh,
                               seq_axis="seq" if sp > 1 else None,
                               zero_optimizer=self.zero_optimizer,
                               accum_steps=self.grad_accum,
                               fsdp=self.fsdp and mesh is not None)
        opt_state = (self._opt_state if self._opt_state is not None
                     else jax.jit(self._tx.init)(params))

        eval_loss = jax.jit(
            lambda p, t: lm_loss(p, t, self.config,
                                 mesh=mesh,
                                 seq_axis=("seq" if mesh is not None
                                           and sp > 1 else None),
                                 batch_axis="data" if mesh else None,
                                 model_axis="model" if mesh else None))

        from ..utils.tracing import StepTimer

        ema_update = None
        if self.ema_decay is not None:
            decay = float(self.ema_decay)
            ema_update = jax.jit(lambda e, p: jax.tree_util.tree_map(
                lambda a, b: decay * a + (1.0 - decay) * b, e, p))
            if self.ema_params is None:
                # a REAL copy: the train step donates its param buffers,
                # so aliasing them here would read deleted memory
                self.ema_params = jax.tree_util.tree_map(jnp.copy, params)

        rng = np.random.default_rng(seed)
        use_dropout = self.config.dropout_rate > 0
        dropout_base = jax.random.PRNGKey(seed)
        n = tokens.shape[0]
        nb = n // batch_size
        if nb == 0:
            raise ValueError(
                f"fewer token rows ({n}) than batch_size ({batch_size})")
        history: Dict[str, List[float]] = {"loss": []}
        if n_val:
            history["val_loss"] = []
        history["epoch_time"] = []
        self.timer = timer = StepTimer()

        for epoch in range(epochs):
            timer.start()
            order = rng.permutation(n)
            shuffled = tokens[order]
            losses = []
            for i in range(nb):
                xb = shuffled[i * batch_size:(i + 1) * batch_size]
                if mesh is not None and sp > 1:
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as _P

                    xb = jax.device_put(
                        jnp.asarray(xb),
                        NamedSharding(mesh, _P("data", "seq")))
                elif mesh is not None:
                    # shard_leading routes through global-array assembly
                    # on process-spanning meshes (multi-host DCN), plain
                    # device_put otherwise
                    xb = shard_leading(mesh, "data", xb)
                else:
                    xb = jnp.asarray(xb)
                if use_dropout:
                    params, opt_state, loss = step(
                        params, opt_state, xb,
                        jax.random.fold_in(dropout_base, epoch * nb + i))
                else:
                    params, opt_state, loss = step(params, opt_state, xb)
                losses.append(loss)
                if ema_update is not None:
                    self.ema_params = ema_update(self.ema_params, params)
            # the float() fetches block on the epoch's dispatched steps,
            # so the recorded wall time is real (tracing requirement)
            logs = {"loss": float(np.mean([float(l) for l in losses]))}
            timer.stop()
            history["epoch_time"].append(timer.durations[-1])
            if n_val:
                if mesh is not None and sp > 1:
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as _P

                    vb = jax.device_put(
                        jnp.asarray(val_tokens),
                        NamedSharding(mesh, _P("data", "seq")))
                elif mesh is not None:
                    vb = shard_leading(mesh, "data", val_tokens)
                else:
                    vb = jnp.asarray(val_tokens)
                logs["val_loss"] = float(eval_loss(params, vb))
            for k, v in logs.items():
                history[k].append(v)
            if verbose:
                print(f"epoch {epoch + 1}/{epochs} - " +
                      " - ".join(f"{k}: {v:.4f}" for k, v in logs.items()))
            # sync resumable state so callbacks observe current weights
            # and checkpoints carry the optimizer moments
            self.params = params
            self._opt_state = opt_state
            if epoch_callback is not None and epoch_callback(epoch, logs):
                break

        self.params = params
        self._opt_state = opt_state
        return history

    # fit() keeps the (x, y) surface of BaseModel: y is ignored (LM
    # targets are the shifted input)
    def fit(self, x, y=None, epochs: int = 1, batch_size: int = 32,
            verbose: int = 0, validation_split: float = 0.0,
            callbacks=None, seed: int = 0, **kwargs) -> Dict:
        from .callbacks import CallbackList

        cbs = CallbackList(callbacks, self)
        self.stop_training = False
        cbs.train_begin()

        def epoch_cb(epoch, logs):
            cbs.epoch_end(epoch, logs)
            return bool(self.stop_training)

        # finally: async ModelCheckpoint flushes background writes in
        # train_end — it must run even when training raises
        try:
            history = self.fit_tokens(
                x, epochs=epochs, batch_size=batch_size,
                validation_split=validation_split, seed=seed, verbose=verbose,
                epoch_callback=epoch_cb if cbs else None)
        finally:
            cbs.train_end()
        return history

    def apply_ema(self):
        """Swap the EMA average in as the live parameters (returns the
        raw training params so callers can swap back)."""
        if self.ema_params is None:
            raise RuntimeError("no EMA state — set ema_decay and fit first")
        raw = self.params
        self.params = jax.tree_util.tree_map(jnp.asarray, self.ema_params)
        return raw

    def save(self, filepath: str, overwrite: bool = True,
             include_optimizer: bool = True):
        from .saving import save_model

        save_model(self, filepath, overwrite, include_optimizer)

    # ------------------------------------------------------ inference/eval
    def predict(self, tokens: np.ndarray, batch_size: int = 8,
                verbose: int = 0,
                out: Optional[np.ndarray] = None) -> np.ndarray:
        """Logits ``(rows, seq, vocab)`` in input order.

        ``out``: optional preallocated ``(rows, seq, vocab)`` array
        (e.g. a writable memmap) receiving each batch's logits in
        place — with a file-backed token column neither the inputs nor
        the (rows×seq×vocab, typically huge) outputs ever fully
        materialize in memory."""
        from ._streaming import batched_logits_predict

        if self._jit_forward is None:
            config = self.config
            self._jit_forward = jax.jit(
                lambda p, t: forward(p, t, config))
        return batched_logits_predict(self._jit_forward, self.params,
                                      tokens, batch_size, out=out)

    def generate(self, prompt: np.ndarray, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 prompt_lengths=None) -> np.ndarray:
        """Autoregressive continuation of ``(batch, prompt_len)`` token
        ids via the KV-cache decode loop (one lax.scan, compiled once per
        shape): ``temperature=0`` greedy, otherwise categorical sampling,
        optionally top-k and/or nucleus (top-p) filtered."""
        key = jax.random.PRNGKey(seed)
        return np.asarray(_generate(self.params, np.asarray(prompt),
                                    int(max_new_tokens), self.config,
                                    temperature=temperature, key=key,
                                    top_k=top_k, top_p=top_p,
                                    prompt_lengths=prompt_lengths))

    def engine(self, draft: Optional["TransformerModel"] = None,
               **engine_kwargs):
        """A :class:`~elephas_tpu.serving_engine.DecodeEngine` over this
        model's parameters (continuous batching, prefix caching,
        multi-step scheduling, paged KV — see the serving guide). Pass
        ``draft=`` for speculative stepping."""
        from ..serving_engine import DecodeEngine

        if self.params is None:
            raise RuntimeError("build() or load weights before serving")
        if draft is not None:
            if draft.params is None:
                raise RuntimeError("the draft model needs build() or "
                                   "loaded weights before serving")
            engine_kwargs.setdefault("draft_params", draft.params)
            engine_kwargs.setdefault("draft_config", draft.config)
        return DecodeEngine(self.params, self.config, **engine_kwargs)

    def serve(self, host: str = "127.0.0.1", port: int = 0,
              tokenizer=None, draft: Optional["TransformerModel"] = None,
              warmup_lengths=(), **engine_kwargs):
        """One call from a trained model to a RUNNING HTTP server:
        builds the engine, optionally warms the given prompt lengths,
        and starts a :class:`~elephas_tpu.serving_http.ServingServer`
        (returned started; ``.port`` has the bound port, ``.stop()``
        shuts down)."""
        from ..serving_http import ServingServer

        eng = self.engine(draft=draft, **engine_kwargs)
        if warmup_lengths:
            eng.warmup(prompt_lengths=warmup_lengths)
        return ServingServer(eng, host=host, port=port,
                             tokenizer=tokenizer).start()

    def speculative_generate(self, draft: "TransformerModel",
                             prompt: np.ndarray, max_new_tokens: int,
                             gamma: int = 4, temperature: float = 0.0,
                             seed: int = 0, return_stats: bool = False):
        """Draft-and-verify decoding: ``draft`` (a smaller
        TransformerModel sharing this model's vocabulary) proposes
        ``gamma`` tokens per round and this model verifies them in one
        cached block forward. Greedy output is token-identical to
        :meth:`generate`; the speedup is ``1 + gamma * acceptance``
        emitted tokens per target weight read."""
        from .speculative import speculative_generate as _spec

        out = _spec(self.params, draft.params, np.asarray(prompt),
                    int(max_new_tokens), self.config, draft.config,
                    gamma=gamma, temperature=temperature,
                    key=jax.random.PRNGKey(seed),
                    return_stats=return_stats)
        if return_stats:
            return np.asarray(out[0]), out[1]
        return np.asarray(out)

    def beam_search(self, prompt: np.ndarray, max_new_tokens: int,
                    num_beams: int = 4, length_penalty: float = 0.0,
                    eos_id: Optional[int] = None):
        """Beam-search continuations ``(batch, num_beams, max_new_tokens)``
        with per-beam scores, best first."""
        from .transformer import beam_search as _beam_search

        seqs, scores = _beam_search(self.params, np.asarray(prompt),
                                    int(max_new_tokens), self.config,
                                    num_beams=num_beams,
                                    length_penalty=length_penalty,
                                    eos_id=eos_id)
        return np.asarray(seqs), np.asarray(scores)

    def evaluate(self, tokens: np.ndarray, y=None, batch_size: int = 8,
                 verbose: int = 0) -> float:
        """Mean next-token loss over the rows (batch-weighted)."""
        tokens = np.asarray(tokens)
        if self._jit_loss is None:
            config = self.config
            self._jit_loss = jax.jit(lambda p, t: lm_loss(p, t, config))
        total, count = 0.0, 0
        for i in range(0, tokens.shape[0], batch_size):
            chunk = tokens[i:i + batch_size]
            total += float(self._jit_loss(
                self.params, jnp.asarray(chunk))) * len(chunk)
            count += len(chunk)
        return total / max(count, 1)
