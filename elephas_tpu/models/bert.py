"""BERT-style bidirectional encoder with masked-language-model training.

Third transformer family next to the causal LM (``transformer.py``) and
ViT (``vit.py``), reusing the same block sublayers and Megatron
tensor-parallel specs. Bidirectional attention with a padding mask,
token+position+segment embeddings, an MLM head tied to the embedding
matrix, and a [CLS] pooler for fine-tuning — trained with the standard
80/10/10 dynamic masking recipe (:func:`mask_tokens`).

TPU notes: the MLM loss only gathers the masked positions' hidden states
before the vocab projection (a ``(num_masked, D) @ (D, V)`` matmul
instead of ``(B*T, D) @ (D, V)`` — ~6x fewer head FLOPs at the usual 15%
mask rate), with a static masked-position budget so shapes stay
compile-friendly.
"""
import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.attention import attention
from .transformer import (_attn_apply, _dropout, _layer_norm,
                          _mesh_divides, _mlp_apply)

__all__ = ["BertConfig", "init_params", "param_specs", "encode", "pool",
           "mlm_loss", "mask_tokens", "make_mlm_train_step", "shard_params",
           "init_classifier_head", "classify", "make_classifier_train_step"]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    num_layers: int = 12
    num_heads: int = 12
    d_model: int = 768
    d_ff: int = 3072
    max_seq_len: int = 512
    type_vocab_size: int = 2
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    #: id of the [MASK] token used by :func:`mask_tokens`
    mask_token_id: int = 103
    #: id of the padding token (excluded from attention and masking)
    pad_token_id: int = 0
    #: static budget of masked positions per row in the MLM loss: the
    #: gather keeps shapes fixed for XLA (ceil(mask_rate * seq) rounded
    #: up; rows with fewer masks pad with weight-0 entries)
    max_predictions: int = 80
    #: residual dropout on each sublayer output (active only when a
    #: dropout key reaches the forward pass)
    dropout_rate: float = 0.0
    remat: bool = False
    num_kv_heads: Optional[int] = None

    def __post_init__(self):
        if self.d_model % self.num_heads:
            raise ValueError("num_heads must divide d_model")
        if not 0.0 <= self.dropout_rate < 1.0:
            raise ValueError("dropout_rate must be in [0, 1)")
        if self.num_kv_heads is not None and (
                self.num_kv_heads < 1
                or self.num_heads % self.num_kv_heads):
            raise ValueError("num_kv_heads must divide num_heads")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def kv_heads(self) -> int:
        return (self.num_kv_heads if self.num_kv_heads is not None
                else self.num_heads)

    # read by the shared _attn_apply: BERT position is an additive table
    @property
    def positional(self) -> str:
        return "learned"


def init_params(config: BertConfig, key) -> Dict:
    c = config
    keys = jax.random.split(key, 6 + c.num_layers)

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, c.param_dtype)
                / math.sqrt(fan_in))

    params: Dict[str, Any] = {
        "embed": {
            "tokens": 0.02 * jax.random.normal(
                keys[0], (c.vocab_size, c.d_model), c.param_dtype),
            "pos": 0.02 * jax.random.normal(
                keys[1], (c.max_seq_len, c.d_model), c.param_dtype),
            "seg": 0.02 * jax.random.normal(
                keys[2], (c.type_vocab_size, c.d_model), c.param_dtype),
            "ln": {"gamma": jnp.ones((c.d_model,), c.param_dtype),
                   "beta": jnp.zeros((c.d_model,), c.param_dtype)},
        },
        "pooler": {"kernel": dense(keys[3], (c.d_model, c.d_model),
                                   c.d_model),
                   "bias": jnp.zeros((c.d_model,), c.param_dtype)},
        "mlm": {  # transform + tied-embedding output bias (BERT head)
            "kernel": dense(keys[4], (c.d_model, c.d_model), c.d_model),
            "bias": jnp.zeros((c.d_model,), c.param_dtype),
            "ln": {"gamma": jnp.ones((c.d_model,), c.param_dtype),
                   "beta": jnp.zeros((c.d_model,), c.param_dtype)},
            "out_bias": jnp.zeros((c.vocab_size,), c.param_dtype),
        },
    }
    for i in range(c.num_layers):
        lk = jax.random.split(keys[6 + i], 6)
        params[f"layer_{i}"] = {
            "ln1": {"gamma": jnp.ones((c.d_model,), c.param_dtype),
                    "beta": jnp.zeros((c.d_model,), c.param_dtype)},
            "attn": {
                "wq": dense(lk[0], (c.d_model, c.num_heads, c.head_dim),
                            c.d_model),
                "wk": dense(lk[1], (c.d_model, c.kv_heads, c.head_dim),
                            c.d_model),
                "wv": dense(lk[2], (c.d_model, c.kv_heads, c.head_dim),
                            c.d_model),
                "wo": dense(lk[3], (c.num_heads, c.head_dim, c.d_model),
                            c.d_model),
            },
            "ln2": {"gamma": jnp.ones((c.d_model,), c.param_dtype),
                    "beta": jnp.zeros((c.d_model,), c.param_dtype)},
            "mlp": {"w1": dense(lk[4], (c.d_model, c.d_ff), c.d_model),
                    "b1": jnp.zeros((c.d_ff,), c.param_dtype),
                    "w2": dense(lk[5], (c.d_ff, c.d_model), c.d_ff),
                    "b2": jnp.zeros((c.d_model,), c.param_dtype)},
        }
    return params


def param_specs(config: BertConfig, model_axis: str = "model",
                mesh: Optional[Mesh] = None) -> Dict:
    """Megatron tensor-parallel specs mirroring :func:`init_params`."""
    kv_shardable = (mesh is None
                    or _mesh_divides(mesh, model_axis, config.kv_heads))
    kv_spec = (P(None, model_axis, None) if kv_shardable
               else P(None, None, None))

    def _div(dim):
        return mesh is None or _mesh_divides(mesh, model_axis, dim)

    h_ax = model_axis if _div(config.num_heads) else None
    ff_ax = model_axis if _div(config.d_ff) else None
    specs: Dict[str, Any] = {
        "embed": {"tokens": P(model_axis, None), "pos": P(None, None),
                  "seg": P(None, None),
                  "ln": {"gamma": P(None), "beta": P(None)}},
        "pooler": {"kernel": P(None, None), "bias": P(None)},
        "mlm": {"kernel": P(None, None), "bias": P(None),
                "ln": {"gamma": P(None), "beta": P(None)},
                "out_bias": P(model_axis)},
    }
    for i in range(config.num_layers):
        specs[f"layer_{i}"] = {
            "ln1": {"gamma": P(None), "beta": P(None)},
            "attn": {"wq": P(None, h_ax, None),
                     "wk": kv_spec, "wv": kv_spec,
                     "wo": P(h_ax, None, None)},
            "ln2": {"gamma": P(None), "beta": P(None)},
            "mlp": {"w1": P(None, ff_ax), "b1": P(ff_ax),
                    "w2": P(ff_ax, None), "b2": P(None)},
        }
    return specs


def encode(params: Dict, tokens: jnp.ndarray,
           segment_ids: Optional[jnp.ndarray] = None,
           config: BertConfig = None, dropout_key=None) -> jnp.ndarray:
    """Token ids ``(B, T)`` -> contextual hidden states ``(B, T, D)``.
    Padding positions (``pad_token_id``) are excluded from every
    attention's key set. ``dropout_key`` activates residual dropout."""
    c = config
    e = params["embed"]
    x = e["tokens"][tokens] + e["pos"][:tokens.shape[1]]
    if segment_ids is None:
        segment_ids = jnp.zeros_like(tokens)
    x = x + e["seg"][segment_ids]
    x = _layer_norm(x, e["ln"]["gamma"], e["ln"]["beta"]).astype(c.dtype)

    pad_mask = (tokens != c.pad_token_id)[:, None, None, :]  # (B,1,1,T)

    def attn_fn(q, k, v):
        return attention(q, k, v, causal=False, mask=pad_mask)

    def layer_apply(layer, x, layer_key):
        if layer_key is not None:
            ak, mk = jax.random.split(layer_key)
        else:
            ak = mk = None
        x = _attn_apply(layer, x, c, attn_fn, dropout_key=ak)
        return _mlp_apply(layer, x, c, dropout_key=mk)

    if c.remat:
        layer_apply = jax.checkpoint(layer_apply)
    for i in range(c.num_layers):
        layer_key = (jax.random.fold_in(dropout_key, i)
                     if dropout_key is not None else None)
        x = layer_apply(params[f"layer_{i}"], x, layer_key)
    return x


def pool(params: Dict, hidden: jnp.ndarray,
         config: BertConfig) -> jnp.ndarray:
    """[CLS] pooler: tanh projection of position 0 — the fine-tuning
    feature vector."""
    h = hidden[:, 0].astype(jnp.float32)
    return jnp.tanh(h @ params["pooler"]["kernel"].astype(jnp.float32)
                    + params["pooler"]["bias"].astype(jnp.float32))


def mask_tokens(tokens: jnp.ndarray, key, config: BertConfig,
                mask_rate: float = 0.15
                ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """BERT dynamic masking: returns ``(masked_tokens, positions,
    weights)`` with the 80/10/10 [MASK]/random/keep recipe over a static
    ``max_predictions`` budget per row (weight 0 pads the budget)."""
    c = config
    b, t = tokens.shape
    k_sel, k_op, k_rand = jax.random.split(key, 3)
    scores = jax.random.uniform(k_sel, (b, t))
    scores = jnp.where(tokens != c.pad_token_id, scores, 2.0)
    # lowest-scoring ~mask_rate fraction of real tokens get masked
    threshold = mask_rate
    # static budget: take the max_predictions smallest scores per row
    n_pred = min(c.max_predictions, t)
    neg = -scores
    _, positions = jax.lax.top_k(neg, n_pred)                 # (B, n_pred)
    picked_score = jnp.take_along_axis(scores, positions, axis=1)
    weights = (picked_score < threshold).astype(jnp.float32)  # budget pad
    op = jax.random.uniform(k_op, (b, n_pred))
    rand_tok = jax.random.randint(k_rand, (b, n_pred), 0, c.vocab_size)
    orig = jnp.take_along_axis(tokens, positions, axis=1)
    replacement = jnp.where(op < 0.8, c.mask_token_id,
                            jnp.where(op < 0.9, rand_tok, orig))
    masked = tokens
    # scatter replacements at the chosen positions (weight-0 entries
    # scatter their original token back: a no-op)
    replacement = jnp.where(weights > 0, replacement, orig)
    masked = jax.vmap(lambda row, pos, rep: row.at[pos].set(rep))(
        masked, positions, replacement)
    return masked, positions, weights


def mlm_loss(params: Dict, masked_tokens: jnp.ndarray,
             positions: jnp.ndarray, labels: jnp.ndarray,
             weights: jnp.ndarray, config: BertConfig,
             segment_ids: Optional[jnp.ndarray] = None,
             dropout_key=None) -> jnp.ndarray:
    """Masked-LM cross-entropy over the selected ``positions`` (labels =
    original tokens at those positions; ``weights`` zero out budget
    padding). Only the masked positions' hidden states reach the vocab
    projection."""
    c = config
    hidden = encode(params, masked_tokens, segment_ids, c,
                    dropout_key=dropout_key)                  # (B, T, D)
    picked = jnp.take_along_axis(
        hidden, positions[..., None].astype(jnp.int32), axis=1)  # (B,P,D)
    h = picked.astype(jnp.float32)
    h = h @ params["mlm"]["kernel"].astype(jnp.float32) \
        + params["mlm"]["bias"].astype(jnp.float32)
    h = jax.nn.gelu(h)
    h = _layer_norm(h, params["mlm"]["ln"]["gamma"],
                    params["mlm"]["ln"]["beta"])
    logits = (h @ params["embed"]["tokens"].T.astype(jnp.float32)
              + params["mlm"]["out_bias"].astype(jnp.float32))
    logp = jax.nn.log_softmax(logits, axis=-1)
    ce = -jnp.take_along_axis(logp, labels[..., None].astype(jnp.int32),
                              axis=-1)[..., 0]
    total = jnp.maximum(jnp.sum(weights), 1.0)
    return jnp.sum(ce * weights) / total


def shard_params(params: Dict, config: BertConfig, mesh: Mesh,
                 model_axis: str = "model") -> Dict:
    specs = param_specs(config, model_axis=model_axis, mesh=mesh)
    return jax.tree_util.tree_map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs)


def make_mlm_train_step(config: BertConfig, tx,
                        mesh: Optional[Mesh] = None,
                        mask_rate: float = 0.15):
    """Jitted ``(params, opt_state, tokens, key) -> (params, opt_state,
    loss)``: dynamic masking + encoder + MLM loss + optax update in one
    compiled program (fresh masks each step, per the RoBERTa finding)."""

    def step(params, opt_state, tokens, key):
        mask_key, drop_key = jax.random.split(key)
        masked, positions, weights = mask_tokens(tokens, mask_key, config,
                                                 mask_rate)
        labels = jax.vmap(jnp.take)(tokens, positions)
        drop_key = drop_key if config.dropout_rate > 0 else None

        def loss_fn(p):
            return mlm_loss(p, masked, positions, labels, weights, config,
                            dropout_key=drop_key)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


# ------------------------------------------------------------- fine-tuning
def init_classifier_head(config: BertConfig, num_classes: int, key) -> Dict:
    """Classification head over the [CLS] pooler (the BERT fine-tuning
    recipe): one dense layer to ``num_classes`` logits."""
    return {"kernel": (jax.random.normal(
                key, (config.d_model, num_classes), config.param_dtype)
                / math.sqrt(config.d_model)),
            "bias": jnp.zeros((num_classes,), config.param_dtype)}


def classify(params: Dict, head: Dict, tokens: jnp.ndarray,
             config: BertConfig,
             segment_ids: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Sequence-classification logits ``(B, num_classes)``."""
    hidden = encode(params, tokens, segment_ids, config)
    pooled = pool(params, hidden, config)
    return (pooled @ head["kernel"].astype(jnp.float32)
            + head["bias"].astype(jnp.float32))


def make_classifier_train_step(config: BertConfig, tx,
                               freeze_encoder: bool = False):
    """Jitted fine-tuning step ``(state, opt_state, tokens, labels) ->
    (state, opt_state, loss)`` where ``state = {"params", "head"}``.
    ``freeze_encoder=True`` trains the head only (linear probing) —
    gradients never flow into the encoder and its optimizer state is a
    single frozen subtree."""

    def loss_fn(trainable, frozen, tokens, labels):
        params = frozen if freeze_encoder else trainable["params"]
        logits = classify(params, trainable["head"], tokens, config)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.mean(jnp.take_along_axis(
            logp, labels[:, None].astype(jnp.int32), axis=1))

    def step(state, opt_state, tokens, labels):
        if freeze_encoder:
            trainable = {"head": state["head"]}
            frozen = state["params"]
        else:
            trainable = state
            frozen = None
        loss, grads = jax.value_and_grad(loss_fn)(trainable, frozen,
                                                  tokens, labels)
        updates, opt_state = tx.update(grads, opt_state, trainable)
        trainable = jax.tree_util.tree_map(lambda p, u: p + u, trainable,
                                           updates)
        if freeze_encoder:
            state = {"params": state["params"], "head": trainable["head"]}
        else:
            state = trainable
        return state, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))
