"""Keras-style training callbacks.

The reference inherits callbacks implicitly from Keras (``model.fit``
kwargs ride through the Spark workers, ``elephas/worker.py:42``); this
module provides the native equivalents, including a ModelCheckpoint backed
by the step-checkpoint manager (mid-training checkpoint/resume is an
upgrade over the reference, which only has whole-model save/load —
SURVEY.md §5).
"""
import math
import warnings
from typing import Callable, Dict, List, Optional

import numpy as np

__all__ = ["Callback", "CallbackList", "EarlyStopping", "LambdaCallback",
           "ModelCheckpoint"]


class Callback:
    """Base class; hook methods are no-ops."""

    def __init__(self):
        self.model = None

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs: Optional[Dict] = None):
        pass

    def on_train_end(self, logs: Optional[Dict] = None):
        pass

    def on_epoch_begin(self, epoch: int, logs: Optional[Dict] = None):
        pass

    def on_epoch_end(self, epoch: int, logs: Optional[Dict] = None):
        pass

    def on_batch_end(self, batch: int, logs: Optional[Dict] = None):
        pass


class CallbackList:
    """Dispatches hooks to a list of callbacks."""

    def __init__(self, callbacks: Optional[List[Callback]], model):
        self.callbacks = list(callbacks or [])
        for cb in self.callbacks:
            cb.set_model(model)

    def __bool__(self):
        return bool(self.callbacks)

    def train_begin(self, logs=None):
        for cb in self.callbacks:
            cb.on_train_begin(logs)

    def train_end(self, logs=None):
        for cb in self.callbacks:
            cb.on_train_end(logs)

    def epoch_begin(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_begin(epoch, logs)

    def epoch_end(self, epoch, logs=None):
        for cb in self.callbacks:
            cb.on_epoch_end(epoch, logs)

    def batch_end(self, batch, logs=None):
        for cb in self.callbacks:
            cb.on_batch_end(batch, logs)


class EarlyStopping(Callback):
    """Stop training when a monitored metric stops improving.

    :param monitor: key in the epoch logs (e.g. ``val_loss``, ``loss``).
    :param patience: epochs without improvement before stopping.
    :param min_delta: minimum change to count as improvement.
    :param restore_best_weights: restore the best epoch's weights on stop.
    """

    def __init__(self, monitor: str = "val_loss", patience: int = 0,
                 min_delta: float = 0.0, mode: str = "min",
                 restore_best_weights: bool = False):
        super().__init__()
        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.mode = mode
        self.restore_best_weights = restore_best_weights
        self.best = math.inf if mode == "min" else -math.inf
        self.wait = 0
        self.stopped_epoch: Optional[int] = None
        self._best_weights = None
        self._warned_missing = False

    def on_train_begin(self, logs=None):
        # a callback instance may be reused across fit() calls — stale
        # best/wait/weights from a previous run must not leak in
        self.best = math.inf if self.mode == "min" else -math.inf
        self.wait = 0
        self.stopped_epoch = None
        self._best_weights = None
        self._warned_missing = False

    def _improved(self, value: float) -> bool:
        if self.mode == "min":
            return value < self.best - self.min_delta
        return value > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        value = (logs or {}).get(self.monitor)
        if value is None:
            # metric absent (e.g. monitor='val_loss' with no validation
            # split): early stopping is inert — say so once
            if not self._warned_missing:
                warnings.warn(
                    f"EarlyStopping conditioned on {self.monitor!r}, which "
                    f"is not in the epoch logs {sorted(logs or {})} — it "
                    "will never trigger")
                self._warned_missing = True
            return
        if self._improved(float(value)):
            self.best = float(value)
            self.wait = 0
            if self.restore_best_weights:
                self._best_weights = [np.copy(w)
                                      for w in self.model.get_weights()]
        else:
            self.wait += 1
            # Keras semantics: stop once `patience` epochs pass without
            # improvement
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True

    def on_train_end(self, logs=None):
        # restore the best epoch's weights whether or not the stop
        # triggered (epochs may simply have run out mid-plateau)
        if self.restore_best_weights and self._best_weights is not None:
            self.model.set_weights(self._best_weights)


class ModelCheckpoint(Callback):
    """Save the full training state (params + optimizer state) every epoch
    via :class:`~elephas_tpu.utils.checkpoint.CheckpointManager`.

    Resume with ``model.restore_training_state(directory)``.

    :param save_best_only: only write when ``monitor`` improves.
    :param block: ``False`` writes checkpoints on a background thread
        (state is snapshotted to host first), so epochs never stall on
        checkpoint IO; the final write is flushed at ``on_train_end``.
    :param checkpoint_on_preemption: trap SIGTERM (the Cloud TPU
        eviction notice) for the duration of training and write one
        final checkpoint of the live state before exiting (manifest gets
        ``preempted: true``). Requires fit() to run in the main thread.
    """

    def __init__(self, directory: str, monitor: str = "loss",
                 save_best_only: bool = False, mode: str = "min",
                 max_to_keep: int = 3, block: bool = True,
                 checkpoint_on_preemption: bool = False):
        super().__init__()
        from ..utils.checkpoint import CheckpointManager

        if mode not in ("min", "max"):
            raise ValueError("mode must be 'min' or 'max'")
        self.manager = CheckpointManager(directory, max_to_keep=max_to_keep)
        self.monitor = monitor
        self.save_best_only = save_best_only
        self.mode = mode
        self.best = math.inf if mode == "min" else -math.inf
        self.block = block
        self.checkpoint_on_preemption = checkpoint_on_preemption
        self._uninstall_preemption = None
        self._cur_epoch = 0
        self._epoch_offset = 0
        self._warned_missing = False

    def on_train_begin(self, logs=None):
        # instance may be reused across fit() calls: reset the best and
        # number epochs after any already-checkpointed step
        self.best = math.inf if self.mode == "min" else -math.inf
        self._warned_missing = False
        self._cur_epoch = 0   # stale value from a previous fit would
        latest = self.manager.latest_step()  # stamp a phantom step
        self._epoch_offset = (latest + 1) if latest is not None else 0
        if self.checkpoint_on_preemption:
            from ..utils.checkpoint import install_preemption_checkpoint

            self._uninstall_preemption = install_preemption_checkpoint(
                self.manager,
                lambda: (self._epoch_offset + self._cur_epoch,
                         self.model.training_state()),
                model_json=self.model.to_json())

    def on_epoch_begin(self, epoch, logs=None):
        self._cur_epoch = epoch

    def on_epoch_end(self, epoch, logs=None):
        if self.save_best_only:
            value = (logs or {}).get(self.monitor)
            if value is None:
                # can't judge "best" without the metric — skip the save
                # (saving anyway would quietly degrade to save-always)
                if not self._warned_missing:
                    warnings.warn(
                        f"ModelCheckpoint(save_best_only=True) conditioned "
                        f"on {self.monitor!r}, which is not in the epoch "
                        f"logs {sorted(logs or {})} — no checkpoints will "
                        "be written")
                    self._warned_missing = True
                return
            improved = (float(value) < self.best if self.mode == "min"
                        else float(value) > self.best)
            if not improved:
                return
            self.best = float(value)
        self.manager.save(self._epoch_offset + epoch,
                          self.model.training_state(),
                          model_json=self.model.to_json(),
                          block=self.block)

    def on_train_end(self, logs=None):
        if self._uninstall_preemption is not None:
            self._uninstall_preemption()
            self._uninstall_preemption = None
        self.manager.wait_until_finished()


class LambdaCallback(Callback):
    """Ad-hoc callbacks from plain functions (Keras parity)."""

    def __init__(self, on_train_begin: Callable = None,
                 on_train_end: Callable = None,
                 on_epoch_begin: Callable = None,
                 on_epoch_end: Callable = None,
                 on_batch_end: Callable = None):
        super().__init__()
        self._hooks = {"train_begin": on_train_begin,
                       "train_end": on_train_end,
                       "epoch_begin": on_epoch_begin,
                       "epoch_end": on_epoch_end,
                       "batch_end": on_batch_end}

    def on_train_begin(self, logs=None):
        if self._hooks["train_begin"]:
            self._hooks["train_begin"](logs)

    def on_train_end(self, logs=None):
        if self._hooks["train_end"]:
            self._hooks["train_end"](logs)

    def on_epoch_begin(self, epoch, logs=None):
        if self._hooks["epoch_begin"]:
            self._hooks["epoch_begin"](epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        if self._hooks["epoch_end"]:
            self._hooks["epoch_end"](epoch, logs)

    def on_batch_end(self, batch, logs=None):
        if self._hooks["batch_end"]:
            self._hooks["batch_end"](batch, logs)
