"""ResNet model family built on the functional layer API.

CIFAR-style residual networks (He et al.) assembled from the framework's
own layers — Conv2D/BatchNorm/Add — exercising the functional graph,
merge layers and batch-stat threading end to end. NHWC layout, MXU-sized
channel counts.
"""
from typing import Optional, Sequence, Tuple

from .core import Model
from .layers import (Activation, Add, BatchNormalization, Conv2D, Dense,
                     GlobalAveragePooling2D, Input, MaxPooling2D)


def _conv_bn_relu(x, filters, kernel_size=3, strides=1, activation=True,
                  name=None):
    x = Conv2D(filters, kernel_size, strides=strides, padding="same",
               use_bias=False, name=None if name is None else name + "_conv")(x)
    x = BatchNormalization(name=None if name is None else name + "_bn")(x)
    if activation:
        x = Activation("relu",
                       name=None if name is None else name + "_relu")(x)
    return x


def _basic_block(x, filters, strides=1, name=None):
    shortcut = x
    y = _conv_bn_relu(x, filters, strides=strides,
                      name=None if name is None else name + "_a")
    y = _conv_bn_relu(y, filters, activation=False,
                      name=None if name is None else name + "_b")
    if strides != 1 or x.shape[-1] != filters:
        shortcut = Conv2D(filters, 1, strides=strides, padding="same",
                          use_bias=False,
                          name=None if name is None else name + "_proj")(x)
        shortcut = BatchNormalization(
            name=None if name is None else name + "_proj_bn")(shortcut)
    out = Add(name=None if name is None else name + "_add")([y, shortcut])
    return Activation("relu",
                      name=None if name is None else name + "_out")(out)


def build_resnet(input_shape: Tuple[int, int, int] = (32, 32, 3),
                 num_classes: int = 10, depth: int = 20,
                 width: int = 16, name: Optional[str] = None) -> Model:
    """CIFAR-style ResNet: ``depth`` must be 6n+2 (20, 32, 44, 56...)."""
    if (depth - 2) % 6 != 0:
        raise ValueError("depth must be 6n+2 (e.g. 20, 32, 44)")
    blocks_per_stage = (depth - 2) // 6

    inputs = Input(shape=input_shape)
    x = _conv_bn_relu(inputs, width)
    filters = width
    for stage in range(3):
        for block in range(blocks_per_stage):
            strides = 2 if stage > 0 and block == 0 else 1
            x = _basic_block(x, filters, strides=strides)
        filters *= 2
    x = GlobalAveragePooling2D()(x)
    outputs = Dense(num_classes, activation="softmax")(x)
    return Model(inputs=inputs, outputs=outputs, name=name or f"resnet{depth}")


def _bottleneck_block(x, filters, strides=1, name=None):
    """ImageNet-style bottleneck (He et al. §4): 1x1 reduce -> 3x3 ->
    1x1 expand (4x), projection shortcut on shape change. The 3x3 conv
    carries the stride (the 'ResNet v1.5' placement every modern
    implementation and benchmark uses — it keeps more spatial
    information than striding the 1x1 and is MXU-friendlier)."""
    expansion = 4
    shortcut = x
    n = (lambda s: None if name is None else f"{name}_{s}")
    y = _conv_bn_relu(x, filters, kernel_size=1, name=n("a"))
    y = _conv_bn_relu(y, filters, kernel_size=3, strides=strides, name=n("b"))
    y = _conv_bn_relu(y, filters * expansion, kernel_size=1,
                      activation=False, name=n("c"))
    if strides != 1 or x.shape[-1] != filters * expansion:
        shortcut = Conv2D(filters * expansion, 1, strides=strides,
                          padding="same", use_bias=False, name=n("proj"))(x)
        shortcut = BatchNormalization(name=n("proj_bn"))(shortcut)
    out = Add(name=n("add"))([y, shortcut])
    return Activation("relu", name=n("out"))(out)


def build_resnet_imagenet(input_shape: Tuple[int, int, int] = (224, 224, 3),
                          num_classes: int = 1000,
                          stage_blocks: Sequence[int] = (3, 4, 6, 3),
                          name: Optional[str] = None) -> Model:
    """ImageNet-family ResNet with bottleneck blocks: 7x7/2 stem, 3x3/2
    max pool, four stages at 64/128/256/512 base filters (x4 expansion).
    ``stage_blocks`` (3,4,6,3) -> ResNet-50, (3,4,23,3) -> ResNet-101,
    (3,8,36,3) -> ResNet-152."""
    inputs = Input(shape=input_shape)
    x = _conv_bn_relu(inputs, 64, kernel_size=7, strides=2, name="stem")
    x = MaxPooling2D(pool_size=3, strides=2, padding="same",
                     name="stem_pool")(x)
    filters = 64
    for stage, blocks in enumerate(stage_blocks):
        for block in range(blocks):
            strides = 2 if stage > 0 and block == 0 else 1
            x = _bottleneck_block(x, filters, strides=strides,
                                  name=f"s{stage}b{block}")
        filters *= 2
    x = GlobalAveragePooling2D()(x)
    outputs = Dense(num_classes, activation="softmax")(x)
    depth = 2 + 3 * sum(stage_blocks)
    return Model(inputs=inputs, outputs=outputs,
                 name=name or f"resnet{depth}")


def build_resnet50(input_shape: Tuple[int, int, int] = (224, 224, 3),
                   num_classes: int = 1000) -> Model:
    """ResNet-50 (the BASELINE.md benchmark workload)."""
    return build_resnet_imagenet(input_shape, num_classes,
                                 stage_blocks=(3, 4, 6, 3), name="resnet50")


def build_resnet8(input_shape=(32, 32, 3), num_classes=10) -> Model:
    """Tiny 8-layer variant for tests/smoke runs."""
    inputs = Input(shape=input_shape)
    x = _conv_bn_relu(inputs, 16)
    x = _basic_block(x, 16)
    x = _basic_block(x, 32, strides=2)
    x = _basic_block(x, 64, strides=2)
    x = GlobalAveragePooling2D()(x)
    outputs = Dense(num_classes, activation="softmax")(x)
    return Model(inputs=inputs, outputs=outputs, name="resnet8")
