"""Loss functions.

Every loss maps ``(y_true, y_pred)`` to a per-sample loss vector of shape
``(batch,)``; reductions (weighted means over real samples) happen in the
training/eval steps so that padded shards contribute nothing. All are pure
``jnp`` and differentiable under ``jax.grad``.

Covers the reference's loss surface (the names registered in
``elephas/utils/model_utils.py:35-45`` plus callables via custom objects).
"""
from typing import Callable, Dict, Optional, Union

import jax.numpy as jnp

EPS = 1e-7


def _reduce_sample(x):
    """Mean over all non-batch axes -> per-sample scalar."""
    if x.ndim <= 1:
        return x
    return jnp.mean(x.reshape(x.shape[0], -1), axis=-1)


def mean_squared_error(y_true, y_pred):
    return _reduce_sample(jnp.square(y_pred - y_true))


def mean_absolute_error(y_true, y_pred):
    return _reduce_sample(jnp.abs(y_pred - y_true))


def mean_absolute_percentage_error(y_true, y_pred):
    diff = jnp.abs((y_true - y_pred) / jnp.maximum(jnp.abs(y_true), EPS))
    return 100.0 * _reduce_sample(diff)


def mean_squared_logarithmic_error(y_true, y_pred):
    first = jnp.log(jnp.maximum(y_pred, EPS) + 1.0)
    second = jnp.log(jnp.maximum(y_true, EPS) + 1.0)
    return _reduce_sample(jnp.square(first - second))


def log_cosh(y_true, y_pred):
    x = y_pred - y_true
    return _reduce_sample(x + jnp.log1p(jnp.exp(-2.0 * x)) - jnp.log(2.0))


def cosine_similarity(y_true, y_pred):
    def _norm(v):
        flat = v.reshape(v.shape[0], -1)
        return flat / jnp.maximum(jnp.linalg.norm(flat, axis=-1, keepdims=True), EPS)

    return -jnp.sum(_norm(y_true) * _norm(y_pred), axis=-1)


def huber(y_true, y_pred, delta: float = 1.0):
    err = y_pred - y_true
    abs_err = jnp.abs(err)
    quadratic = jnp.minimum(abs_err, delta)
    linear = abs_err - quadratic
    return _reduce_sample(0.5 * jnp.square(quadratic) + delta * linear)


def binary_crossentropy(y_true, y_pred):
    p = jnp.clip(y_pred, EPS, 1.0 - EPS)
    bce = -(y_true * jnp.log(p) + (1.0 - y_true) * jnp.log(1.0 - p))
    return _reduce_sample(bce)


def categorical_crossentropy(y_true, y_pred):
    p = jnp.clip(y_pred, EPS, 1.0)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    ce = -jnp.sum(y_true * jnp.log(p), axis=-1)
    return _reduce_sample(ce) if ce.ndim > 1 else ce


def sparse_categorical_crossentropy(y_true, y_pred):
    p = jnp.clip(y_pred, EPS, 1.0)
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    labels = y_true.astype(jnp.int32)
    if labels.ndim == p.ndim:  # trailing singleton label dim
        labels = labels[..., 0]
    picked = jnp.take_along_axis(p, labels[..., None], axis=-1)[..., 0]
    ce = -jnp.log(picked)
    return _reduce_sample(ce) if ce.ndim > 1 else ce


_LOSSES: Dict[str, Callable] = {
    "mean_squared_error": mean_squared_error,
    "mse": mean_squared_error,
    "mean_absolute_error": mean_absolute_error,
    "mae": mean_absolute_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "mape": mean_absolute_percentage_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "msle": mean_squared_logarithmic_error,
    "logcosh": log_cosh,
    "log_cosh": log_cosh,
    "cosine_proximity": cosine_similarity,
    "cosine_similarity": cosine_similarity,
    "huber": huber,
    "binary_crossentropy": binary_crossentropy,
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
}


def get(identifier: Union[str, Callable],
        custom_objects: Optional[Dict[str, Callable]] = None) -> Callable:
    """Resolve a loss from a name or callable."""
    if callable(identifier):
        return identifier
    if custom_objects and identifier in custom_objects:
        return custom_objects[identifier]
    if identifier in _LOSSES:
        return _LOSSES[identifier]
    raise ValueError(f"Unknown loss: {identifier!r}")


def serialize(identifier: Union[str, Callable]) -> str:
    if isinstance(identifier, str):
        return identifier
    for name, fn in _LOSSES.items():
        if fn is identifier:
            return name
    return getattr(identifier, "__name__", str(identifier))
