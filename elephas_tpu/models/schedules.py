"""Learning-rate schedules: serializable wrappers over optax schedules.

Keras-parity surface (the reference inherits `LearningRateSchedule`
support from Keras optimizers implicitly): schedule objects pass as the
``learning_rate`` of any :mod:`~elephas_tpu.models.optimizers` optimizer,
lower to optax schedule callables inside the jitted train step (the step
count drives them on-device — no host involvement per step), and
round-trip through the same ``{'class_name', 'config'}`` serialization as
optimizers, so scheduled configs travel inside model JSON, h5 files and
checkpoint manifests.
"""
from typing import Dict, List, Union

import optax

__all__ = ["LearningRateSchedule", "ExponentialDecay", "CosineDecay",
           "PiecewiseConstantDecay", "WarmupCosine", "serialize",
           "deserialize", "get"]


class LearningRateSchedule:
    """Base class: named hyperparameter bundle lowering to an optax
    schedule ``step -> learning_rate``."""

    def to_optax(self):
        raise NotImplementedError

    def __call__(self, step: int) -> float:
        return float(self.to_optax()(step))

    def get_config(self) -> Dict:
        raise NotImplementedError

    @classmethod
    def from_config(cls, config: Dict) -> "LearningRateSchedule":
        return cls(**config)


class ExponentialDecay(LearningRateSchedule):
    """``lr = initial * decay_rate ** (step / decay_steps)`` (Keras
    semantics; ``staircase`` floors the exponent)."""

    def __init__(self, initial_learning_rate: float, decay_steps: int,
                 decay_rate: float, staircase: bool = False):
        self.initial_learning_rate = float(initial_learning_rate)
        self.decay_steps = int(decay_steps)
        self.decay_rate = float(decay_rate)
        self.staircase = bool(staircase)

    def to_optax(self):
        return optax.exponential_decay(
            init_value=self.initial_learning_rate,
            transition_steps=self.decay_steps,
            decay_rate=self.decay_rate, staircase=self.staircase)

    def get_config(self):
        return {"initial_learning_rate": self.initial_learning_rate,
                "decay_steps": self.decay_steps,
                "decay_rate": self.decay_rate,
                "staircase": self.staircase}


class CosineDecay(LearningRateSchedule):
    """Cosine anneal from the initial rate to ``alpha * initial`` over
    ``decay_steps``."""

    def __init__(self, initial_learning_rate: float, decay_steps: int,
                 alpha: float = 0.0):
        self.initial_learning_rate = float(initial_learning_rate)
        self.decay_steps = int(decay_steps)
        self.alpha = float(alpha)

    def to_optax(self):
        return optax.cosine_decay_schedule(
            init_value=self.initial_learning_rate,
            decay_steps=self.decay_steps, alpha=self.alpha)

    def get_config(self):
        return {"initial_learning_rate": self.initial_learning_rate,
                "decay_steps": self.decay_steps, "alpha": self.alpha}


class PiecewiseConstantDecay(LearningRateSchedule):
    """``values[i]`` between ``boundaries[i-1]`` and ``boundaries[i]``
    (len(values) == len(boundaries) + 1, Keras semantics)."""

    def __init__(self, boundaries: List[int], values: List[float]):
        if len(values) != len(boundaries) + 1:
            raise ValueError("need len(values) == len(boundaries) + 1")
        self.boundaries = [int(b) for b in boundaries]
        self.values = [float(v) for v in values]

    def to_optax(self):
        # hand-rolled rather than optax.piecewise_constant_schedule: the
        # optax version is multiplicative (breaks on zero values, a legal
        # input) and switches one step early relative to Keras's
        # "values[i] while step <= boundaries[i]" contract
        import jax.numpy as jnp

        boundaries = jnp.asarray(self.boundaries)
        values = jnp.asarray(self.values, jnp.float32)

        def schedule(count):
            return values[jnp.sum(count > boundaries)]

        return schedule

    def get_config(self):
        return {"boundaries": self.boundaries, "values": self.values}


class WarmupCosine(LearningRateSchedule):
    """Linear warmup to ``peak_learning_rate`` over ``warmup_steps``, then
    cosine decay to ``end_learning_rate`` by ``decay_steps`` — the
    standard LM training schedule."""

    def __init__(self, peak_learning_rate: float, warmup_steps: int,
                 decay_steps: int, end_learning_rate: float = 0.0):
        self.peak_learning_rate = float(peak_learning_rate)
        self.warmup_steps = int(warmup_steps)
        self.decay_steps = int(decay_steps)
        self.end_learning_rate = float(end_learning_rate)

    def to_optax(self):
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=self.peak_learning_rate,
            warmup_steps=self.warmup_steps, decay_steps=self.decay_steps,
            end_value=self.end_learning_rate)

    def get_config(self):
        return {"peak_learning_rate": self.peak_learning_rate,
                "warmup_steps": self.warmup_steps,
                "decay_steps": self.decay_steps,
                "end_learning_rate": self.end_learning_rate}


_SCHEDULES = {cls.__name__: cls for cls in
              (ExponentialDecay, CosineDecay, PiecewiseConstantDecay,
               WarmupCosine)}


def serialize(schedule: LearningRateSchedule) -> Dict:
    return {"class_name": type(schedule).__name__,
            "config": schedule.get_config()}


def deserialize(config: Dict) -> LearningRateSchedule:
    cls = _SCHEDULES.get(config.get("class_name"))
    if cls is None:
        raise ValueError(f"Unknown schedule: {config.get('class_name')!r}")
    return cls.from_config(config.get("config", {}))


def get(identifier: Union[Dict, LearningRateSchedule]
        ) -> LearningRateSchedule:
    if isinstance(identifier, LearningRateSchedule):
        return identifier
    if isinstance(identifier, dict):
        return deserialize(identifier)
    raise ValueError(f"Cannot interpret schedule: {identifier!r}")
