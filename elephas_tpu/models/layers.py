"""Layer zoo: serializable, functionally-pure building blocks.

Each layer is a *config object*; parameters live outside the layer in a
pytree (``{layer_name: {param_name: array}}``), so the whole model is a pure
function ``apply(params, x)`` that jits, vmaps, and shards without hidden
state. Layers know how to

- ``build(key, input_shape) -> params`` (shapes exclude the batch dim),
- ``compute_output_shape(input_shape)``,
- ``call(params, inputs, training, rng)``,
- round-trip through ``get_config``/``from_config`` for model JSON.

Calling a layer on a :class:`KTensor` records a node in a functional graph
(Keras functional-API analog, see :mod:`.core`).

Capability parity target: the layer surface used by the reference's models
and examples (Dense/Activation/Dropout chains, ``/root/reference/tests/conftest.py``,
``examples/*.py``), extended with conv/pool/norm/embedding/attention blocks
for the model families the TPU framework ships.
"""
import collections
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from . import activations as activations_mod
from . import initializers

_LAYER_UIDS: Dict[str, int] = collections.defaultdict(int)


def _unique_name(prefix: str) -> str:
    _LAYER_UIDS[prefix] += 1
    count = _LAYER_UIDS[prefix]
    return prefix if count == 1 else f"{prefix}_{count - 1}"


def reset_layer_uids():
    """Reset auto-naming counters (used by tests for determinism)."""
    _LAYER_UIDS.clear()


class KTensor:
    """Symbolic tensor flowing through the functional-API graph.

    ``shape`` excludes the batch dimension. ``history`` is the producing
    ``(layer, inbound KTensors)`` pair, or None for placeholders.
    """

    def __init__(self, shape: Tuple, history=None):
        self.shape = tuple(shape)
        self.history = history

    def __repr__(self):
        return f"KTensor(shape={self.shape})"


def Input(shape: Sequence[int], name: Optional[str] = None) -> KTensor:
    """Create a symbolic model input (batch dimension implicit)."""
    layer = InputLayer(shape=tuple(shape), name=name)
    return layer._output


class Layer:
    """Base layer. Subclasses override build/compute_output_shape/call."""

    #: ordering of weight arrays for get_weights()/set_weights()
    weight_order: Tuple[str, ...] = ()

    def __init__(self, name: Optional[str] = None, **kwargs):
        prefix = kwargs.pop("name_prefix", None) or type(self).__name__.lower()
        self.name = name or _unique_name(prefix)
        self.input_spec: Optional[Tuple] = kwargs.pop("input_shape", None)
        input_dim = kwargs.pop("input_dim", None)
        if input_dim is not None:
            self.input_spec = (input_dim,)
        self.built_input_shape: Optional[Tuple] = None
        self._custom_objects: Dict[str, Any] = {}

    # -- graph recording -----------------------------------------------------
    def __call__(self, inputs: Union[KTensor, List[KTensor]]):
        in_list = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        if not all(isinstance(t, KTensor) for t in in_list):
            raise TypeError(
                "Layers are called on symbolic KTensors (from Input(...)); to "
                "run data through a model use model.predict / model.apply.")
        shapes = [t.shape for t in in_list]
        out_shape = self.compute_output_shape(shapes if len(shapes) > 1 else shapes[0])
        return KTensor(out_shape, history=(self, list(in_list)))

    # -- to be overridden ----------------------------------------------------
    def build(self, key, input_shape) -> Dict[str, jnp.ndarray]:
        self.built_input_shape = tuple(input_shape) if not isinstance(
            input_shape, list) else [tuple(s) for s in input_shape]
        return {}

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)

    def call(self, params: Dict[str, jnp.ndarray], inputs, training: bool, rng):
        raise NotImplementedError

    # -- serialization -------------------------------------------------------
    def get_config(self) -> Dict:
        config: Dict[str, Any] = {"name": self.name}
        if self.input_spec is not None:
            config["input_shape"] = list(self.input_spec)
        return config

    @classmethod
    def from_config(cls, config: Dict, custom_objects: Optional[Dict] = None):
        config = dict(config)
        if "input_shape" in config and config["input_shape"] is not None:
            config["input_shape"] = tuple(config["input_shape"])
        obj = cls(**config)
        obj._custom_objects = custom_objects or {}
        return obj

    def __repr__(self):
        return f"{type(self).__name__}(name={self.name!r})"


class InputLayer(Layer):
    def __init__(self, shape: Tuple, name: Optional[str] = None, **kwargs):
        super().__init__(name=name, name_prefix="input", **kwargs)
        self.shape = tuple(shape)
        self._output = KTensor(self.shape, history=(self, []))

    def compute_output_shape(self, input_shape):
        return self.shape

    def call(self, params, inputs, training, rng):
        return inputs

    def get_config(self):
        return {"name": self.name, "shape": list(self.shape)}

    @classmethod
    def from_config(cls, config, custom_objects=None):
        return cls(shape=tuple(config["shape"]), name=config.get("name"))


class Dense(Layer):
    """Fully-connected layer: ``y = act(x @ kernel + bias)``.

    The workhorse of the MXU — a (batch, in) x (in, out) matmul that XLA
    tiles onto the systolic array; the fused activation rides along as an
    epilogue instead of a separate HBM round-trip.
    """

    weight_order = ("kernel", "bias")

    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 kernel_initializer="glorot_uniform", bias_initializer="zeros",
                 name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.units = int(units)
        self.activation = activation
        self.use_bias = bool(use_bias)
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer

    def _activation_fn(self):
        return activations_mod.get(self.activation, self._custom_objects)

    def build(self, key, input_shape):
        super().build(key, input_shape)
        in_dim = int(input_shape[-1]) if len(input_shape) else 1
        k_kernel, k_bias = jax.random.split(key)
        params = {"kernel": initializers.get(self.kernel_initializer)(
            k_kernel, (in_dim, self.units))}
        if self.use_bias:
            params["bias"] = initializers.get(self.bias_initializer)(
                k_bias, (self.units,))
        return params

    def compute_output_shape(self, input_shape):
        if not len(input_shape):
            return (self.units,)
        return tuple(input_shape[:-1]) + (self.units,)

    def call(self, params, inputs, training, rng):
        if inputs.ndim == 1:  # scalar feature per sample
            inputs = inputs[:, None]
        y = inputs @ params["kernel"]
        if self.use_bias:
            y = y + params["bias"]
        return self._activation_fn()(y)

    def get_config(self):
        config = super().get_config()
        config.update({
            "units": self.units,
            "activation": activations_mod.serialize(self.activation),
            "use_bias": self.use_bias,
        })
        return config


class Activation(Layer):
    def __init__(self, activation, name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.activation = activation

    def call(self, params, inputs, training, rng):
        return activations_mod.get(self.activation, self._custom_objects)(inputs)

    def get_config(self):
        config = super().get_config()
        config["activation"] = activations_mod.serialize(self.activation)
        return config


class Dropout(Layer):
    def __init__(self, rate: float, name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.rate = float(rate)

    def call(self, params, inputs, training, rng):
        if not training or self.rate <= 0.0:
            return inputs
        keep = 1.0 - self.rate
        mask = jax.random.bernoulli(rng, keep, inputs.shape)
        return jnp.where(mask, inputs / keep, 0.0)

    def get_config(self):
        config = super().get_config()
        config["rate"] = self.rate
        return config


class Flatten(Layer):
    def compute_output_shape(self, input_shape):
        size = 1
        for d in input_shape:
            size *= int(d)
        return (size,)

    def call(self, params, inputs, training, rng):
        return inputs.reshape(inputs.shape[0], -1)


class Reshape(Layer):
    def __init__(self, target_shape: Sequence[int], name: Optional[str] = None,
                 **kwargs):
        super().__init__(name=name, **kwargs)
        self.target_shape = tuple(target_shape)

    def compute_output_shape(self, input_shape):
        return self.target_shape

    def call(self, params, inputs, training, rng):
        return inputs.reshape((inputs.shape[0],) + self.target_shape)

    def get_config(self):
        config = super().get_config()
        config["target_shape"] = list(self.target_shape)
        return config


def _pair(value) -> Tuple[int, int]:
    if isinstance(value, (tuple, list)):
        return int(value[0]), int(value[1])
    return int(value), int(value)


class Conv2D(Layer):
    """2-D convolution, NHWC layout (TPU-native ordering)."""

    weight_order = ("kernel", "bias")

    def __init__(self, filters: int, kernel_size, strides=1, padding: str = "valid",
                 activation=None, use_bias: bool = True,
                 kernel_initializer="glorot_uniform", bias_initializer="zeros",
                 name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.filters = int(filters)
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding.lower()
        self.activation = activation
        self.use_bias = bool(use_bias)
        self.kernel_initializer = kernel_initializer
        self.bias_initializer = bias_initializer

    def build(self, key, input_shape):
        super().build(key, input_shape)
        in_ch = int(input_shape[-1])
        k_kernel, k_bias = jax.random.split(key)
        kernel_shape = self.kernel_size + (in_ch, self.filters)
        params = {"kernel": initializers.get(self.kernel_initializer)(
            k_kernel, kernel_shape)}
        if self.use_bias:
            params["bias"] = initializers.get(self.bias_initializer)(
                k_bias, (self.filters,))
        return params

    def _out_spatial(self, size, k, s):
        if self.padding == "same":
            return -(-size // s)
        return (size - k) // s + 1

    def compute_output_shape(self, input_shape):
        h, w, _ = input_shape
        return (self._out_spatial(h, self.kernel_size[0], self.strides[0]),
                self._out_spatial(w, self.kernel_size[1], self.strides[1]),
                self.filters)

    def call(self, params, inputs, training, rng):
        y = lax.conv_general_dilated(
            inputs, params["kernel"], window_strides=self.strides,
            padding=self.padding.upper(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if self.use_bias:
            y = y + params["bias"]
        return activations_mod.get(self.activation, self._custom_objects)(y)

    def get_config(self):
        config = super().get_config()
        config.update({
            "filters": self.filters,
            "kernel_size": list(self.kernel_size),
            "strides": list(self.strides),
            "padding": self.padding,
            "activation": activations_mod.serialize(self.activation),
            "use_bias": self.use_bias,
        })
        return config


class _Pool2D(Layer):
    def __init__(self, pool_size=2, strides=None, padding: str = "valid",
                 name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None else self.pool_size
        self.padding = padding.lower()

    def _out_spatial(self, size, k, s):
        if self.padding == "same":
            return -(-size // s)
        return (size - k) // s + 1

    def compute_output_shape(self, input_shape):
        h, w, c = input_shape
        return (self._out_spatial(h, self.pool_size[0], self.strides[0]),
                self._out_spatial(w, self.pool_size[1], self.strides[1]), c)

    def get_config(self):
        config = super().get_config()
        config.update({"pool_size": list(self.pool_size),
                       "strides": list(self.strides), "padding": self.padding})
        return config


class MaxPooling2D(_Pool2D):
    def call(self, params, inputs, training, rng):
        return lax.reduce_window(
            inputs, -jnp.inf, lax.max,
            (1,) + self.pool_size + (1,), (1,) + self.strides + (1,),
            self.padding.upper())


class AveragePooling2D(_Pool2D):
    def call(self, params, inputs, training, rng):
        window = (1,) + self.pool_size + (1,)
        strides = (1,) + self.strides + (1,)
        summed = lax.reduce_window(inputs, 0.0, lax.add, window, strides,
                                   self.padding.upper())
        counts = lax.reduce_window(jnp.ones_like(inputs), 0.0, lax.add, window,
                                   strides, self.padding.upper())
        return summed / counts


class GlobalAveragePooling2D(Layer):
    def compute_output_shape(self, input_shape):
        return (input_shape[-1],)

    def call(self, params, inputs, training, rng):
        return jnp.mean(inputs, axis=(1, 2))


class Embedding(Layer):
    weight_order = ("embeddings",)

    def __init__(self, input_dim: int, output_dim: int,
                 embeddings_initializer="random_uniform",
                 name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.embeddings_initializer = embeddings_initializer

    def build(self, key, input_shape):
        super().build(key, input_shape)
        return {"embeddings": initializers.get(self.embeddings_initializer)(
            key, (self.input_dim, self.output_dim))}

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)

    def call(self, params, inputs, training, rng):
        return jnp.take(params["embeddings"], inputs.astype(jnp.int32), axis=0)

    def get_config(self):
        config = super().get_config()
        config.update({"input_dim": self.input_dim, "output_dim": self.output_dim})
        return config


class LSTM(Layer):
    """Long short-term memory over ``(batch, time, features)`` inputs.

    TPU-shaped recurrence: the input projection for ALL timesteps and all
    four gates is ONE ``(B*T, D) @ (D, 4U)`` matmul (MXU-sized, outside
    the loop); only the ``(B, U) @ (U, 4U)`` recurrent half runs inside
    the ``lax.scan``, whose carry is the ``(h, c)`` pair. Forget-gate bias
    initializes to 1 (Jozefowicz et al. 2015).

    Capability addition over the reference era's Keras LSTM
    (sequence models trained data-parallel through the same
    SparkModel/TPUModel surface).
    """

    weight_order = ("kernel", "recurrent_kernel", "bias")

    def __init__(self, units: int, activation="tanh",
                 recurrent_activation="sigmoid",
                 return_sequences: bool = False, use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 recurrent_initializer="orthogonal",
                 name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.units = int(units)
        self.activation = activation
        self.recurrent_activation = recurrent_activation
        self.return_sequences = bool(return_sequences)
        self.use_bias = bool(use_bias)
        self.kernel_initializer = kernel_initializer
        self.recurrent_initializer = recurrent_initializer

    def build(self, key, input_shape):
        super().build(key, input_shape)
        in_dim = int(input_shape[-1])
        k_in, k_rec = jax.random.split(key)
        params = {
            "kernel": initializers.get(self.kernel_initializer)(
                k_in, (in_dim, 4 * self.units)),
            "recurrent_kernel": initializers.get(self.recurrent_initializer)(
                k_rec, (self.units, 4 * self.units)),
        }
        if self.use_bias:
            bias = jnp.zeros((4 * self.units,))
            # unit forget-gate bias (gate order: i, f, g, o)
            bias = bias.at[self.units:2 * self.units].set(1.0)
            params["bias"] = bias
        return params

    def compute_output_shape(self, input_shape):
        t = input_shape[0]
        return ((t, self.units) if self.return_sequences
                else (self.units,))

    def call(self, params, inputs, training, rng):
        act = activations_mod.get(self.activation, self._custom_objects)
        rec_act = activations_mod.get(self.recurrent_activation,
                                      self._custom_objects)
        u = self.units
        xz = jnp.einsum("btd,dz->btz", inputs, params["kernel"])
        if self.use_bias:
            xz = xz + params["bias"]
        batch = inputs.shape[0]
        h0 = jnp.zeros((batch, u), inputs.dtype)
        c0 = jnp.zeros((batch, u), inputs.dtype)
        w_rec = params["recurrent_kernel"]

        def step(carry, xz_t):
            h, c = carry
            z = xz_t + h @ w_rec
            i = rec_act(z[:, :u])
            f = rec_act(z[:, u:2 * u])
            g = act(z[:, 2 * u:3 * u])
            o = rec_act(z[:, 3 * u:])
            c = f * c + i * g
            h = o * act(c)
            return (h, c), h

        (h, _), hs = lax.scan(step, (h0, c0), xz.swapaxes(0, 1))
        return hs.swapaxes(0, 1) if self.return_sequences else h

    def get_config(self):
        config = super().get_config()
        config.update({
            "units": self.units,
            "activation": activations_mod.serialize(self.activation),
            "recurrent_activation": activations_mod.serialize(
                self.recurrent_activation),
            "return_sequences": self.return_sequences,
            "use_bias": self.use_bias,
        })
        return config


class GRU(Layer):
    """Gated recurrent unit over ``(batch, time, features)`` inputs; same
    hoisted-input-matmul structure as :class:`LSTM` (gate order: z, r, n;
    v1 formulation — reset gate applied before the candidate matmul)."""

    weight_order = ("kernel", "recurrent_kernel", "bias")

    def __init__(self, units: int, activation="tanh",
                 recurrent_activation="sigmoid",
                 return_sequences: bool = False, use_bias: bool = True,
                 kernel_initializer="glorot_uniform",
                 recurrent_initializer="orthogonal",
                 name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.units = int(units)
        self.activation = activation
        self.recurrent_activation = recurrent_activation
        self.return_sequences = bool(return_sequences)
        self.use_bias = bool(use_bias)
        self.kernel_initializer = kernel_initializer
        self.recurrent_initializer = recurrent_initializer

    def build(self, key, input_shape):
        super().build(key, input_shape)
        in_dim = int(input_shape[-1])
        k_in, k_rec = jax.random.split(key)
        params = {
            "kernel": initializers.get(self.kernel_initializer)(
                k_in, (in_dim, 3 * self.units)),
            "recurrent_kernel": initializers.get(self.recurrent_initializer)(
                k_rec, (self.units, 3 * self.units)),
        }
        if self.use_bias:
            params["bias"] = jnp.zeros((3 * self.units,))
        return params

    def compute_output_shape(self, input_shape):
        t = input_shape[0]
        return ((t, self.units) if self.return_sequences
                else (self.units,))

    def call(self, params, inputs, training, rng):
        act = activations_mod.get(self.activation, self._custom_objects)
        rec_act = activations_mod.get(self.recurrent_activation,
                                      self._custom_objects)
        u = self.units
        xz = jnp.einsum("btd,dz->btz", inputs, params["kernel"])
        if self.use_bias:
            xz = xz + params["bias"]
        batch = inputs.shape[0]
        h0 = jnp.zeros((batch, u), inputs.dtype)
        w_rec = params["recurrent_kernel"]

        def step(h, xz_t):
            rz = xz_t[:, :2 * u] + h @ w_rec[:, :2 * u]
            z = rec_act(rz[:, :u])
            r = rec_act(rz[:, u:])
            n = act(xz_t[:, 2 * u:] + (r * h) @ w_rec[:, 2 * u:])
            h = (1.0 - z) * n + z * h
            return h, h

        h, hs = lax.scan(step, h0, xz.swapaxes(0, 1))
        return hs.swapaxes(0, 1) if self.return_sequences else h

    def get_config(self):
        config = super().get_config()
        config.update({
            "units": self.units,
            "activation": activations_mod.serialize(self.activation),
            "recurrent_activation": activations_mod.serialize(
                self.recurrent_activation),
            "return_sequences": self.return_sequences,
            "use_bias": self.use_bias,
        })
        return config


class LayerNormalization(Layer):
    weight_order = ("gamma", "beta")

    def __init__(self, epsilon: float = 1e-5, name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.epsilon = float(epsilon)

    def build(self, key, input_shape):
        super().build(key, input_shape)
        dim = int(input_shape[-1])
        return {"gamma": jnp.ones((dim,)), "beta": jnp.zeros((dim,))}

    def call(self, params, inputs, training, rng):
        mean = jnp.mean(inputs, axis=-1, keepdims=True)
        var = jnp.var(inputs, axis=-1, keepdims=True)
        normed = (inputs - mean) * lax.rsqrt(var + self.epsilon)
        return normed * params["gamma"] + params["beta"]

    def get_config(self):
        config = super().get_config()
        config["epsilon"] = self.epsilon
        return config


class BatchNormalization(Layer):
    """Batch normalization.

    Moving statistics are non-trainable weights updated outside the gradient
    path: the train step returns batch-stat updates alongside gradients (see
    ``training.py``), keeping the layer function pure so it shards/jits like
    everything else.
    """

    weight_order = ("gamma", "beta", "moving_mean", "moving_variance")
    non_trainable = ("moving_mean", "moving_variance")

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)

    def build(self, key, input_shape):
        super().build(key, input_shape)
        dim = int(input_shape[-1])
        return {"gamma": jnp.ones((dim,)), "beta": jnp.zeros((dim,)),
                "moving_mean": jnp.zeros((dim,)),
                "moving_variance": jnp.ones((dim,))}

    def call(self, params, inputs, training, rng):
        axes = tuple(range(inputs.ndim - 1))
        if training:
            mean = jnp.mean(inputs, axis=axes)
            var = jnp.var(inputs, axis=axes)
        else:
            mean, var = params["moving_mean"], params["moving_variance"]
        normed = (inputs - mean) * lax.rsqrt(var + self.epsilon)
        return normed * params["gamma"] + params["beta"]

    def batch_stats(self, params, inputs):
        """Fresh batch statistics for moving-average updates."""
        axes = tuple(range(inputs.ndim - 1))
        return jnp.mean(inputs, axis=axes), jnp.var(inputs, axis=axes)

    def get_config(self):
        config = super().get_config()
        config.update({"momentum": self.momentum, "epsilon": self.epsilon})
        return config


class _Merge(Layer):
    """Base for multi-input merge layers."""

    def compute_output_shape(self, input_shapes):
        return tuple(input_shapes[0])


class Add(_Merge):
    def call(self, params, inputs, training, rng):
        out = inputs[0]
        for t in inputs[1:]:
            out = out + t
        return out


class Multiply(_Merge):
    def call(self, params, inputs, training, rng):
        out = inputs[0]
        for t in inputs[1:]:
            out = out * t
        return out


class Concatenate(_Merge):
    def __init__(self, axis: int = -1, name: Optional[str] = None, **kwargs):
        super().__init__(name=name, **kwargs)
        self.axis = int(axis)

    def compute_output_shape(self, input_shapes):
        # axis counts the batch dim (Keras semantics): axis=0 is invalid,
        # axis>0 maps to index axis-1 of the batch-less symbolic shape.
        ref = list(input_shapes[0])
        if self.axis == 0:
            raise ValueError("Cannot concatenate along the batch axis (0)")
        idx = self.axis - 1 if self.axis > 0 else len(ref) + self.axis
        total = sum(int(s[idx]) for s in input_shapes)
        ref[idx] = total
        return tuple(ref)

    def call(self, params, inputs, training, rng):
        return jnp.concatenate(list(inputs), axis=self.axis)

    def get_config(self):
        config = super().get_config()
        config["axis"] = self.axis
        return config


_LAYERS = {
    "InputLayer": InputLayer,
    "Dense": Dense,
    "Activation": Activation,
    "Dropout": Dropout,
    "Flatten": Flatten,
    "Reshape": Reshape,
    "Conv2D": Conv2D,
    "MaxPooling2D": MaxPooling2D,
    "AveragePooling2D": AveragePooling2D,
    "GlobalAveragePooling2D": GlobalAveragePooling2D,
    "Embedding": Embedding,
    "LSTM": LSTM,
    "GRU": GRU,
    "LayerNormalization": LayerNormalization,
    "BatchNormalization": BatchNormalization,
    "Add": Add,
    "Multiply": Multiply,
    "Concatenate": Concatenate,
}


def register_layer(cls, name: Optional[str] = None):
    """Register a custom Layer subclass for deserialization."""
    _LAYERS[name or cls.__name__] = cls
    return cls


def deserialize_layer(spec: Dict, custom_objects: Optional[Dict] = None) -> Layer:
    class_name = spec["class_name"]
    cls = None
    if custom_objects and class_name in custom_objects:
        cls = custom_objects[class_name]
    elif class_name in _LAYERS:
        cls = _LAYERS[class_name]
    if cls is None:
        raise ValueError(f"Unknown layer class: {class_name!r}")
    return cls.from_config(spec.get("config", {}), custom_objects=custom_objects)


def serialize_layer(layer: Layer) -> Dict:
    return {"class_name": type(layer).__name__, "config": layer.get_config()}
