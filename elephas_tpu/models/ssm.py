"""Selective state-space (Mamba-style) LM family — the linear-time
complement to the attention transformer.

TPU-first design: training computes the whole input-dependent diagonal
recurrence ``h_t = a_t * h_{t-1} + b_t * u_t`` in ONE
``lax.associative_scan`` (log-depth, MXU/VPU-friendly, no sequential
loop), and decode carries a constant ``(batch, d_inner)`` hidden state
per layer — O(1) cache versus attention's O(seq) KV, which is the whole
serving story for very long contexts. The reference has no sequence
models at all (its models are user-supplied Keras MLPs/convs,
``elephas/spark_model.py``); this family is beyond-parity breadth, and
its API mirrors :mod:`.transformer` (init/loss/generate + cached
decode) so the trainers and serving utilities compose the same way.

Block structure (per layer, pre-norm residual):

    u, g = x @ W_in  (split)                 # expand D -> 2E
    a_t  = exp(-softplus(x @ W_dt + b_dt))   # input-SELECTIVE decay
    b_t  = x @ W_b                           # input-dependent drive
    h_t  = a_t * h_{t-1} + b_t * silu(u_t)   # diagonal recurrence
    y    = (h_t * silu(g_t)) @ W_out + d * u # gated readout + skip

First-order recurrences compose associatively:
``(a2, s2) ∘ (a1, s1) = (a1*a2, a2*s1 + s2)`` — exactly what
``lax.associative_scan`` parallelizes. The step-by-step decode applies
the same update once per token; scan ≡ sequential is pinned by tests.
"""
import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SSMConfig", "init_ssm_params", "ssm_forward", "ssm_lm_loss",
           "init_ssm_state", "ssm_prefill", "ssm_decode_step",
           "ssm_generate", "make_ssm_train_step"]


@dataclasses.dataclass(frozen=True)
class SSMConfig:
    """Hyperparameters for the selective-SSM LM. ``d_inner`` is the
    expanded state width (Mamba's ``expand * d_model``; defaults to
    ``2 * d_model``). ``max_seq_len`` is advisory only (an SSM has no
    positional table or cache bound — any sequence length runs); it
    exists so generic tooling written against the transformer config
    keeps working. Frozen dataclass like the other families' configs:
    value-hashable (jit static arg) and checkpoint-manifest
    round-trippable via :mod:`.saving`."""

    vocab_size: int
    num_layers: int = 4
    d_model: int = 256
    d_inner: Optional[int] = None
    max_seq_len: int = 2048
    dtype: Any = jnp.float32

    def __post_init__(self):
        if self.d_inner is None:
            object.__setattr__(self, "d_inner", 2 * self.d_model)


def init_ssm_params(config: SSMConfig, key) -> Dict:
    c = config
    keys = jax.random.split(key, 2 + 4 * c.num_layers)
    scale_in = 1.0 / math.sqrt(c.d_model)
    scale_out = 1.0 / math.sqrt(c.d_inner)
    params: Dict = {
        "embed": jax.random.normal(keys[0], (c.vocab_size, c.d_model),
                                   jnp.float32) * 0.02,
        "final_ln": {"scale": jnp.ones(c.d_model, jnp.float32)},
    }
    for i in range(c.num_layers):
        k1, k2, k3, k4 = keys[2 + 4 * i: 6 + 4 * i]
        params[f"layer_{i}"] = {
            "ln": {"scale": jnp.ones(c.d_model, jnp.float32)},
            "w_in": jax.random.normal(k1, (c.d_model, 2 * c.d_inner),
                                      jnp.float32) * scale_in,
            "w_dt": jax.random.normal(k2, (c.d_model, c.d_inner),
                                      jnp.float32) * scale_in,
            # softplus(b_dt) ~ decay rate; init spread over timescales
            # (Mamba's dt init): decays between ~0.9 and ~0.999
            "b_dt": jnp.asarray(np.log(np.expm1(np.geomspace(
                0.001, 0.1, c.d_inner))), jnp.float32),
            "w_b": jax.random.normal(k3, (c.d_model, c.d_inner),
                                     jnp.float32) * scale_in,
            "w_out": jax.random.normal(k4, (c.d_inner, c.d_model),
                                       jnp.float32) * scale_out,
            "d_skip": jnp.ones(c.d_inner, jnp.float32),
        }
    return params


def _rms(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def _layer_coeffs(layer: Dict, x: jnp.ndarray, c: SSMConfig):
    """Shared by the parallel scan and the single decode step: the
    input-dependent (a, drive, gate, u) of one layer at the given
    position(s)."""
    h = _rms(x, layer["ln"]["scale"]).astype(c.dtype)
    ug = h @ layer["w_in"].astype(c.dtype)
    u, g = jnp.split(ug, 2, axis=-1)
    u = jax.nn.silu(u)
    a = jnp.exp(-jax.nn.softplus(
        h @ layer["w_dt"].astype(c.dtype)
        + layer["b_dt"].astype(c.dtype)))
    drive = (h @ layer["w_b"].astype(c.dtype)) * u
    return a, drive, g, u


def _layer_readout(layer: Dict, h_states: jnp.ndarray, g: jnp.ndarray,
                   u: jnp.ndarray, c: SSMConfig) -> jnp.ndarray:
    y = (h_states * jax.nn.silu(g)
         + layer["d_skip"].astype(c.dtype) * u)
    return y @ layer["w_out"].astype(c.dtype)


def _combine(left, right):
    a1, s1 = left
    a2, s2 = right
    return a1 * a2, a2 * s1 + s2


def _scan_recurrence(a: jnp.ndarray, drive: jnp.ndarray,
                     init: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """All T hidden states of ``h_t = a_t h_{t-1} + drive_t`` in one
    log-depth associative scan over the time axis. ``init`` (``(B, E)``,
    default zeros) continues from an earlier chunk's final state:
    ``h_t = (prod a_{1..t}) init + zero-init scan`` — the cumulative
    decay product falls out of the same scan for free."""
    cum_a, states = jax.lax.associative_scan(_combine, (a, drive), axis=1)
    if init is not None:
        states = states + cum_a * init[:, None, :]
    return states


def ssm_forward(params: Dict, tokens: jnp.ndarray,
                config: SSMConfig) -> jnp.ndarray:
    """Full-sequence logits ``(B, T, V)`` — training/prefill path, the
    whole recurrence parallelized per layer."""
    c = config
    x = params["embed"][tokens].astype(c.dtype)
    for i in range(c.num_layers):
        layer = params[f"layer_{i}"]
        a, drive, g, u = _layer_coeffs(layer, x, c)
        states = _scan_recurrence(a, drive)
        x = x + _layer_readout(layer, states, g, u, c)
    x = _rms(x, params["final_ln"]["scale"])
    return x.astype(jnp.float32) @ params["embed"].T


def ssm_lm_loss(params: Dict, tokens: jnp.ndarray,
                config: SSMConfig) -> jnp.ndarray:
    logits = ssm_forward(params, tokens[:, :-1], config)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
    return jnp.mean(nll)


def make_ssm_train_step(config: SSMConfig, tx, mesh=None,
                        data_axis: str = "data"):
    """(params, opt_state, tokens) -> (params, opt_state, loss), batch
    dp-sharded when a mesh is given (same pattern as the transformer's
    :func:`~elephas_tpu.models.transformer.make_train_step`)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def step(params, opt_state, tokens):
        if mesh is not None:
            tokens = jax.lax.with_sharding_constraint(
                tokens, NamedSharding(mesh, P(data_axis, None)))
        loss, grads = jax.value_and_grad(ssm_lm_loss)(params, tokens,
                                                      config)
        updates, opt_state = tx.update(grads, opt_state, params)
        import optax

        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1))


# ------------------------------------------------------------- decoding
def ssm_prefill(params: Dict, tokens: jnp.ndarray, config: SSMConfig,
                state: Optional[Dict] = None) -> Tuple[jnp.ndarray, Dict]:
    """Parallel prefill: run ``(B, T)`` tokens through every layer's
    associative scan and return (last-position logits ``(B, V)``, final
    per-layer state). ``state`` continues from a previous chunk's
    output, so long prompts can prefill in fixed-size pieces with
    bounded compile shapes. THE prefill: ``ssm_generate`` and the
    serving engine both call it, so the block math lives in one place."""
    c = config
    x = params["embed"][tokens].astype(c.dtype)
    new_state: Dict = {}
    for i in range(c.num_layers):
        layer = params[f"layer_{i}"]
        a, drive, g, u = _layer_coeffs(layer, x, c)
        states = _scan_recurrence(
            a, drive, None if state is None else state[f"layer_{i}"])
        new_state[f"layer_{i}"] = states[:, -1]
        x = x + _layer_readout(layer, states, g, u, c)
    x = _rms(x, params["final_ln"]["scale"])
    return x[:, -1].astype(jnp.float32) @ params["embed"].T, new_state


def init_ssm_state(config: SSMConfig, batch: int) -> Dict:
    """O(1) decode state: one ``(batch, d_inner)`` hidden vector per
    layer — independent of sequence length (attention's KV cache is
    O(seq); this is the SSM serving advantage)."""
    return {f"layer_{i}": jnp.zeros((batch, config.d_inner),
                                    config.dtype)
            for i in range(config.num_layers)}


def ssm_decode_step(params: Dict, state: Dict, tokens: jnp.ndarray,
                    config: SSMConfig) -> Tuple[jnp.ndarray, Dict]:
    """One token per row: ``(B,)`` ids -> (logits ``(B, V)``, new
    state). Applies exactly the recurrence the parallel scan computes,
    once."""
    c = config
    x = params["embed"][tokens].astype(c.dtype)        # (B, D)
    new_state: Dict = {}
    for i in range(c.num_layers):
        layer = params[f"layer_{i}"]
        a, drive, g, u = _layer_coeffs(layer, x, c)
        h_new = a * state[f"layer_{i}"] + drive
        new_state[f"layer_{i}"] = h_new
        x = x + _layer_readout(layer, h_new, g, u, c)
    x = _rms(x, params["final_ln"]["scale"])
    return x.astype(jnp.float32) @ params["embed"].T, new_state


@partial(jax.jit, static_argnames=("max_new_tokens", "config",
                                   "temperature"))
def _ssm_generate_scan(params, prompt, key, max_new_tokens: int,
                       config: SSMConfig, temperature: float):
    c = config
    logits0, state = ssm_prefill(params, prompt, c)

    def pick(logits, k):
        if temperature > 0:
            k, sub = jax.random.split(k)
            return (jax.random.categorical(
                sub, logits / temperature).astype(jnp.int32), k)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), k

    tok0, key2 = pick(logits0, key)

    def body(carry, _):
        state, tok, k = carry
        logits, state = ssm_decode_step(params, state, tok, c)
        nxt, k = pick(logits, k)
        return (state, nxt, k), tok

    (_, last, _), toks = jax.lax.scan(
        body, (state, tok0, key2), None, length=max_new_tokens - 1)
    return jnp.concatenate([jnp.swapaxes(toks, 0, 1), last[:, None]],
                           axis=1)


def ssm_generate(params: Dict, prompt: jnp.ndarray, max_new_tokens: int,
                 config: SSMConfig, temperature: float = 0.0,
                 key=None) -> jnp.ndarray:
    """Greedy (or sampled) continuation of ``(B, T)`` prompts: prefill
    runs the parallel scan once to build the O(1) state, then one fused
    ``lax.scan`` emits tokens — no KV cache, state size is constant in
    sequence length. Compiled once per (shape, config,
    ``max_new_tokens``, sampled-or-greedy); repeated calls reuse the
    executable."""
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if temperature > 0 and key is None:
        raise ValueError("sampling (temperature > 0) requires a PRNG key")
    if key is None:
        key = jax.random.PRNGKey(0)
    return _ssm_generate_scan(params, jnp.asarray(prompt), key,
                              int(max_new_tokens), config,
                              float(temperature))
